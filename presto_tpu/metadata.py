"""Engine metadata layer: sessions, catalogs, and the metadata manager.

Analogue of presto-main's metadata/MetadataManager.java (fronting per-catalog
connector metadata), metadata/CatalogManager, and Session.java:56. Narrowed to what
the analyzer/planner need: qualified-name resolution to table handles, column
enumeration, and statistics for the cost-based join ordering.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .spi.connector import (ColumnHandle, Connector, Constraint, SchemaTableName,
                            TableHandle, TableMetadata, TableStatistics)


@dataclasses.dataclass
class Session:
    """Session.java:56 — per-query context (user, catalog/schema defaults,
    system + per-catalog session properties, SystemSessionProperties.java:54)."""

    user: str = "user"
    catalog: Optional[str] = None
    schema: Optional[str] = None
    properties: Dict[str, object] = dataclasses.field(default_factory=dict)

    # engine defaults (the SystemSessionProperties subset that matters here)
    DEFAULTS = {
        # None = platform default (default_page_capacity), resolved only
        # when execution actually needs the backend
        "page_capacity": None,
        "task_concurrency": 4,
        # intra-pipeline driver parallelism: AUTO = task_concurrency on
        # accelerators, 1 on the CPU backend (XLA-CPU already uses all cores);
        # an integer forces that many drivers per eligible pipeline
        "driver_parallelism": "AUTO",
        "join_distribution_type": "AUTOMATIC",   # BROADCAST | PARTITIONED | AUTOMATIC
        # AUTOMATIC broadcasts a build side whose estimated row count is below
        # this (join-distribution CBO; the reference bounds replicated size via
        # join_max_broadcast_table_size)
        "broadcast_join_threshold_rows": 1 << 15,
        "join_reordering_strategy": "AUTOMATIC",  # NONE | AUTOMATIC
        "max_groups": 1 << 20,
        # memory/spill (advisory accounting over XLA's allocator). Under
        # pressure, revocation walks the full ladder: device HBM -> host RAM
        # -> disk (exec/spill.py writes PCOL runs; the reference's
        # FileSingleStreamSpiller). OOM kill is the LAST rung, after the
        # ladder has been attempted.
        "memory_pool_bytes": 8 << 30,
        "query_max_memory_bytes": 4 << 30,
        "revoke_target_fraction": 0.9,
        # disk tier: on by default; spill_dir "" = <tempdir>/presto-tpu-spill;
        # spill_max_bytes 0 = unlimited on-disk bytes per query
        "spill_to_disk": True,
        "spill_dir": "",
        "spill_max_bytes": 0,
        # grouped (lifespan) execution over co-bucketed tables: run the plan
        # once per bucket so join/agg state is bounded by one bucket's data
        # (execution/Lifespan.java + StageExecutionDescriptor analogue)
        "grouped_execution": True,
        # scaled writers: INSERT/CTAS fan out over K parallel writer drivers
        # (one sink file each) when the source is at least K * this many rows
        "scaled_writers": True,
        "writer_min_rows_per_driver": 1 << 20,
        # pack filtered scans' surviving rows into full pages before the
        # stateful operators (ops/coalesce.py) — downstream kernel work and
        # per-page dispatches then scale with selectivity
        "coalesce_pages": True,
        # fuse maximal runs of page-local operators (filter/project -> join
        # probe -> partial hash-agg / TopN contribution) into ONE jitted
        # dispatch per page (ops/fused_segment.py). False = per-operator
        # dispatches — the differential-testing oracle
        "segment_fusion": True,
        # --- Pallas hash kernels (ops/pallas_hash.py) ---
        # join build/probe + aggregation grouping strategy:
        #   sorted — the sort + binary-search / segment-reduce paths (the
        #            differential oracle, today's default);
        #   pallas — open-addressing hash tables built and probed by the
        #            Pallas kernels wherever they are CORRECT (unique
        #            single-key INNER/LEFT builds; table-friendly group
        #            counts) — ineligible shapes fall back to sorted, never
        #            raise;
        #   auto   — pallas only where the runtime heuristics also expect it
        #            to be PROFITABLE (joins: compiled backends only — the
        #            interpreted kernels measurably lose; agg: small
        #            observed group counts on sync-cheap backends), sorted
        #            everywhere else.
        # Kernels interpret off-TPU, so all three values are row-identical
        # on every backend (tests/test_pallas_hash.py is the contract).
        # Note: aggregations whose partials run inside FUSED segments keep
        # the sort kernel (the segment compiles the sort partial config at
        # plan time); the agg half engages on unfused pipelines.
        "hash_kernels": "sorted",
        # --- streaming scan pipeline (ops/scan_pipeline.py) ---
        # staged host->HBM ingest: split-parallel readers -> ordered
        # re-batch into device-shaped pages -> async upload. False =
        # single-reader passthrough (pages keep their source shapes)
        "scan_pipeline": True,
        # reader pool size per scan driver; 0 = engine default
        # (scan_pipeline.DEFAULT_READER_THREADS: min(8, host cores))
        "scan_reader_threads": 0,
        # re-batched page rows; 0 = the session page_capacity (canonical
        # device shape: kernels see ONE large static shape per schema)
        "scan_target_page_rows": 0,
        # in-flight byte bound per scan, applied to BOTH the decoded host
        # staging and the uploaded-but-unconsumed device pages — bounding
        # bytes (not page count) lets prefetch depth adapt to page size;
        # 0 = engine default (scan_pipeline.DEFAULT_PREFETCH_BYTES, 256MB)
        "scan_prefetch_bytes": 0,
        # --- streaming mesh exchange (parallel/streaming_exchange.py) ---
        # stream fixed-capacity chunks through the inter-fragment collectives
        # while producer drivers still run (producer/consumer fragments share
        # one task executor). False = the stage-barrier exchange — each
        # fragment drains fully before one variable-shape collective — kept
        # as the differential oracle, exactly like segment_fusion
        "streaming_exchange": True,
        # per-worker chunk capacity in rows (pow2-rounded); 0 = engine
        # default (streaming_exchange.DEFAULT_CHUNK_ROWS, 4096). The chunk
        # shape is FIXED per query, so each exchange kind compiles ONE
        # collective program per query shape instead of one per pow2 volume
        "exchange_chunk_rows": 0,
        # in-flight byte bound per exchange: producer sinks park (BLOCKED)
        # while staged + undelivered bytes exceed it — no stage ever holds a
        # full intermediate result; 0 = engine default
        # (streaming_exchange.DEFAULT_INFLIGHT_BYTES, 256MB)
        "exchange_inflight_bytes": 0,
        # skew-aware repartitioning for partitioned INNER joins (streaming
        # mode): the build-side exchange samples its first chunk for heavy-
        # hitter keys, SPLITS hot build rows round-robin across partitions
        # and the probe-side exchange REPLICATES matching probe rows to all
        # partitions — a 99%-one-key join spreads across the mesh instead of
        # landing on one chip (carry-over already made it *correct*; this
        # makes it *parallel*). Per-partition delivered-row counts surface
        # in QueryResult.stats["exchange"]. False = hash-only routing.
        "skew_aware_exchange": True,
        # --- multi-tenant serving (exec/shared_pools.py) ---
        # run scan-pipeline stages and exchange pumps on the process-wide
        # shared worker pools with per-query round-robin fairness, so N
        # concurrent queries cost O(pool) threads instead of O(N * stages).
        # Pool sizes are fixed once per process (PRESTO_TPU_SCAN_POOL_THREADS
        # / PRESTO_TPU_EXCHANGE_POOL_THREADS env knobs). False = per-query
        # dedicated stage threads — the differential-testing oracle
        "shared_pools": True,
        # --- observability: per-query flight recorder (utils/trace.py) ---
        # record spans across every engine layer (lifecycle, driver quanta,
        # operators, fused segments, scan stages, exchange chunks, cluster
        # HTTP) and export Chrome trace-event JSON readable in Perfetto /
        # chrome://tracing; the path lands in QueryResult.trace_path and is
        # served at GET /v1/query/{id}/trace. Near-zero cost when False.
        "query_trace": False,
        # export directory for trace files; "" = the platform tempdir
        "query_trace_dir": "",
        # span ring-buffer capacity: oldest spans overwrite beyond this
        # (the export reports how many were dropped); 0 = engine default
        "query_trace_max_events": 0,
        # always-on black-box recorder: every query keeps a small COARSE
        # span ring (driver quanta, exchange chunks, scan stage stalls,
        # pool steps, kernel builds, cluster HTTP — per-page operator spans
        # dropped at the source) so a FAILED / OOM-killed / retry-exhausted
        # query dumps a forensic Chrome trace it never opted into
        # (QueryInfo.failure_trace_path, GET /v1/query/{id}/trace). False =
        # recorder compiled out — the bench's overhead comparison point
        "query_blackbox": True,
        # black-box ring capacity; 0 = engine default (trace.BLACKBOX_MAX_EVENTS)
        "query_blackbox_max_events": 0,
        # --- cluster fault tolerance (cluster/retry.py) ---
        # NONE fails fast; QUERY re-plans + re-runs the whole query on
        # retryable failures (failed nodes excluded from placement); TASK
        # additionally re-places failed task creates and recovers failed
        # leaf tasks in place
        "retry_policy": "NONE",
        "query_retry_attempts": 2,      # extra attempts after the first
        "task_retry_attempts": 2,       # in-place recoveries per task (TASK)
        "retry_initial_delay_s": 0.1,   # jittered-exponential backoff floor
        "retry_max_delay_s": 2.0,       # ... and ceiling
        # transient-failure budget for one remote-task create
        "remote_task_error_budget_s": 10.0,
        # transient-failure budget before an exchange source is declared dead
        "exchange_error_budget_s": 60.0,
        # deterministic fault-injection spec (cluster/faults.py); "" = off
        "fault_injection": "",
        "fault_seed": 0,
        # per-task bound on the acked-frame replay spool (cluster/buffers.py);
        # spooled bytes are reserved in the shared pool under the query id.
        # 0 disables spooling — mid-stream TASK recovery then escalates
        # loudly to a query-level retry (ReplayWindowLost / HTTP 410)
        "exchange_spool_bytes": 64 << 20,
        # rows a sink accumulates per partition before flushing one exchange
        # frame (= one replayable chunk); None = the 16k built-in. Small
        # values force many-chunk streams (chaos tests, latency-sensitive
        # pipelines), large values amortize serialization
        "exchange_flush_rows": None,
        # --- straggler speculation (cluster/scheduler.py) ---
        # launch a duplicate of a straggling task on another node; the first
        # copy to FINISH wins (its consumers rewire from their chunk
        # cursors), the loser is aborted and journaled `task.speculated`
        "speculative_execution": False,
        "speculation_min_wall_s": 5.0,   # never speculate younger tasks
        # straggler = running wall > multiplier x median FINISHED sibling wall
        "speculation_multiplier": 2.0,
    }

    def get(self, name: str, default=None):
        if name in self.properties:
            return self.properties[name]
        if name in self.DEFAULTS:
            return self.DEFAULTS[name]
        return default

    def with_properties(self, **kw) -> "Session":
        props = dict(self.properties)
        props.update(kw)
        return dataclasses.replace(self, properties=props)


def default_page_capacity() -> int:
    """Platform default page size, resolved at execution time. Pages are the
    unit of dispatch: on an accelerator every page costs kernel-launch
    round-trips (over a remote tunnel each is a network RTT), so pages are
    sized to make the page COUNT small — SF1 lineitem is 2 x 4M-row pages
    instead of 23 x 256k. XLA-CPU prefers cache-sized batches (256k)."""
    import jax

    return (1 << 22) if jax.default_backend() != "cpu" else (1 << 18)


@dataclasses.dataclass(frozen=True)
class QualifiedObjectName:
    catalog: str
    schema: str
    table: str

    def __str__(self):
        return f"{self.catalog}.{self.schema}.{self.table}"


class CatalogManager:
    """metadata/CatalogManager — registered connectors by catalog name."""

    def __init__(self):
        self._catalogs: Dict[str, Connector] = {}

    def register(self, name: str, connector: Connector) -> None:
        self._catalogs[name] = connector

    def get(self, name: str) -> Optional[Connector]:
        return self._catalogs.get(name)

    def names(self) -> List[str]:
        return list(self._catalogs)


class MetadataManager:
    """metadata/MetadataManager.java — engine-facing metadata fronting connectors."""

    def __init__(self, catalogs: CatalogManager):
        self.catalogs = catalogs

    def resolve_table_name(self, session: Session,
                           parts: Sequence[str]) -> QualifiedObjectName:
        """tree.Table name -> fully qualified, filling session defaults
        (metadata/MetadataUtil.createQualifiedObjectName analogue)."""
        parts = list(parts)
        if len(parts) == 1:
            if not session.catalog or not session.schema:
                raise ValueError(f"table '{parts[0]}' requires session catalog/schema")
            return QualifiedObjectName(session.catalog, session.schema, parts[0])
        if len(parts) == 2:
            if not session.catalog:
                raise ValueError(f"table '{'.'.join(parts)}' requires session catalog")
            return QualifiedObjectName(session.catalog, parts[0], parts[1])
        if len(parts) == 3:
            return QualifiedObjectName(*parts)
        raise ValueError(f"invalid table name {'.'.join(parts)}")

    def get_table_handle(self, session: Session,
                         name: QualifiedObjectName) -> Optional[TableHandle]:
        conn = self.catalogs.get(name.catalog)
        if conn is None:
            return None
        return conn.metadata().get_table_handle(SchemaTableName(name.schema, name.table))

    def get_table_metadata(self, table: TableHandle) -> TableMetadata:
        return self._connector(table).metadata().get_table_metadata(table)

    def get_column_handles(self, table: TableHandle) -> Dict[str, ColumnHandle]:
        return self._connector(table).metadata().get_column_handles(table)

    def get_table_statistics(self, table: TableHandle,
                             constraint: Constraint = Constraint.all()) -> TableStatistics:
        return self._connector(table).metadata().get_table_statistics(table, constraint)

    def connector(self, catalog: str) -> Connector:
        conn = self.catalogs.get(catalog)
        if conn is None:
            raise KeyError(f"unknown catalog {catalog}")
        return conn

    def _connector(self, table: TableHandle) -> Connector:
        return self.connector(table.connector_id)
