"""Columnar file formats (the presto-orc / presto-parquet layer, TPU-native)."""
