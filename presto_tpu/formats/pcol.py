"""PCOL: the engine's TPU-native columnar file format.

Analogue of the reference's columnar formats (presto-orc 46k LoC,
presto-parquet), re-designed for the TPU host path instead of ported:

- column chunks are RAW little-endian arrays at 64-byte alignment — a scan
  is mmap -> numpy view -> device DMA, with zero decode (the reference burns
  worker CPU decompressing ORC streams; HBM-bound TPU pipelines want bytes,
  not codecs);
- dictionary varchar stores the code array + the dictionary values once —
  the engine's native string representation round-trips losslessly;
- a JSON header carries schema + chunk offsets + per-column min/max stats,
  so split pruning reads ~1KB per file (the ORC stripe-footer pattern);
- the data plane (mmap, stats, range pre-filters) is native C++ (libpcol),
  falling back to numpy when no toolchain is available.

Layout:  magic 'PCOL1\\n' | u32 header_len | header json | padded chunks...
Header: {"rows": N, "columns": [{name, type, scale, dtype, offset, nbytes,
         nulls_offset?, dict?: [values...], min?, max?}]}
"""
from __future__ import annotations

import ctypes
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Block, Dictionary, Page
from ..types import (BIGINT, BOOLEAN, DATE, DecimalType, DOUBLE, INTEGER,
                     REAL, SMALLINT, TIMESTAMP, Type, VARCHAR, WIDE_VARCHAR)

MAGIC = b"PCOL1\n"
_ALIGN = 64

_TYPE_TAGS = {"bigint": BIGINT, "integer": INTEGER, "smallint": SMALLINT,
              "double": DOUBLE, "real": REAL, "boolean": BOOLEAN,
              "date": DATE, "timestamp": TIMESTAMP, "varchar": VARCHAR,
              "wide_varchar": WIDE_VARCHAR}


def compact_pages(names: Sequence[str], types: Sequence[Type],
                  pages: Sequence[Page]
                  ) -> Tuple[int, List[Tuple[np.ndarray,
                                             Optional[np.ndarray]]]]:
    """Compact live rows (page mask) into one contiguous array per column.

    The shared preamble of every columnar file writer (pcol and parquet):
    -> (total_rows, [(data astype the engine dtype, bool null mask or None)]).
    Null masks are returned only when at least one null survives compaction.
    """
    masks = [np.asarray(p.mask) for p in pages]
    keeps = [np.flatnonzero(m) for m in masks]
    total = int(sum(len(k) for k in keeps))
    cols: List[Tuple[np.ndarray, Optional[np.ndarray]]] = []
    for c in range(len(names)):
        datas = [np.asarray(p.blocks[c].data)[k]
                 for p, k in zip(pages, keeps)]
        data = np.concatenate(datas) if datas else \
            np.zeros(0, dtype=types[c].np_dtype)
        data = np.ascontiguousarray(data.astype(types[c].np_dtype,
                                                copy=False))
        nulls = None
        if any(p.blocks[c].nulls is not None for p in pages):
            nparts = [np.asarray(p.blocks[c].null_mask())[k]
                      for p, k in zip(pages, keeps)]
            nm = np.concatenate(nparts) if nparts else np.zeros(0, dtype=bool)
            if nm.any():
                nulls = nm
        cols.append((data, nulls))
    return total, cols


def _type_tag(t: Type) -> Tuple[str, int]:
    if isinstance(t, DecimalType):
        return "decimal", t.scale
    name = t.name
    if name == "varchar" and getattr(t, "wide", False):
        return "wide_varchar", 0
    return name, 0


def _type_from_tag(tag: str, scale: int) -> Type:
    if tag == "decimal":
        return DecimalType(18, scale)
    return _TYPE_TAGS[tag]


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def row_ranges(rows: int, step: int) -> List[Tuple[int, int]]:
    """[lo, hi) row splits at `step` granularity — the unit of the
    split-parallel pcol read (each range is decoded independently)."""
    step = max(int(step), 1)
    return [(lo, min(lo + step, rows)) for lo in range(0, rows, step)]


def _native_stats(arr: np.ndarray):
    """Column min/max via libpcol when available (bandwidth-bound native
    loop), else numpy."""
    try:
        from ..native import libpcol
        lib = libpcol()
    except Exception:
        lib = None
    if lib is not None and arr.dtype in (np.int64, np.int32, np.float64) \
            and len(arr) > 0:
        c = np.ascontiguousarray(arr)
        if arr.dtype == np.float64:
            mn, mx = ctypes.c_double(), ctypes.c_double()
            lib.pcol_stats_f64(c.ctypes.data, len(c),
                               ctypes.byref(mn), ctypes.byref(mx))
        elif arr.dtype == np.int32:
            mn, mx = ctypes.c_int64(), ctypes.c_int64()
            lib.pcol_stats_i32(c.ctypes.data, len(c),
                               ctypes.byref(mn), ctypes.byref(mx))
        else:
            mn, mx = ctypes.c_int64(), ctypes.c_int64()
            lib.pcol_stats_i64(c.ctypes.data, len(c),
                               ctypes.byref(mn), ctypes.byref(mx))
        return mn.value, mx.value
    if len(arr) == 0:
        return None, None
    return np.min(arr).item(), np.max(arr).item()


def write_pcol(path: str, names: Sequence[str], types: Sequence[Type],
               dicts: Sequence[Optional[Dictionary]],
               pages: Sequence[Page]) -> int:
    """Write pages (live rows compacted) as one pcol file; returns rows."""
    ncols = len(names)
    total, compacted = compact_pages(names, types, pages)
    cols = [(data,
             None if nulls is None
             else np.ascontiguousarray(nulls.astype(np.uint8)))
            for data, nulls in compacted]

    # header with chunk offsets (two passes: size then write)
    headers = []
    offset = 0  # relative to the data section start
    for c in range(ncols):
        data, nulls = cols[c]
        tag, scale = _type_tag(types[c])
        entry: Dict = {"name": names[c], "type": tag, "scale": scale,
                       "dtype": data.dtype.str, "offset": offset,
                       "nbytes": int(data.nbytes)}
        offset = _pad(offset + data.nbytes)
        if nulls is not None:
            entry["nulls_offset"] = offset
            offset = _pad(offset + nulls.nbytes)
        d = dicts[c]
        if d is not None:
            if not hasattr(d, "values"):
                raise ValueError(
                    f"column {names[c]}: virtual dictionaries cannot be "
                    "persisted; decode before writing")
            entry["dict"] = [str(v) for v in d.values]
        mn, mx = _native_stats(data) if data.dtype.kind in "if" \
            else (None, None)
        if mn is not None:
            entry["min"], entry["max"] = mn, mx
        headers.append(entry)

    header = json.dumps({"rows": total, "columns": headers}).encode()
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint32(len(header)).tobytes())
        f.write(header)
        data_start = _pad(f.tell())
        f.write(b"\0" * (data_start - f.tell()))
        for c in range(ncols):
            data, nulls = cols[c]
            f.write(data.tobytes())
            f.write(b"\0" * (_pad(data.nbytes) - data.nbytes))
            if nulls is not None:
                f.write(nulls.tobytes())
                f.write(b"\0" * (_pad(nulls.nbytes) - nulls.nbytes))
    return total


class PcolFile:
    """Reader: native mmap when available, else a host read.

    `header` short-circuits the JSON header parse with an already-parsed
    one — split-parallel range readers of one file open their own mapping
    each but share a single parse (a dict-heavy header can be megabytes)."""

    def __init__(self, path: str, header: Optional[Dict] = None):
        self.path = path
        self._map = None
        self._lib = None
        try:
            from ..native import libpcol
            self._lib = libpcol()
            self._map = self._lib.pcol_open(path.encode())
            if not self._map:
                self._lib = None
        except Exception:
            self._lib = None
        if self._lib is not None:
            length = self._lib.pcol_length(self._map)
            base = self._lib.pcol_data(self._map)
            self._buf = np.ctypeslib.as_array(base, shape=(length,))
        else:
            self._buf = np.fromfile(path, dtype=np.uint8)
        assert bytes(self._buf[:6]) == MAGIC, f"{path}: not a pcol file"
        hlen = int(np.frombuffer(self._buf[6:10], dtype=np.uint32)[0])
        self.header = header if header is not None \
            else json.loads(bytes(self._buf[10:10 + hlen]))
        self.rows = self.header["rows"]
        self._data_start = _pad(10 + hlen)
        self.columns = {e["name"]: e for e in self.header["columns"]}

    def close(self) -> None:
        if self._lib is not None and self._map:
            self._lib.pcol_close(self._map)
            self._map = None
            self._lib = None

    def column_stats(self, name: str):
        e = self.columns[name]
        return e.get("min"), e.get("max")

    def read_column(self, name: str):
        """-> (data view, null mask or None, Dictionary or None). Zero-copy
        views into the mapping."""
        e = self.columns[name]
        lo = self._data_start + e["offset"]
        data = self._buf[lo: lo + e["nbytes"]].view(np.dtype(e["dtype"]))
        nulls = None
        if "nulls_offset" in e:
            nlo = self._data_start + e["nulls_offset"]
            nulls = self._buf[nlo: nlo + self.rows].view(np.uint8) \
                .astype(bool)
        d = Dictionary(e["dict"]) if "dict" in e else None
        return data, nulls, d

    def read_column_range(self, name: str, lo: int, hi: int):
        """Rows [lo, hi) of one column: (data view, bool null mask or None,
        Dictionary or None). Chunks are raw aligned arrays, so a row range
        is a byte range — the split-parallel scan reads ranges of ONE file
        concurrently without touching the rest of the mapping."""
        e = self.columns[name]
        dt = np.dtype(e["dtype"])
        base = self._data_start + e["offset"]
        data = self._buf[base + lo * dt.itemsize:
                         base + hi * dt.itemsize].view(dt)
        nulls = None
        if "nulls_offset" in e:
            nlo = self._data_start + e["nulls_offset"]
            nulls = self._buf[nlo + lo: nlo + hi].view(np.uint8).astype(bool)
        d = Dictionary(e["dict"]) if "dict" in e else None
        return data, nulls, d

    def pages(self, names: Sequence[str], page_capacity: int):
        """Yield fixed-capacity pages over the selected columns."""
        cols = [self.read_column(n) for n in names]
        types = [_type_from_tag(self.columns[n]["type"],
                                self.columns[n]["scale"]) for n in names]
        for lo in range(0, max(self.rows, 1), page_capacity):
            hi = min(lo + page_capacity, self.rows)
            n = hi - lo
            blocks = []
            for (data, nulls, d), tt in zip(cols, types):
                seg = np.zeros(page_capacity, dtype=data.dtype)
                seg[:n] = data[lo:hi]
                nseg = None
                if nulls is not None:
                    nseg = np.zeros(page_capacity, dtype=bool)
                    nseg[:n] = nulls[lo:hi]
                blocks.append(Block(tt, seg, nseg, d))
            mask = np.arange(page_capacity) < n
            yield Page(tuple(blocks), mask)
            if self.rows == 0:
                break
