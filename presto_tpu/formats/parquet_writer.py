"""Parquet writer: columnar export for the file connector.

Own implementation of the write side of the format — the counterpart of the
reader in formats/parquet.py and the analogue of the reference's parquet/ORC
writers (presto-orc OrcWriter pattern: presto-orc/.../orc/OrcWriter.java;
presto-parquet is read-only in the reference, so parity here is with the ORC
write path's role: the engine's own columnar persistence in an interchange
format). NOT a pyarrow wrapper — pyarrow appears only in tests, verifying the
files interoperate.

Scope (flat schemas, mirroring the reader):
- thrift compact-protocol writer for FileMetaData / PageHeader;
- PLAIN values for numerics/booleans, dictionary page + RLE_DICTIONARY
  indices for varchar (matching the engine's dictionary-encoded blocks, and
  keeping ParquetFile.column_distinct_strings a metadata-only read);
- RLE/bit-packed definition levels for nullable columns (max def level 1);
- data page v1, codecs UNCOMPRESSED / GZIP / ZSTD (SNAPPY is read-only: the
  engine has a snappy decoder but compressing buys nothing in-process);
- column-chunk statistics (min_value/max_value/null_count) so the file
  connector's row-group pruning works on files the engine wrote itself.

Types map exactly as the reader expects them back: BIGINT->INT64,
INTEGER/SMALLINT->INT32, DOUBLE->DOUBLE, REAL->FLOAT, BOOLEAN->BOOLEAN,
DATE->INT32(DATE), DECIMAL(p<=18,s)->INT64(DECIMAL), VARCHAR->BYTE_ARRAY(UTF8).
"""
from __future__ import annotations

import gzip
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..block import Dictionary, Page
from ..types import (DecimalType, Type, is_string)
from .parquet import (C_GZIP, C_UNCOMPRESSED, C_ZSTD, CT_DATE, CT_DECIMAL,
                      CT_INT_16, CT_TIMESTAMP_MILLIS, CT_UTF8, E_PLAIN, E_RLE,
                      E_RLE_DICTIONARY, MAGIC, PT_DATA, PT_DICTIONARY,
                      T_BOOLEAN, T_BYTE_ARRAY, T_DOUBLE, T_FLOAT, T_INT32,
                      T_INT64)

# thrift compact-protocol wire types
_CT_BOOL_TRUE, _CT_BOOL_FALSE, _CT_BYTE = 1, 2, 3
_CT_I16, _CT_I32, _CT_I64, _CT_DOUBLE = 4, 5, 6, 7
_CT_BINARY, _CT_LIST, _CT_STRUCT = 8, 9, 12

_PAGE_ROWS = 1 << 16          # values per data page
_ROW_GROUP_ROWS = 1 << 20     # rows per row group


class _TWriter:
    """Minimal thrift compact-protocol writer (the mirror of _TReader)."""

    __slots__ = ("out", "_last")

    def __init__(self):
        self.out = bytearray()
        self._last = [0]  # per-struct last-field-id stack; root struct open

    def varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def zigzag(self, v: int) -> None:
        self.varint((v << 1) ^ (v >> 63))

    def _field_header(self, fid: int, ctype: int) -> None:
        delta = fid - self._last[-1]
        if 0 < delta < 16:
            self.out.append((delta << 4) | ctype)
        else:
            self.out.append(ctype)
            self.zigzag(fid)
        self._last[-1] = fid

    def field_i32(self, fid: int, v: int) -> None:
        self._field_header(fid, _CT_I32)
        self.zigzag(v)

    def field_i64(self, fid: int, v: int) -> None:
        self._field_header(fid, _CT_I64)
        self.zigzag(v)

    def field_bool(self, fid: int, v: bool) -> None:
        self._field_header(fid, _CT_BOOL_TRUE if v else _CT_BOOL_FALSE)

    def field_binary(self, fid: int, data: bytes) -> None:
        self._field_header(fid, _CT_BINARY)
        self.varint(len(data))
        self.out += data

    def field_struct(self, fid: int) -> None:
        """Open a struct field; caller writes fields then struct_end()."""
        self._field_header(fid, _CT_STRUCT)
        self._last.append(0)

    def struct_end(self) -> None:
        self.out.append(0)
        self._last.pop()

    def field_list(self, fid: int, etype: int, size: int) -> None:
        self._field_header(fid, _CT_LIST)
        if size < 15:
            self.out.append((size << 4) | etype)
        else:
            self.out.append(0xF0 | etype)
            self.varint(size)

    def list_struct_begin(self) -> None:
        """Element of a list<struct>: structs carry their own id stack."""
        self._last.append(0)

    def bytes(self) -> bytes:
        return bytes(self.out)


# ---------------------------------------------------------------------------
# encoders
# ---------------------------------------------------------------------------

def _encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_rle_bitpacked(vals: np.ndarray, bit_width: int,
                         length_prefixed: bool) -> bytes:
    """RLE/bit-packed hybrid. Constant inputs get one RLE run; everything
    else one bit-packed run (groups of 8 values, LSB-first bit order) —
    both spec-legal, and the reader's _decode_rle_bitpacked round-trips
    either."""
    n = len(vals)
    if bit_width == 0 or n == 0:
        body = b""
    elif (vals == vals[0]).all():
        byte_width = (bit_width + 7) // 8
        body = (_encode_varint(n << 1)
                + int(vals[0]).to_bytes(byte_width, "little"))
    else:
        n_groups = (n + 7) // 8
        padded = np.zeros(n_groups * 8, dtype=np.int64)
        padded[:n] = vals
        bits = ((padded[:, None] >> np.arange(bit_width)) & 1).astype(np.uint8)
        body = (_encode_varint((n_groups << 1) | 1)
                + np.packbits(bits.reshape(-1), bitorder="little").tobytes())
    if length_prefixed:
        return struct.pack("<I", len(body)) + body
    return body


def _plain_encode(ptype: int, vals: np.ndarray) -> bytes:
    if ptype == T_INT32:
        return np.ascontiguousarray(vals.astype("<i4")).tobytes()
    if ptype == T_INT64:
        return np.ascontiguousarray(vals.astype("<i8")).tobytes()
    if ptype == T_FLOAT:
        return np.ascontiguousarray(vals.astype("<f4")).tobytes()
    if ptype == T_DOUBLE:
        return np.ascontiguousarray(vals.astype("<f8")).tobytes()
    if ptype == T_BOOLEAN:
        return np.packbits(vals.astype(bool), bitorder="little").tobytes()
    if ptype == T_BYTE_ARRAY:
        parts = []
        for v in vals:
            b = str(v).encode("utf-8")
            parts.append(struct.pack("<I", len(b)))
            parts.append(b)
        return b"".join(parts)
    raise NotImplementedError(f"parquet physical type {ptype}")


def _codec_id(codec: str) -> int:
    return {"uncompressed": C_UNCOMPRESSED, "none": C_UNCOMPRESSED,
            "gzip": C_GZIP, "zstd": C_ZSTD}[codec]


def _compress(codec_id: int, raw: bytes) -> bytes:
    if codec_id == C_GZIP:
        return gzip.compress(raw, 6)
    if codec_id == C_ZSTD:
        import zstandard

        return zstandard.ZstdCompressor().compress(raw)
    return raw


def _stat_bytes(ptype: int, v) -> bytes:
    if ptype == T_INT32:
        return struct.pack("<i", int(v))
    if ptype == T_INT64:
        return struct.pack("<q", int(v))
    if ptype == T_FLOAT:
        return struct.pack("<f", float(v))
    if ptype == T_DOUBLE:
        return struct.pack("<d", float(v))
    if ptype == T_BYTE_ARRAY:
        return str(v).encode("utf-8")
    if ptype == T_BOOLEAN:
        return b"\x01" if v else b"\x00"
    raise NotImplementedError(f"stats for parquet type {ptype}")


# ---------------------------------------------------------------------------
# schema mapping
# ---------------------------------------------------------------------------

def _physical(t: Type) -> Tuple[int, Optional[int], int, int]:
    """engine type -> (ptype, converted_type, scale, precision)."""
    if isinstance(t, DecimalType):
        if t.precision > 18:
            raise NotImplementedError(
                f"decimal({t.precision},{t.scale}) wider than 64 bits")
        return T_INT64, CT_DECIMAL, t.scale, t.precision
    if is_string(t):
        return T_BYTE_ARRAY, CT_UTF8, 0, 0
    name = t.name
    if name == "bigint":
        return T_INT64, None, 0, 0
    if name == "timestamp":  # engine timestamps are millis since epoch
        return T_INT64, CT_TIMESTAMP_MILLIS, 0, 0
    if name == "integer":
        return T_INT32, None, 0, 0
    if name == "smallint":
        return T_INT32, CT_INT_16, 0, 0
    if name == "double":
        return T_DOUBLE, None, 0, 0
    if name == "real":
        return T_FLOAT, None, 0, 0
    if name == "boolean":
        return T_BOOLEAN, None, 0, 0
    if name == "date":
        return T_INT32, CT_DATE, 0, 0
    raise NotImplementedError(f"cannot write type {t} to parquet")


def _write_page_header(page_type: int, uncomp: int, comp: int,
                       num_values: int, encoding: int) -> bytes:
    w = _TWriter()
    w.field_i32(1, page_type)
    w.field_i32(2, uncomp)
    w.field_i32(3, comp)
    if page_type == PT_DICTIONARY:
        w.field_struct(7)
        w.field_i32(1, num_values)
        w.field_i32(2, encoding)
        w.struct_end()
    else:
        w.field_struct(5)
        w.field_i32(1, num_values)
        w.field_i32(2, encoding)
        w.field_i32(3, E_RLE)   # definition_level_encoding
        w.field_i32(4, E_RLE)   # repetition_level_encoding
        w.struct_end()
    w.struct_end()  # PageHeader root
    return w.bytes()


class _ChunkResult:
    __slots__ = ("buf", "data_page_offset", "dict_page_offset", "encodings",
                 "num_values", "uncompressed", "min_v", "max_v", "null_count")

    def __init__(self):
        self.buf = bytearray()
        self.data_page_offset = 0
        self.dict_page_offset: Optional[int] = None
        self.encodings: List[int] = []
        self.num_values = 0
        self.uncompressed = 0
        self.min_v = None
        self.max_v = None
        self.null_count = 0


def _write_chunk(ptype: int, codec_id: int, values: np.ndarray,
                 nulls: Optional[np.ndarray], optional: bool,
                 dictionary: Optional[Dictionary]) -> _ChunkResult:
    """Encode one column of one row group into pages. `values` holds dict
    CODES when `dictionary` is given; null slots' values are ignored.

    `optional` is the WHOLE-COLUMN nullability: the schema declares one
    repetition per column, so every row group must carry def levels whenever
    any group has a null — a null-free group still writes (constant) levels."""
    res = _ChunkResult()
    res.num_values = len(values)
    if nulls is None and optional:
        nulls = np.zeros(len(values), dtype=bool)

    if dictionary is not None:
        dict_vals = [str(v) for v in dictionary.values]
        raw = _plain_encode(T_BYTE_ARRAY, np.asarray(dict_vals, dtype=object))
        comp = _compress(codec_id, raw)
        res.dict_page_offset = 0
        header = _write_page_header(PT_DICTIONARY, len(raw), len(comp),
                                    len(dict_vals), E_PLAIN)
        res.buf += header + comp
        res.uncompressed += len(header) + len(raw)
        bit_width = max(1, int(max(len(dict_vals) - 1, 1)).bit_length())
        value_encoding = E_RLE_DICTIONARY
        res.encodings = [E_RLE_DICTIONARY, E_PLAIN, E_RLE]
    else:
        bit_width = 0
        value_encoding = E_PLAIN
        res.encodings = [E_PLAIN, E_RLE]

    res.data_page_offset = len(res.buf)
    present_all = None if nulls is None else ~np.asarray(nulls)

    for lo in range(0, len(values), _PAGE_ROWS):
        hi = min(lo + _PAGE_ROWS, len(values))
        page_vals = values[lo:hi]
        parts = []
        if optional:
            defs = present_all[lo:hi].astype(np.int64)
            parts.append(encode_rle_bitpacked(defs, 1, length_prefixed=True))
            present = page_vals[present_all[lo:hi]]
            res.null_count += int(hi - lo - len(present))
        else:
            present = page_vals
        if dictionary is not None:
            codes = np.clip(present.astype(np.int64), 0, None)
            parts.append(bytes([bit_width])
                         + encode_rle_bitpacked(codes, bit_width,
                                                length_prefixed=False))
            if len(codes):
                pmn, pmx = dict_min_max(dictionary, codes)
                res.min_v = pmn if res.min_v is None else min(res.min_v, pmn)
                res.max_v = pmx if res.max_v is None else max(res.max_v, pmx)
        else:
            parts.append(_plain_encode(ptype, present))
            if len(present):
                pmn, pmx = present.min(), present.max()
                res.min_v = pmn if res.min_v is None else min(res.min_v, pmn)
                res.max_v = pmx if res.max_v is None else max(res.max_v, pmx)
        raw = b"".join(parts)
        comp = _compress(codec_id, raw)
        header = _write_page_header(PT_DATA, len(raw), len(comp),
                                    hi - lo, value_encoding)
        res.buf += header + comp
        res.uncompressed += len(header) + len(raw)
    return res


def dict_min_max(dictionary: Dictionary, codes: np.ndarray):
    vals = dictionary.values[np.unique(codes)]
    s = sorted(str(v) for v in vals)
    return s[0], s[-1]


# ---------------------------------------------------------------------------
# file-level writer
# ---------------------------------------------------------------------------

def write_parquet(path: str, names: Sequence[str], types: Sequence[Type],
                  dicts: Sequence[Optional[Dictionary]],
                  pages: Sequence[Page], codec: str = "uncompressed",
                  row_group_rows: int = _ROW_GROUP_ROWS) -> int:
    """Write pages (live rows compacted) as one parquet file; returns rows.
    Mirrors write_pcol's contract so the file connector's sink can target
    either format."""
    codec_id = _codec_id(codec)
    ncols = len(names)
    from .pcol import compact_pages
    total, cols = compact_pages(names, types, pages)
    for c in range(ncols):
        if dicts[c] is not None and not hasattr(dicts[c], "values"):
            raise ValueError(
                f"column {names[c]}: virtual dictionaries cannot be "
                "persisted; decode before writing")

    phys = [_physical(t) for t in types]

    with open(path, "wb") as f:
        f.write(MAGIC)
        row_groups = []  # (num_rows, [(chunk_meta...)])
        for lo in range(0, total, row_group_rows):
            hi = min(lo + row_group_rows, total)
            chunk_metas = []
            for c in range(ncols):
                ptype, _ct, _s, _p = phys[c]
                data, nulls = cols[c]
                chunk = _write_chunk(
                    ptype, codec_id, data[lo:hi],
                    None if nulls is None else nulls[lo:hi],
                    nulls is not None, dicts[c])
                start = f.tell()
                f.write(chunk.buf)
                chunk_metas.append((c, start, chunk))
            row_groups.append((hi - lo, chunk_metas))

        meta = _TWriter()
        meta.field_i32(1, 1)                        # version
        meta.field_list(2, _CT_STRUCT, ncols + 1)   # schema
        meta.list_struct_begin()                    # root element
        meta.field_binary(4, b"schema")
        meta.field_i32(5, ncols)
        meta.struct_end()
        for c in range(ncols):
            ptype, ct, scale, precision = phys[c]
            _data, nulls = cols[c]
            meta.list_struct_begin()
            meta.field_i32(1, ptype)
            meta.field_i32(3, 1 if nulls is not None else 0)  # repetition
            meta.field_binary(4, names[c].encode("utf-8"))
            if ct is not None:
                meta.field_i32(6, ct)
                if ct == CT_DECIMAL:
                    meta.field_i32(7, scale)
                    meta.field_i32(8, precision)
            meta.struct_end()
        meta.field_i64(3, total)                    # num_rows
        meta.field_list(4, _CT_STRUCT, len(row_groups))
        for num_rows, chunk_metas in row_groups:
            meta.list_struct_begin()                # RowGroup
            meta.field_list(1, _CT_STRUCT, len(chunk_metas))
            group_bytes = 0
            for c, start, chunk in chunk_metas:
                ptype, _ct, _s, _p = phys[c]
                group_bytes += chunk.uncompressed
                meta.list_struct_begin()            # ColumnChunk
                meta.field_i64(2, start)            # file_offset
                meta.field_struct(3)                # ColumnMetaData
                meta.field_i32(1, ptype)
                meta.field_list(2, _CT_I32, len(chunk.encodings))
                for e in chunk.encodings:
                    meta.zigzag(e)
                meta.field_list(3, _CT_BINARY, 1)   # path_in_schema
                meta.varint(len(names[c].encode()))
                meta.out += names[c].encode()
                meta.field_i32(4, codec_id)
                meta.field_i64(5, chunk.num_values)
                meta.field_i64(6, chunk.uncompressed)
                meta.field_i64(7, len(chunk.buf))   # total_compressed_size
                meta.field_i64(9, start + chunk.data_page_offset)
                if chunk.dict_page_offset is not None:
                    meta.field_i64(11, start + chunk.dict_page_offset)
                if chunk.min_v is not None or chunk.null_count:
                    meta.field_struct(12)           # Statistics
                    meta.field_i64(3, chunk.null_count)
                    if chunk.max_v is not None:
                        meta.field_binary(
                            5, _stat_bytes(ptype, chunk.max_v))
                        meta.field_binary(
                            6, _stat_bytes(ptype, chunk.min_v))
                    meta.struct_end()
                meta.struct_end()                   # ColumnMetaData
                meta.struct_end()                   # ColumnChunk
            meta.field_i64(2, group_bytes)          # total_byte_size
            meta.field_i64(3, num_rows)
            meta.struct_end()                       # RowGroup
        meta.field_binary(6, b"presto-tpu")         # created_by
        meta.struct_end()                           # FileMetaData STOP byte
        footer = meta.bytes()
        f.write(footer)
        f.write(struct.pack("<I", len(footer)))
        f.write(MAGIC)
    return total
