"""ORC reader: the reference's benchmark-schema format, from scratch.

Analogue of presto-orc (presto-orc/src/main/java/com/facebook/presto/orc/,
27k LoC: OrcReader footer/stripe parsing, stream decoders, OrcPredicate
stripe skipping) — NOT a pyarrow wrapper: pyarrow appears only in tests as
the fixture writer, the read path is this module.

Scope (the flat-schema core, mirroring the parquet reader's):
- protobuf wire-format reader for PostScript / Footer / Metadata /
  StripeFooter (ORC metadata is protobuf where parquet's is thrift);
- compression framing (3-byte chunk headers) with NONE/ZLIB/SNAPPY/ZSTD/LZ4;
- byte RLE + boolean (bit) RLE, and integer RLEv2 in all four sub-formats
  (SHORT_REPEAT, DIRECT, PATCHED_BASE, DELTA) with vectorized bit-unpacking;
- column types: boolean, byte/short/int/long (DIRECT_V2), float, double,
  string/varchar/char (DIRECT_V2 + DICTIONARY_V2), date, decimal (<=18
  digits, varint mantissa + scale stream);
- PRESENT streams -> null masks; stripe-level IntegerStatistics for the
  file connector's split pruning (the OrcPredicate stripe-skip pattern).

Nested types (struct/list/map/union beyond the root struct), timestamps and
binary are out of scope and rejected loudly.
"""
from __future__ import annotations

import dataclasses
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Dictionary
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, SMALLINT,
                     Type, VARCHAR, DecimalType)
from .parquet import snappy_decompress

MAGIC = b"ORC"

# CompressionKind
K_NONE, K_ZLIB, K_SNAPPY, K_LZO, K_LZ4, K_ZSTD = range(6)
# Type.Kind
T_BOOLEAN, T_BYTE, T_SHORT, T_INT, T_LONG, T_FLOAT, T_DOUBLE = range(7)
T_STRING, T_BINARY, T_TIMESTAMP, T_LIST, T_MAP, T_STRUCT = 7, 8, 9, 10, 11, 12
T_UNION, T_DECIMAL, T_DATE, T_VARCHAR, T_CHAR = 13, 14, 15, 16, 17
# Stream.Kind
S_PRESENT, S_DATA, S_LENGTH, S_DICT_DATA = 0, 1, 2, 3
S_DICT_COUNT, S_SECONDARY, S_ROW_INDEX = 4, 5, 6
# ColumnEncoding.Kind
E_DIRECT, E_DICTIONARY, E_DIRECT_V2, E_DICTIONARY_V2 = range(4)

_INT_KINDS = (T_BYTE, T_SHORT, T_INT, T_LONG, T_DATE)
_STR_KINDS = (T_STRING, T_VARCHAR, T_CHAR)


# ---------------------------------------------------------------------------
# protobuf wire reader
# ---------------------------------------------------------------------------

class _PBReader:
    """Minimal protobuf wire-format reader over a bytes buffer."""

    __slots__ = ("buf", "pos", "end")

    def __init__(self, buf: bytes, pos: int = 0, end: Optional[int] = None):
        self.buf = buf
        self.pos = pos
        self.end = len(buf) if end is None else end

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def fields(self):
        """Yield (field_number, wire_type) until the buffer region ends."""
        while self.pos < self.end:
            key = self.varint()
            yield key >> 3, key & 7

    def bytes_field(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def sub(self) -> "_PBReader":
        n = self.varint()
        r = _PBReader(self.buf, self.pos, self.pos + n)
        self.pos += n
        return r

    def skip(self, wire_type: int) -> None:
        if wire_type == 0:
            self.varint()
        elif wire_type == 1:
            self.pos += 8
        elif wire_type == 2:
            # read the varint FIRST: `pos += varint()` loads pos before
            # varint() advances it (augmented-assignment order; the thrift
            # reader in parquet.py hit the same trap)
            n = self.varint()
            self.pos += n
        elif wire_type == 5:
            self.pos += 4
        else:
            raise ValueError(f"cannot skip protobuf wire type {wire_type}")


# ---------------------------------------------------------------------------
# metadata structs
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OrcType:
    kind: int = T_STRUCT
    subtypes: List[int] = dataclasses.field(default_factory=list)
    field_names: List[str] = dataclasses.field(default_factory=list)
    precision: int = 0
    scale: int = 0


@dataclasses.dataclass
class StripeInfo:
    offset: int = 0
    index_length: int = 0
    data_length: int = 0
    footer_length: int = 0
    num_rows: int = 0


@dataclasses.dataclass
class StreamInfo:
    kind: int = 0
    column: int = 0
    length: int = 0


@dataclasses.dataclass
class ColumnStats:
    """IntegerStatistics / DoubleStatistics subset for stripe pruning."""
    min_value: Optional[Any] = None
    max_value: Optional[Any] = None
    has_null: bool = False


def _read_postscript(buf: bytes):
    r = _PBReader(buf)
    footer_len = metadata_len = 0
    compression = K_NONE
    block_size = 256 * 1024
    for f, wt in r.fields():
        if f == 1:
            footer_len = r.varint()
        elif f == 2:
            compression = r.varint()
        elif f == 3:
            block_size = r.varint()
        elif f == 5:
            metadata_len = r.varint()
        elif f == 8000:
            r.bytes_field()  # magic
        else:
            r.skip(wt)
    return footer_len, compression, block_size, metadata_len


def _read_type(r: _PBReader) -> OrcType:
    t = OrcType()
    for f, wt in r.fields():
        if f == 1:
            t.kind = r.varint()
        elif f == 2:
            if wt == 2:  # packed repeated uint32
                sub = r.sub()
                while sub.pos < sub.end:
                    t.subtypes.append(sub.varint())
            else:
                t.subtypes.append(r.varint())
        elif f == 3:
            t.field_names.append(r.bytes_field().decode())
        elif f == 5:
            t.precision = r.varint()
        elif f == 6:
            t.scale = r.varint()
        else:
            r.skip(wt)
    return t


def _read_stripe_info(r: _PBReader) -> StripeInfo:
    s = StripeInfo()
    for f, wt in r.fields():
        if f == 1:
            s.offset = r.varint()
        elif f == 2:
            s.index_length = r.varint()
        elif f == 3:
            s.data_length = r.varint()
        elif f == 4:
            s.footer_length = r.varint()
        elif f == 5:
            s.num_rows = r.varint()
        else:
            r.skip(wt)
    return s


def _zigzag_decode_int(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _read_column_stats(r: _PBReader) -> ColumnStats:
    out = ColumnStats()
    for f, wt in r.fields():
        if f == 2:      # IntegerStatistics {1: min sint64, 2: max sint64}
            sub = r.sub()
            for f2, wt2 in sub.fields():
                if f2 == 1:
                    v = sub.varint()
                    out.min_value = _zigzag_decode_int(v)
                elif f2 == 2:
                    v = sub.varint()
                    out.max_value = _zigzag_decode_int(v)
                else:
                    sub.skip(wt2)
        elif f == 3:    # DoubleStatistics {1: min, 2: max} (wire type 1)
            sub = r.sub()
            for f2, wt2 in sub.fields():
                if f2 in (1, 2):
                    (val,) = struct.unpack("<d", sub.buf[sub.pos:sub.pos + 8])
                    sub.pos += 8
                    if f2 == 1:
                        out.min_value = val
                    else:
                        out.max_value = val
                else:
                    sub.skip(wt2)
        elif f == 10:   # hasNull
            out.has_null = bool(r.varint())
        else:
            r.skip(wt)
    return out


# ---------------------------------------------------------------------------
# compression framing
# ---------------------------------------------------------------------------

def _decompress_block(codec: int, data: bytes) -> bytes:
    if codec == K_ZLIB:
        return zlib.decompress(data, -15)  # raw deflate
    if codec == K_SNAPPY:
        return snappy_decompress(data)
    if codec == K_ZSTD:
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            data, max_output_size=1 << 26)
    if codec == K_LZ4:
        raise NotImplementedError("orc lz4 compression not supported")
    raise NotImplementedError(f"orc compression kind {codec}")


def decompress_stream(codec: int, data: bytes) -> bytes:
    """Undo ORC chunk framing: 3-byte headers (len << 1 | is_original)."""
    if codec == K_NONE:
        return data
    out = []
    pos = 0
    n = len(data)
    while pos < n:
        header = int.from_bytes(data[pos:pos + 3], "little")
        pos += 3
        chunk_len = header >> 1
        chunk = data[pos:pos + chunk_len]
        pos += chunk_len
        out.append(chunk if header & 1 else _decompress_block(codec, chunk))
    return b"".join(out)


# ---------------------------------------------------------------------------
# run-length decoders
# ---------------------------------------------------------------------------

def decode_byte_rle(data: bytes, count: int) -> np.ndarray:
    """Byte RLE: control c in [0,127] = run of c+3 copies; c in [128,255] =
    256-c literal bytes."""
    out = np.empty(count, dtype=np.uint8)
    filled = 0
    pos = 0
    while filled < count:
        c = data[pos]
        pos += 1
        if c < 128:
            run = c + 3
            out[filled:filled + run] = data[pos]
            pos += 1
            filled += run
        else:
            lit = 256 - c
            out[filled:filled + lit] = np.frombuffer(
                data, dtype=np.uint8, count=lit, offset=pos)
            pos += lit
            filled += lit
    return out[:count]


def decode_bool_rle(data: bytes, count: int) -> np.ndarray:
    """Boolean stream: byte RLE over bit-bytes, bits MSB-first."""
    nbytes = (count + 7) // 8
    raw = decode_byte_rle(data, nbytes)
    return np.unpackbits(raw, bitorder="big")[:count].astype(bool)


# 5-bit width codes for DIRECT/PATCHED_BASE/DELTA payloads
_WIDTH_TABLE = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16,
                17, 18, 19, 20, 21, 22, 23, 24, 26, 28, 30, 32, 40, 48,
                56, 64]


def _closest_fixed_bits(bits: int) -> int:
    """Round up to the nearest encodable bit width (1..24, 26, 28, 30, 32,
    40, 48, 56, 64) — the Java reader's getClosestFixedBits."""
    for w in _WIDTH_TABLE:
        if bits <= w:
            return w
    return 64


def _bits_be(data: bytes, start_bit: int, count: int, width: int
             ) -> np.ndarray:
    """Unpack `count` big-endian `width`-bit values starting at start_bit."""
    if width == 0 or count == 0:
        return np.zeros(count, dtype=np.int64)
    end_bit = start_bit + count * width
    end_byte = (end_bit + 7) // 8
    bits = np.unpackbits(np.frombuffer(data, dtype=np.uint8,
                                       count=end_byte),
                         bitorder="big")[start_bit:end_bit]
    vals = bits.reshape(count, width).astype(np.int64)
    weights = (np.int64(1) << np.arange(width - 1, -1, -1,
                                        dtype=np.int64))
    return vals @ weights


def _varint_at(data: bytes, pos: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def decode_rlev2(data: bytes, count: int, signed: bool) -> np.ndarray:
    """Integer RLEv2: SHORT_REPEAT / DIRECT / PATCHED_BASE / DELTA runs."""
    out = np.empty(count, dtype=np.int64)
    filled = 0
    pos = 0
    while filled < count:
        b0 = data[pos]
        enc = b0 >> 6
        if enc == 0:                      # SHORT_REPEAT
            width = ((b0 >> 3) & 0x7) + 1
            run = (b0 & 0x7) + 3
            v = int.from_bytes(data[pos + 1:pos + 1 + width], "big")
            if signed:
                v = (v >> 1) ^ -(v & 1)
            out[filled:filled + run] = v
            filled += run
            pos += 1 + width
        elif enc == 1:                    # DIRECT
            width = _WIDTH_TABLE[(b0 >> 1) & 0x1F]
            run = ((b0 & 1) << 8 | data[pos + 1]) + 1
            vals = _bits_be(data[pos + 2:], 0, run, width)
            if signed:
                # LOGICAL shift for the zigzag decode: 64-bit-wide values set
                # the int64 sign bit and an arithmetic >> would sign-extend
                vals = (vals.view(np.uint64) >> np.uint64(1)).view(
                    np.int64) ^ -(vals & 1)
            out[filled:filled + run] = vals
            filled += run
            pos += 2 + (run * width + 7) // 8
        elif enc == 2:                    # PATCHED_BASE
            width = _WIDTH_TABLE[(b0 >> 1) & 0x1F]
            run = ((b0 & 1) << 8 | data[pos + 1]) + 1
            b2 = data[pos + 2]
            base_w = ((b2 >> 5) & 0x7) + 1
            patch_w = _WIDTH_TABLE[b2 & 0x1F]
            b3 = data[pos + 3]
            pgw = ((b3 >> 5) & 0x7) + 1   # patch GAP width, 1..8 BITS
            pll = b3 & 0x1F
            p = pos + 4
            base = int.from_bytes(data[p:p + base_w], "big")
            sign_mask = 1 << (base_w * 8 - 1)
            if base & sign_mask:
                base = -(base & (sign_mask - 1))
            p += base_w
            vals = _bits_be(data[p:], 0, run, width)
            p += (run * width + 7) // 8
            # each patch entry is gap(pgw bits) | patch(patch_w bits), stored
            # at the closest fixed bit width (the Java reader's
            # getClosestFixedBits(pgw + pw)); the block pads to whole bytes
            patch_bits = _closest_fixed_bits(pgw + patch_w)
            entries = _bits_be(data[p:], 0, pll, patch_bits)
            p += (pll * patch_bits + 7) // 8
            gap_acc = 0
            for e in entries:
                gap_acc += int(e) >> patch_w
                patch = int(e) & ((1 << patch_w) - 1)
                vals[gap_acc] |= patch << width
            out[filled:filled + run] = vals + base
            filled += run
            pos = p
        else:                             # DELTA
            width_code = (b0 >> 1) & 0x1F
            width = 0 if width_code == 0 else _WIDTH_TABLE[width_code]
            run = ((b0 & 1) << 8 | data[pos + 1]) + 1
            p = pos + 2
            base, p = _varint_at(data, p)
            if signed:
                base = (base >> 1) ^ -(base & 1)
            delta0, p = _varint_at(data, p)
            delta0 = (delta0 >> 1) ^ -(delta0 & 1)  # always signed
            seq = np.empty(run, dtype=np.int64)
            seq[0] = base
            if run > 1:
                seq[1] = base + delta0
                if run > 2:
                    if width == 0:
                        deltas = np.full(run - 2, abs(delta0),
                                         dtype=np.int64)
                    else:
                        deltas = _bits_be(data[p:], 0, run - 2, width)
                        p += ((run - 2) * width + 7) // 8
                    if delta0 < 0:
                        deltas = -deltas
                    seq[2:] = deltas
                    np.cumsum(seq[1:], out=seq[1:])
                elif width:  # spec: payload padded even when empty
                    p += 0
            out[filled:filled + run] = seq
            filled += run
            pos = p
    return out[:count]


# ---------------------------------------------------------------------------
# column readers
# ---------------------------------------------------------------------------

def _engine_type(t: OrcType) -> Type:
    if t.kind == T_BOOLEAN:
        return BOOLEAN
    if t.kind in (T_BYTE, T_SHORT):
        return SMALLINT
    if t.kind == T_INT:
        return INTEGER
    if t.kind == T_LONG:
        return BIGINT
    if t.kind == T_FLOAT:
        return REAL
    if t.kind == T_DOUBLE:
        return DOUBLE
    if t.kind in _STR_KINDS:
        return VARCHAR
    if t.kind == T_DATE:
        return DATE
    if t.kind == T_DECIMAL:
        if t.precision > 18:
            raise NotImplementedError(
                f"orc decimal({t.precision},{t.scale}) wider than 64 bits")
        return DecimalType(t.precision or 18, t.scale)
    raise NotImplementedError(f"orc type kind {t.kind} not supported")


def _decode_varint_stream(data: bytes, count: int) -> np.ndarray:
    """Decimal mantissas: `count` zigzag base-128 varints."""
    out = np.empty(count, dtype=np.int64)
    pos = 0
    for i in range(count):
        v, pos = _varint_at(data, pos)
        out[i] = (v >> 1) ^ -(v & 1)
    return out


class OrcFile:
    """One ORC file: schema + stripe readers (OrcReader analogue)."""

    def __init__(self, path: str):
        self.path = path
        self.f = open(path, "rb")
        try:
            size = os.fstat(self.f.fileno()).st_size
            tail_len = min(size, 16 * 1024)
            self.f.seek(size - tail_len)
            tail = self.f.read(tail_len)
            ps_len = tail[-1]
            ps = tail[-1 - ps_len:-1]
            footer_len, self.codec, self.block_size, meta_len = \
                _read_postscript(ps)
            need = footer_len + meta_len + ps_len + 1
            if need > tail_len:  # big footer (many stripes / wide schema)
                tail_len = min(size, need)
                self.f.seek(size - tail_len)
                tail = self.f.read(tail_len)
            footer_end = tail_len - 1 - ps_len
            footer_buf = decompress_stream(
                self.codec, tail[footer_end - footer_len:footer_end])
            self._parse_footer(footer_buf)
            meta_end = footer_end - footer_len
            # stripe statistics parse LAZILY on first stripe_col_stats call:
            # scans open one OrcFile per stripe split and never read them
            self._meta_raw = tail[meta_end - meta_len:meta_end] \
                if meta_len else b""
            self._stripe_stats: Optional[List[List[ColumnStats]]] = None
        except BaseException:
            self.f.close()
            raise
        root = self.types[0]
        if root.kind != T_STRUCT:
            raise NotImplementedError("orc root type must be a struct")
        for sub in root.subtypes:
            if self.types[sub].kind in (T_LIST, T_MAP, T_STRUCT, T_UNION,
                                        T_TIMESTAMP, T_BINARY):
                raise NotImplementedError(
                    f"orc column type kind {self.types[sub].kind} "
                    "not supported (flat schemas only)")

    def _parse_footer(self, buf: bytes) -> None:
        r = _PBReader(buf)
        self.stripes: List[StripeInfo] = []
        self.types: List[OrcType] = []
        self.num_rows = 0
        self.file_stats: List[ColumnStats] = []
        for f, wt in r.fields():
            if f == 3:
                self.stripes.append(_read_stripe_info(r.sub()))
            elif f == 4:
                self.types.append(_read_type(r.sub()))
            elif f == 6:
                self.num_rows = r.varint()
            elif f == 7:
                self.file_stats.append(_read_column_stats(r.sub()))
            else:
                r.skip(wt)

    @property
    def stripe_stats(self) -> List[List[ColumnStats]]:
        if self._stripe_stats is None:
            self._stripe_stats = []
            if self._meta_raw:
                self._parse_metadata(
                    decompress_stream(self.codec, self._meta_raw))
        return self._stripe_stats

    def _parse_metadata(self, buf: bytes) -> None:
        r = _PBReader(buf)
        for f, wt in r.fields():
            if f == 1:  # StripeStatistics { 1: colStats repeated }
                sub = r.sub()
                cols = []
                for f2, wt2 in sub.fields():
                    if f2 == 1:
                        cols.append(_read_column_stats(sub.sub()))
                    else:
                        sub.skip(wt2)
                self._stripe_stats.append(cols)
            else:
                r.skip(wt)

    # ------------------------------------------------------------------ api

    @property
    def schema(self) -> List[Tuple[str, Type]]:
        root = self.types[0]
        return [(name, _engine_type(self.types[sub]))
                for name, sub in zip(root.field_names, root.subtypes)]

    @property
    def n_stripes(self) -> int:
        return len(self.stripes)

    def stripe_rows(self, s: int) -> int:
        return self.stripes[s].num_rows

    def stripe_col_stats(self, s: int, column: str
                         ) -> Optional[Tuple[Any, Any]]:
        """(min, max) for an int/double column of one stripe, or None."""
        if s >= len(self.stripe_stats):
            return None
        root = self.types[0]
        try:
            ci = root.field_names.index(column)
        except ValueError:
            return None
        col_id = root.subtypes[ci]
        stats = self.stripe_stats[s]
        if col_id >= len(stats):
            return None
        cs = stats[col_id]
        if cs.min_value is None:
            return None
        return cs.min_value, cs.max_value

    def column_distinct_strings(self, name: str) -> Optional[List[str]]:
        """Distinct values of a string column by decoding ONLY dictionary
        streams (parallel of ParquetFile.column_distinct_strings). Returns
        None when any stripe is direct-encoded — caller falls back to a
        full read."""
        root = self.types[0]
        try:
            ci = root.field_names.index(name)
        except ValueError:
            return None
        col_id = root.subtypes[ci]
        if self.types[col_id].kind not in _STR_KINDS:
            return None
        out: List[str] = []
        seen = set()
        for info in self.stripes:
            streams, encodings, dict_sizes = self._stripe_footer(info)
            if encodings[col_id] != E_DICTIONARY_V2:
                return None
            offset = info.offset + info.index_length
            blob = lens_raw = None
            for st in streams:
                if st.kind in (S_ROW_INDEX, 7, 8):
                    continue
                if st.column == col_id and st.kind in (S_DICT_DATA, S_LENGTH):
                    self.f.seek(offset)
                    raw = decompress_stream(self.codec,
                                            self.f.read(st.length))
                    if st.kind == S_DICT_DATA:
                        blob = raw
                    else:
                        lens_raw = raw
                offset += st.length
            dsz = dict_sizes[col_id]
            lens = decode_rlev2(lens_raw or b"", dsz, signed=False)
            offs = np.concatenate([[0], np.cumsum(lens)])
            blob = blob or b""
            for i in range(dsz):
                v = blob[offs[i]:offs[i + 1]].decode("utf-8", "replace")
                if v not in seen:
                    seen.add(v)
                    out.append(v)
        return out

    def _stripe_footer(self, info: StripeInfo):
        self.f.seek(info.offset + info.index_length + info.data_length)
        buf = decompress_stream(self.codec, self.f.read(info.footer_length))
        r = _PBReader(buf)
        streams: List[StreamInfo] = []
        encodings: List[int] = []
        dict_sizes: List[int] = []
        for f, wt in r.fields():
            if f == 1:
                sub = r.sub()
                st = StreamInfo()
                for f2, wt2 in sub.fields():
                    if f2 == 1:
                        st.kind = sub.varint()
                    elif f2 == 2:
                        st.column = sub.varint()
                    elif f2 == 3:
                        st.length = sub.varint()
                    else:
                        sub.skip(wt2)
                streams.append(st)
            elif f == 2:
                sub = r.sub()
                enc = 0
                dsz = 0
                for f2, wt2 in sub.fields():
                    if f2 == 1:
                        enc = sub.varint()
                    elif f2 == 2:
                        dsz = sub.varint()
                    else:
                        sub.skip(wt2)
                encodings.append(enc)
                dict_sizes.append(dsz)
            else:
                r.skip(wt)
        return streams, encodings, dict_sizes

    def read_stripe(self, s: int, columns: Sequence[str]
                    ) -> Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]:
        """-> {name: (values, null_mask_or_None)} with len == stripe rows."""
        info = self.stripes[s]
        streams, encodings, dict_sizes = self._stripe_footer(info)
        n = info.num_rows
        root = self.types[0]
        wanted = {}
        for name in columns:
            try:
                ci = root.field_names.index(name)
            except ValueError:
                raise KeyError(f"{self.path}: no column {name}") from None
            wanted[root.subtypes[ci]] = name

        # stream layout: index streams first, then data streams sequentially
        offset = info.offset + info.index_length
        chunks: Dict[Tuple[int, int], bytes] = {}
        for st in streams:
            if st.kind in (S_ROW_INDEX, 7, 8):  # row index + bloom filters
                continue                        # live in the index region
            if st.column in wanted:
                self.f.seek(offset)
                chunks[(st.column, st.kind)] = decompress_stream(
                    self.codec, self.f.read(st.length))
            offset += st.length

        out = {}
        for col_id, name in wanted.items():
            t = self.types[col_id]
            enc = encodings[col_id] if col_id < len(encodings) else E_DIRECT
            nulls = None
            present = chunks.get((col_id, S_PRESENT))
            n_present = n
            if present is not None:
                bits = decode_bool_rle(present, n)
                if not bits.all():
                    nulls = ~bits
                n_present = int(bits.sum())
            vals = self._decode_column(t, enc, chunks, col_id, n_present,
                                       dict_sizes[col_id]
                                       if col_id < len(dict_sizes) else 0)
            if nulls is not None:
                if vals.dtype == object:
                    full = np.full(n, None, dtype=object)
                else:
                    full = np.zeros(n, dtype=vals.dtype)
                full[~nulls] = vals
                vals = full
            out[name] = (vals, nulls)
        return out

    def _decode_column(self, t: OrcType, enc: int, chunks, col_id: int,
                       n: int, dict_size: int) -> np.ndarray:
        data = chunks.get((col_id, S_DATA), b"")
        if t.kind == T_BOOLEAN:
            return decode_bool_rle(data, n)
        if t.kind == T_BYTE:
            # tinyint DATA is byte RLE regardless of the column encoding
            return decode_byte_rle(data, n).astype(np.int8).astype(np.int64)
        if t.kind in _INT_KINDS:
            if enc not in (E_DIRECT_V2,):
                raise NotImplementedError(
                    f"orc integer encoding {enc} (RLEv1) not supported")
            return decode_rlev2(data, n, signed=True)
        if t.kind == T_FLOAT:
            return np.frombuffer(data, dtype="<f4", count=n)
        if t.kind == T_DOUBLE:
            return np.frombuffer(data, dtype="<f8", count=n)
        if t.kind == T_DECIMAL:
            mantissa = _decode_varint_stream(data, n)
            # SECONDARY carries per-value scales; normalize to declared scale
            scales = decode_rlev2(chunks.get((col_id, S_SECONDARY), b""),
                                  n, signed=True)
            declared = t.scale
            diff = declared - scales
            return mantissa * (10 ** diff.clip(0)) // (10 ** (-diff).clip(0))
        if t.kind in _STR_KINDS:
            if enc == E_DICTIONARY_V2:
                codes = decode_rlev2(data, n, signed=False)
                lens = decode_rlev2(chunks.get((col_id, S_LENGTH), b""),
                                    dict_size, signed=False)
                blob = chunks.get((col_id, S_DICT_DATA), b"")
                offs = np.concatenate([[0], np.cumsum(lens)])
                values = [blob[offs[i]:offs[i + 1]].decode("utf-8", "replace")
                          for i in range(dict_size)]
                arr = np.empty(n, dtype=object)
                vals_np = np.asarray(values, dtype=object)
                if n:
                    arr[:] = vals_np[codes]
                return arr
            if enc == E_DIRECT_V2:
                lens = decode_rlev2(chunks.get((col_id, S_LENGTH), b""),
                                    n, signed=False)
                offs = np.concatenate([[0], np.cumsum(lens)])
                arr = np.empty(n, dtype=object)
                for i in range(n):
                    arr[i] = data[offs[i]:offs[i + 1]].decode(
                        "utf-8", "replace")
                return arr
            raise NotImplementedError(f"orc string encoding {enc}")
        raise NotImplementedError(f"orc type kind {t.kind}")

    def close(self):
        self.f.close()
