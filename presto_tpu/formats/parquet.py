"""Parquet reader: external columnar ingest for the file connector.

Own implementation of the format core — the analogue of presto-parquet
(presto-parquet/src/main/java/com/facebook/presto/parquet/, 4.7k LoC: footer
thrift metadata, page headers, PLAIN/RLE_DICTIONARY/RLE decoding, codecs) —
NOT a pyarrow wrapper: the engine must own its ingest path the way the
reference owns ORC/Parquet (pyarrow appears only in tests, as the writer of
fixture files).

Scope (the flat-schema core that covers TPC-H/DS exports):
- thrift compact-protocol reader for FileMetaData / PageHeader;
- PLAIN (int32/int64/float/double/byte_array/boolean), RLE_DICTIONARY
  (+ PLAIN_DICTIONARY) value encodings; RLE/bit-packed hybrid def levels
  (max_def_level <= 1: flat optional columns);
- data page v1 + v2, dictionary pages;
- codecs: UNCOMPRESSED, SNAPPY (own decoder), GZIP (zlib), ZSTD;
- type mapping into this engine's substrate: INT32->INTEGER/DATE,
  INT64->BIGINT/DECIMAL(scaled int), FIXED_LEN_BYTE_ARRAY decimal ->
  scaled int64 (precision <= 18), BYTE_ARRAY (utf8) -> dictionary-encoded
  VARCHAR, DOUBLE->DOUBLE, FLOAT->REAL, BOOLEAN->BOOLEAN.

Nested (repeated) schemas and INT96 timestamps are out of scope and rejected
loudly.
"""
from __future__ import annotations

import dataclasses
import gzip
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Dictionary
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, SMALLINT,
                     TIMESTAMP, Type, VARCHAR, DecimalType)

MAGIC = b"PAR1"

# parquet::Type
T_BOOLEAN, T_INT32, T_INT64, T_INT96 = 0, 1, 2, 3
T_FLOAT, T_DOUBLE, T_BYTE_ARRAY, T_FLBA = 4, 5, 6, 7
# parquet::CompressionCodec
C_UNCOMPRESSED, C_SNAPPY, C_GZIP, C_LZO, C_BROTLI, C_LZ4, C_ZSTD = range(7)
# parquet::Encoding
E_PLAIN, E_PLAIN_DICTIONARY, E_RLE, E_BIT_PACKED = 0, 2, 3, 4
E_DELTA_BINARY_PACKED, E_DELTA_LENGTH_BA, E_DELTA_BA = 5, 6, 7
E_RLE_DICTIONARY = 8
# parquet::ConvertedType (subset)
CT_UTF8, CT_DECIMAL, CT_DATE = 0, 5, 6
CT_TIMESTAMP_MILLIS, CT_INT_16 = 9, 16
# parquet::PageType
PT_DATA, PT_INDEX, PT_DICTIONARY, PT_DATA_V2 = 0, 1, 2, 3


# ---------------------------------------------------------------------------
# thrift compact protocol (reader only)
# ---------------------------------------------------------------------------

class _TReader:
    """Minimal thrift compact-protocol reader over a bytes buffer."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _byte(self) -> int:
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def varint(self) -> int:
        out = 0
        shift = 0
        while True:
            b = self._byte()
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out
            shift += 7

    def zigzag(self) -> int:
        v = self.varint()
        return (v >> 1) ^ -(v & 1)

    def read_bytes(self) -> bytes:
        n = self.varint()
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def skip(self, ftype: int) -> None:
        if ftype in (1, 2):          # BOOL true/false (value in the type)
            return
        if ftype == 3:               # byte
            self.pos += 1
        elif ftype in (4, 5, 6):     # i16/i32/i64 zigzag varints
            self.varint()
        elif ftype == 7:             # double
            self.pos += 8
        elif ftype == 8:             # binary/string
            # NOTE: must read the varint FIRST — `pos += varint()` loads pos
            # before varint() advances it (augmented-assignment order)
            n = self.varint()
            self.pos += n
        elif ftype == 9:             # list
            head = self._byte()
            size = head >> 4
            etype = head & 0x0F
            if size == 15:
                size = self.varint()
            for _ in range(size):
                self.skip(etype)
        elif ftype == 12:            # struct
            self.skip_struct()
        elif ftype == 11:            # map? (not used by parquet)
            raise ValueError("unexpected thrift map in parquet metadata")
        else:
            raise ValueError(f"cannot skip thrift type {ftype}")

    def skip_struct(self) -> None:
        last = 0
        while True:
            head = self._byte()
            if head == 0:
                return
            delta = head >> 4
            ftype = head & 0x0F
            last = last + delta if delta else self.zigzag()
            self.skip(ftype)

    def fields(self):
        """Yield (field_id, ftype) for one struct; caller reads or .skip()s."""
        last = 0
        while True:
            head = self._byte()
            if head == 0:
                return
            delta = head >> 4
            ftype = head & 0x0F
            if delta:
                last += delta
            else:
                last = self.zigzag()
            yield last, ftype

    def list_header(self) -> Tuple[int, int]:
        head = self._byte()
        size = head >> 4
        etype = head & 0x0F
        if size == 15:
            size = self.varint()
        return size, etype

    def bool_value(self, ftype: int) -> bool:
        return ftype == 1


# ---------------------------------------------------------------------------
# metadata structs (only the fields the reader uses)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SchemaElement:
    name: str = ""
    ptype: Optional[int] = None
    type_length: int = 0
    repetition: int = 0          # 0 required, 1 optional, 2 repeated
    num_children: int = 0
    converted_type: Optional[int] = None
    scale: int = 0
    precision: int = 0


@dataclasses.dataclass
class ColumnMeta:
    ptype: int = 0
    encodings: List[int] = dataclasses.field(default_factory=list)
    path: Tuple[str, ...] = ()
    codec: int = 0
    num_values: int = 0
    total_compressed_size: int = 0
    data_page_offset: int = 0
    dictionary_page_offset: Optional[int] = None
    min_value: Optional[bytes] = None
    max_value: Optional[bytes] = None
    null_count: Optional[int] = None


@dataclasses.dataclass
class RowGroup:
    columns: List[ColumnMeta]
    num_rows: int


def _read_schema_element(r: _TReader) -> SchemaElement:
    e = SchemaElement()
    for fid, ft in r.fields():
        if fid == 1:
            e.ptype = r.zigzag()
        elif fid == 2:
            e.type_length = r.zigzag()
        elif fid == 3:
            e.repetition = r.zigzag()
        elif fid == 4:
            e.name = r.read_bytes().decode("utf-8")
        elif fid == 5:
            e.num_children = r.zigzag()
        elif fid == 6:
            e.converted_type = r.zigzag()
        elif fid == 7:
            e.scale = r.zigzag()
        elif fid == 8:
            e.precision = r.zigzag()
        else:
            r.skip(ft)
    return e


def _read_statistics(r: _TReader) -> Tuple[Optional[bytes], Optional[bytes],
                                           Optional[int]]:
    mn = mx = None
    nulls = None
    for fid, ft in r.fields():
        if fid == 1:    # max (legacy)
            mx = mx or r.read_bytes()
        elif fid == 2:  # min (legacy)
            mn = mn or r.read_bytes()
        elif fid == 3:
            nulls = r.zigzag()
        elif fid == 5:  # max_value
            mx = r.read_bytes()
        elif fid == 6:  # min_value
            mn = r.read_bytes()
        else:
            r.skip(ft)
    return mn, mx, nulls


def _read_column_meta(r: _TReader) -> ColumnMeta:
    m = ColumnMeta()
    for fid, ft in r.fields():
        if fid == 1:
            m.ptype = r.zigzag()
        elif fid == 2:
            n, _ = r.list_header()
            m.encodings = [r.zigzag() for _ in range(n)]
        elif fid == 3:
            n, _ = r.list_header()
            m.path = tuple(r.read_bytes().decode() for _ in range(n))
        elif fid == 4:
            m.codec = r.zigzag()
        elif fid == 5:
            m.num_values = r.zigzag()
        elif fid == 7:
            m.total_compressed_size = r.zigzag()
        elif fid == 9:
            m.data_page_offset = r.zigzag()
        elif fid == 11:
            m.dictionary_page_offset = r.zigzag()
        elif fid == 12:
            m.min_value, m.max_value, m.null_count = _read_statistics(r)
        else:
            r.skip(ft)
    return m


def _read_column_chunk(r: _TReader) -> ColumnMeta:
    meta = None
    for fid, ft in r.fields():
        if fid == 3:
            meta = _read_column_meta(r)
        else:
            r.skip(ft)
    if meta is None:
        raise ValueError("column chunk without metadata")
    return meta


def _read_row_group(r: _TReader) -> RowGroup:
    cols: List[ColumnMeta] = []
    rows = 0
    for fid, ft in r.fields():
        if fid == 1:
            n, _ = r.list_header()
            cols = [_read_column_chunk(r) for _ in range(n)]
        elif fid == 3:
            rows = r.zigzag()
        else:
            r.skip(ft)
    return RowGroup(cols, rows)


@dataclasses.dataclass
class FileMeta:
    schema: List[SchemaElement]
    num_rows: int
    row_groups: List[RowGroup]


def _read_file_meta(buf: bytes) -> FileMeta:
    r = _TReader(buf)
    schema: List[SchemaElement] = []
    num_rows = 0
    groups: List[RowGroup] = []
    for fid, ft in r.fields():
        if fid == 2:
            n, _ = r.list_header()
            schema = [_read_schema_element(r) for _ in range(n)]
        elif fid == 3:
            num_rows = r.zigzag()
        elif fid == 4:
            n, _ = r.list_header()
            groups = [_read_row_group(r) for _ in range(n)]
        else:
            r.skip(ft)
    return FileMeta(schema, num_rows, groups)


# ---------------------------------------------------------------------------
# codecs
# ---------------------------------------------------------------------------

def snappy_decompress(data: bytes) -> bytes:
    """Raw-snappy decoder (format: varint uncompressed length, then
    literal/copy tagged elements)."""
    pos = 0
    n = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    out = bytearray(n)
    opos = 0
    ln = len(data)
    while pos < ln:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:                       # literal
            size = (tag >> 2) + 1
            if size > 60:
                nb = size - 60
                size = int.from_bytes(data[pos:pos + nb], "little") + 1
                pos += nb
            out[opos:opos + size] = data[pos:pos + size]
            pos += size
            opos += size
            continue
        if kind == 1:                       # copy, 1-byte offset
            size = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:                     # copy, 2-byte offset
            size = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 2], "little")
            pos += 2
        else:                               # copy, 4-byte offset
            size = (tag >> 2) + 1
            offset = int.from_bytes(data[pos:pos + 4], "little")
            pos += 4
        if offset == 0:
            raise ValueError("corrupt snappy stream: zero offset")
        start = opos - offset
        if offset >= size:
            out[opos:opos + size] = out[start:start + size]
        else:  # overlapping copy: byte-at-a-time semantics
            for i in range(size):
                out[opos + i] = out[start + i]
        opos += size
    return bytes(out)


def _decompress(codec: int, data: bytes, uncompressed_size: int) -> bytes:
    if codec == C_UNCOMPRESSED:
        return data
    if codec == C_SNAPPY:
        return snappy_decompress(data)
    if codec == C_GZIP:
        return gzip.decompress(data)
    if codec == C_ZSTD:
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            data, max_output_size=uncompressed_size)
    raise NotImplementedError(f"parquet codec {codec} not supported")


# ---------------------------------------------------------------------------
# page decoding
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class PageHeader:
    page_type: int = 0
    uncompressed_size: int = 0
    compressed_size: int = 0
    num_values: int = 0
    encoding: int = E_PLAIN
    def_encoding: int = E_RLE
    # v2 extras
    num_nulls: int = 0
    num_rows: int = 0
    def_levels_len: int = 0
    rep_levels_len: int = 0
    v2_is_compressed: bool = True


def _read_page_header(r: _TReader) -> PageHeader:
    h = PageHeader()
    for fid, ft in r.fields():
        if fid == 1:
            h.page_type = r.zigzag()
        elif fid == 2:
            h.uncompressed_size = r.zigzag()
        elif fid == 3:
            h.compressed_size = r.zigzag()
        elif fid == 5:  # data_page_header
            for f2, t2 in r.fields():
                if f2 == 1:
                    h.num_values = r.zigzag()
                elif f2 == 2:
                    h.encoding = r.zigzag()
                elif f2 == 3:
                    h.def_encoding = r.zigzag()
                else:
                    r.skip(t2)
        elif fid == 7:  # dictionary_page_header
            for f2, t2 in r.fields():
                if f2 == 1:
                    h.num_values = r.zigzag()
                elif f2 == 2:
                    h.encoding = r.zigzag()
                else:
                    r.skip(t2)
        elif fid == 8:  # data_page_header_v2
            for f2, t2 in r.fields():
                if f2 == 1:
                    h.num_values = r.zigzag()
                elif f2 == 2:
                    h.num_nulls = r.zigzag()
                elif f2 == 3:
                    h.num_rows = r.zigzag()
                elif f2 == 4:
                    h.encoding = r.zigzag()
                elif f2 == 5:
                    h.def_levels_len = r.zigzag()
                elif f2 == 6:
                    h.rep_levels_len = r.zigzag()
                elif f2 == 7:
                    h.v2_is_compressed = r.bool_value(t2)
                else:
                    r.skip(t2)
        else:
            r.skip(ft)
    return h


def _decode_rle_bitpacked(data: bytes, bit_width: int, count: int,
                          length_prefixed: bool) -> np.ndarray:
    """RLE/bit-packed hybrid (def levels and dictionary indices)."""
    pos = 0
    if length_prefixed:
        pos = 4  # i32 length; trust `count` for the payload extent
    out = np.empty(count, dtype=np.int32)
    filled = 0
    if bit_width == 0:
        out[:] = 0
        return out
    mask = (1 << bit_width) - 1
    byte_width = (bit_width + 7) // 8
    while filled < count:
        header = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            header |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        if header & 1:   # bit-packed run: (header >> 1) groups of 8 values
            n_groups = header >> 1
            n_vals = n_groups * 8
            n_bytes = n_groups * bit_width
            chunk = np.frombuffer(data[pos:pos + n_bytes], dtype=np.uint8)
            pos += n_bytes
            bits = np.unpackbits(chunk, bitorder="little")
            vals = bits.reshape(-1, bit_width)
            weights = (1 << np.arange(bit_width, dtype=np.int64))
            decoded = (vals.astype(np.int64) * weights).sum(axis=1)
            take = min(n_vals, count - filled)
            out[filled:filled + take] = decoded[:take]
            filled += take
        else:            # RLE run
            run_len = header >> 1
            v = int.from_bytes(data[pos:pos + byte_width], "little") & mask
            pos += byte_width
            take = min(run_len, count - filled)
            out[filled:filled + take] = v
            filled += take
    return out


def _decode_plain(ptype: int, data: bytes, count: int, type_length: int
                  ) -> np.ndarray:
    if ptype == T_INT32:
        return np.frombuffer(data, dtype="<i4", count=count)
    if ptype == T_INT64:
        return np.frombuffer(data, dtype="<i8", count=count)
    if ptype == T_FLOAT:
        return np.frombuffer(data, dtype="<f4", count=count)
    if ptype == T_DOUBLE:
        return np.frombuffer(data, dtype="<f8", count=count)
    if ptype == T_BOOLEAN:
        bits = np.frombuffer(data, dtype=np.uint8,
                             count=(count + 7) // 8)
        return np.unpackbits(bits, bitorder="little")[:count].astype(bool)
    if ptype == T_FLBA:
        return _decode_flba_decimal(data, count, type_length)
    if ptype == T_BYTE_ARRAY:
        return _decode_byte_array(data, count)
    raise NotImplementedError(f"parquet physical type {ptype}")


def _decode_byte_array(data: bytes, count: int) -> np.ndarray:
    """PLAIN byte_array: (u32 length, bytes)* -> object array of str."""
    lens = np.empty(count, dtype=np.int64)
    offs = np.empty(count, dtype=np.int64)
    pos = 0
    for i in range(count):
        n = int.from_bytes(data[pos:pos + 4], "little")
        pos += 4
        offs[i] = pos
        lens[i] = n
        pos += n
    out = np.empty(count, dtype=object)
    for i in range(count):
        o = int(offs[i])
        out[i] = data[o:o + int(lens[i])].decode("utf-8", "replace")
    return out


def _decode_flba_decimal(data: bytes, count: int, type_length: int
                         ) -> np.ndarray:
    """Fixed-len big-endian two's-complement decimal -> int64 unscaled."""
    if type_length > 8:
        # high bytes must be pure sign extension for precision <= 18
        arr = np.frombuffer(data, dtype=np.uint8,
                            count=count * type_length).reshape(count, -1)
        head = arr[:, : type_length - 8]
        sign = (arr[:, type_length - 8] & 0x80) != 0
        expect = np.where(sign, 0xFF, 0x00)
        if not np.array_equal(head, np.broadcast_to(
                expect[:, None], head.shape)):
            raise OverflowError("decimal wider than 64 bits")
        arr = arr[:, -8:]
        type_length = 8
    else:
        arr = np.frombuffer(data, dtype=np.uint8,
                            count=count * type_length).reshape(count, -1)
    out = np.zeros(count, dtype=np.int64)
    for b in range(type_length):
        out = (out << 8) | arr[:, b].astype(np.int64)
    # sign-extend from type_length bytes
    bits = 8 * type_length
    if bits < 64:
        sign_bit = np.int64(1) << (bits - 1)
        out = (out ^ sign_bit) - sign_bit
    return out


class ParquetColumnReader:
    """Decodes one column chunk of one row group into a numpy array."""

    def __init__(self, f, meta: ColumnMeta, elem: SchemaElement,
                 num_rows: int):
        self.f = f
        self.meta = meta
        self.elem = elem
        self.num_rows = num_rows
        self._dict_values: Optional[np.ndarray] = None

    def _read_at(self, offset: int, size: int) -> bytes:
        self.f.seek(offset)
        return self.f.read(size)

    def read(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """-> (values ndarray, null mask or None), length num_rows."""
        meta = self.meta
        start = meta.data_page_offset
        if meta.dictionary_page_offset is not None and \
                0 < meta.dictionary_page_offset < start:
            start = meta.dictionary_page_offset
        buf = self._read_at(start, meta.total_compressed_size)
        pos = 0
        vals_parts: List[np.ndarray] = []
        null_parts: List[np.ndarray] = []
        got = 0
        while got < meta.num_values and pos < len(buf):
            r = _TReader(buf, pos)
            h = _read_page_header(r)
            body = buf[r.pos:r.pos + h.compressed_size]
            pos = r.pos + h.compressed_size
            if h.page_type == PT_DICTIONARY:
                raw = _decompress(meta.codec, body, h.uncompressed_size)
                self._dict_values = _decode_plain(
                    meta.ptype, raw, h.num_values, self.elem.type_length)
                continue
            if h.page_type == PT_DATA:
                vals, nulls, n = self._decode_data_v1(h, body)
            elif h.page_type == PT_DATA_V2:
                vals, nulls, n = self._decode_data_v2(h, body)
            else:
                continue  # index pages etc.
            vals_parts.append(vals)
            null_parts.append(nulls)
            got += n
        if not vals_parts:
            return _empty_for(meta.ptype), None
        values = np.concatenate(vals_parts) if len(vals_parts) != 1 else \
            vals_parts[0]
        if any(n is not None for n in null_parts):
            nulls = np.concatenate([
                n if n is not None else np.zeros(len(v), dtype=bool)
                for n, v in zip(null_parts, vals_parts)])
        else:
            nulls = None
        return values, nulls

    # -- page bodies --------------------------------------------------------

    def _max_def(self) -> int:
        return 1 if self.elem.repetition == 1 else 0

    def _decode_values(self, encoding: int, raw: bytes, n_present: int
                       ) -> np.ndarray:
        if encoding in (E_RLE_DICTIONARY, E_PLAIN_DICTIONARY):
            if self._dict_values is None:
                raise ValueError("dictionary-encoded page without dictionary")
            bw = raw[0]
            idx = _decode_rle_bitpacked(raw[1:], bw, n_present,
                                        length_prefixed=False)
            return self._dict_values[idx]
        if encoding == E_PLAIN:
            return _decode_plain(self.meta.ptype, raw, n_present,
                                 self.elem.type_length)
        if encoding == E_RLE and self.meta.ptype == T_BOOLEAN:
            # RLE-encoded booleans (bit width 1, 4-byte length prefix)
            return _decode_rle_bitpacked(raw, 1, n_present,
                                         length_prefixed=True).astype(bool)
        raise NotImplementedError(f"parquet value encoding {encoding}")

    def _scatter(self, present_vals: np.ndarray, defs: Optional[np.ndarray],
                 n: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if defs is None:
            return present_vals, None
        nulls = defs == 0
        if not nulls.any():
            return present_vals, None
        if present_vals.dtype == object:
            out = np.full(n, None, dtype=object)
        else:
            out = np.zeros(n, dtype=present_vals.dtype)
        out[~nulls] = present_vals
        return out, nulls

    def _decode_data_v1(self, h: PageHeader, body: bytes):
        raw = _decompress(self.meta.codec, body, h.uncompressed_size)
        n = h.num_values
        defs = None
        pos = 0
        if self._max_def() == 1:
            length = int.from_bytes(raw[0:4], "little")
            defs = _decode_rle_bitpacked(raw, 1, n, length_prefixed=True)
            pos = 4 + length
        n_present = n if defs is None else int((defs != 0).sum())
        vals = self._decode_values(h.encoding, raw[pos:], n_present)
        vals, nulls = self._scatter(vals, defs, n)
        return vals, nulls, n

    def _decode_data_v2(self, h: PageHeader, body: bytes):
        n = h.num_values
        pos = h.rep_levels_len + h.def_levels_len
        defs = None
        if self._max_def() == 1 and h.def_levels_len > 0:
            defs = _decode_rle_bitpacked(
                body[h.rep_levels_len:pos], 1, n, length_prefixed=False)
        raw = body[pos:]
        if h.v2_is_compressed:
            raw = _decompress(self.meta.codec, raw,
                              h.uncompressed_size - pos)
        n_present = n - h.num_nulls
        vals = self._decode_values(h.encoding, raw, n_present)
        vals, nulls = self._scatter(vals, defs, n)
        return vals, nulls, n


# ---------------------------------------------------------------------------
# file-level API
# ---------------------------------------------------------------------------

def _engine_type(elem: SchemaElement) -> Type:
    ct = elem.converted_type
    if elem.ptype == T_BOOLEAN:
        return BOOLEAN
    if elem.ptype == T_INT32:
        if ct == CT_DATE:
            return DATE
        return SMALLINT if ct == CT_INT_16 else INTEGER
    if elem.ptype == T_INT64:
        if ct == CT_DECIMAL:
            return DecimalType(elem.precision, elem.scale)
        return TIMESTAMP if ct == CT_TIMESTAMP_MILLIS else BIGINT
    if elem.ptype == T_FLOAT:
        return REAL
    if elem.ptype == T_DOUBLE:
        return DOUBLE
    if elem.ptype == T_FLBA and ct == CT_DECIMAL:
        return DecimalType(elem.precision, elem.scale)
    if elem.ptype == T_BYTE_ARRAY:
        return VARCHAR
    raise NotImplementedError(
        f"parquet column {elem.name}: type {elem.ptype}/{ct} not supported")


class ParquetFile:
    """One parquet file: schema + row-group readers."""

    def __init__(self, path: str):
        self.path = path
        self.f = open(path, "rb")
        try:
            size = os.fstat(self.f.fileno()).st_size
            self.f.seek(size - 8)
            tail = self.f.read(8)
            if tail[4:] != MAGIC:
                raise ValueError(f"{path}: not a parquet file")
            meta_len = struct.unpack("<I", tail[:4])[0]
            self.f.seek(size - 8 - meta_len)
            self.meta = _read_file_meta(self.f.read(meta_len))
            root, rest = self.meta.schema[0], self.meta.schema[1:]
            if sum(1 for e in rest if e.num_children) > 0:
                raise NotImplementedError(
                    "nested parquet schemas not supported")
            if any(e.repetition == 2 for e in rest):
                raise NotImplementedError(
                    "repeated parquet fields not supported")
        except BaseException:
            self.f.close()
            raise
        self.columns = rest
        self.num_rows = self.meta.num_rows

    @property
    def schema(self) -> List[Tuple[str, Type]]:
        return [(e.name, _engine_type(e)) for e in self.columns]

    @property
    def n_row_groups(self) -> int:
        return len(self.meta.row_groups)

    def row_group_rows(self, g: int) -> int:
        return self.meta.row_groups[g].num_rows

    def row_group_stats(self, g: int, column: str
                        ) -> Optional[Tuple[Any, Any]]:
        """(min, max) decoded to engine-value space, or None."""
        rg = self.meta.row_groups[g]
        for cm, e in zip(rg.columns, self.columns):
            if e.name != column:
                continue
            if cm.min_value is None or cm.max_value is None:
                return None
            return (_decode_stat(e, cm.min_value),
                    _decode_stat(e, cm.max_value))
        return None

    def column_distinct_strings(self, name: str) -> Optional[List[str]]:
        """Distinct values of a byte_array column WITHOUT decoding data pages:
        walks page headers, decodes only dictionary pages. Returns None when
        any data page is not dictionary-encoded (caller falls back to a full
        read) — parquet writers fall back to PLAIN when a dictionary page
        overflows, so this is exactly the cheap case."""
        out: List[str] = []
        seen = set()
        for rg in self.meta.row_groups:
            for cm, e in zip(rg.columns, self.columns):
                if e.name != name:
                    continue
                if cm.ptype != T_BYTE_ARRAY:
                    return None
                start = cm.data_page_offset
                if cm.dictionary_page_offset is not None and \
                        0 < cm.dictionary_page_offset < start:
                    start = cm.dictionary_page_offset
                self.f.seek(start)
                buf = self.f.read(cm.total_compressed_size)
                pos = 0
                got = 0
                while got < cm.num_values and pos < len(buf):
                    r = _TReader(buf, pos)
                    h = _read_page_header(r)
                    body = buf[r.pos:r.pos + h.compressed_size]
                    pos = r.pos + h.compressed_size
                    if h.page_type == PT_DICTIONARY:
                        raw = _decompress(cm.codec, body, h.uncompressed_size)
                        for v in _decode_byte_array(raw, h.num_values):
                            if v not in seen:
                                seen.add(v)
                                out.append(v)
                    elif h.page_type in (PT_DATA, PT_DATA_V2):
                        if h.encoding not in (E_RLE_DICTIONARY,
                                              E_PLAIN_DICTIONARY):
                            return None
                        got += h.num_values
        return out

    def read_row_group(self, g: int, columns: Sequence[str]
                       ) -> Dict[str, Tuple[np.ndarray, Optional[np.ndarray]]]:
        rg = self.meta.row_groups[g]
        out = {}
        by_name = {e.name: (cm, e) for cm, e in zip(rg.columns, self.columns)}
        for name in columns:
            if name not in by_name:
                raise KeyError(f"{self.path}: no column {name}")
            cm, e = by_name[name]
            reader = ParquetColumnReader(self.f, cm, e, rg.num_rows)
            out[name] = reader.read()
        return out

    def close(self):
        self.f.close()


def _empty_for(ptype: int) -> np.ndarray:
    dt = {T_BOOLEAN: np.bool_, T_INT32: np.int32, T_INT64: np.int64,
          T_FLOAT: np.float32, T_DOUBLE: np.float64}.get(ptype, object)
    return np.empty(0, dtype=dt)


def _decode_stat(elem: SchemaElement, raw: bytes):
    if elem.ptype == T_INT32:
        return struct.unpack("<i", raw)[0]
    if elem.ptype == T_INT64:
        return struct.unpack("<q", raw)[0]
    if elem.ptype == T_FLOAT:
        return struct.unpack("<f", raw)[0]
    if elem.ptype == T_DOUBLE:
        return struct.unpack("<d", raw)[0]
    if elem.ptype == T_BYTE_ARRAY:
        return raw.decode("utf-8", "replace")
    if elem.ptype == T_FLBA:
        return int(_decode_flba_decimal(raw, 1, len(raw))[0])
    return None
