"""RCFile reader/writer: Hive's Record Columnar format, from scratch.

Analogue of presto-rcfile (RcFileReader/RcFilePageSourceFactory with the
text SerDe). The on-disk layout follows Hive's RCFile.java:

    header:  "RCF" magic + version byte 1
             1 byte  compressed flag
             [Text codec class name]          (when compressed)
             SequenceFile.Metadata            (vint-count of Text k/v pairs;
                                               carries hive.io.rcfile.column.number)
             16-byte sync marker
    row group ("record"):
             int32 recordLen   (-1 => 16-byte sync follows, then real len)
             int32 keyLength
             int32 compressedKeyLength
             key buffer (compressed when codec set):
                 vint rowCount
                 per column: vint valueBytes (on-disk), vint uncompressedBytes,
                             vint keySectionLen, then keySectionLen bytes of
                             per-row cell lengths as RUN-LENGTH vints
                             (a negative vint -v means "previous length
                             repeats v MORE times")
             value buffer: per column, valueBytes bytes (per-column
                 compressed when codec set) — cells back to back.

Cells are the TEXT representation (ColumnarSerDe: numbers as ASCII,
dates ISO, `\\N` = NULL), decoded into typed columns. Compression
supports the DefaultCodec (zlib/deflate) and uncompressed files.
Hadoop vints follow WritableUtils.writeVLong.
"""
from __future__ import annotations

import struct
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types import (DecimalType, Type, is_string)

MAGIC = b"RCF"
VERSION = 1
DEFLATE_CODEC = "org.apache.hadoop.io.compress.DefaultCodec"
COLUMN_NUMBER_KEY = "hive.io.rcfile.column.number"
NULL_TEXT = b"\\N"
import re as _re

#: cells that need (had) NULL escaping: one or more backslashes then N
_SENTINEL_FAMILY = _re.compile(rb"\\+N")


# ------------------------------------------------------------- hadoop vints

def write_vlong(v: int) -> bytes:
    """WritableUtils.writeVLong."""
    if -112 <= v <= 127:
        return struct.pack("b", v)
    length = -112
    if v < 0:
        v = ~v
        length = -120
    tmp = v
    while tmp != 0:
        tmp >>= 8
        length -= 1
    out = struct.pack("b", length)
    length = -(length + 120) if length < -120 else -(length + 112)
    for idx in range(length - 1, -1, -1):
        out += bytes([(v >> (8 * idx)) & 0xFF])
    return out


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated rcfile")
        self.pos += n
        return b

    def read_vlong(self) -> int:
        first = struct.unpack("b", self.read(1))[0]
        if first >= -112:
            return first
        negative = first < -120
        length = -(first + 120) if negative else -(first + 112)
        v = 0
        for b in self.read(length):
            v = (v << 8) | b
        return ~v if negative else v

    def read_int(self) -> int:
        return struct.unpack(">i", self.read(4))[0]

    def read_text(self) -> str:
        n = self.read_vlong()
        return self.read(n).decode("utf-8")


# ------------------------------------------------------------------ writer

def write_rcfile(path: str, columns: Sequence[Sequence[Optional[str]]],
                 rows_per_group: int = 4096, compress: bool = True) -> None:
    """Write text-serde cell values (None = NULL) as an RCFile."""
    ncols = len(columns)
    nrows = len(columns[0]) if ncols else 0
    sync = bytes((7 * i + 13) % 256 for i in range(16))  # fixed, arbitrary

    def codec(data: bytes) -> bytes:
        return zlib.compress(data, 6) if compress else data

    out = bytearray()
    out += MAGIC + bytes([VERSION])
    out += bytes([1 if compress else 0])
    if compress:
        enc = DEFLATE_CODEC.encode()
        out += write_vlong(len(enc)) + enc
    # SequenceFile.Metadata: int32 count, then Text/Text pairs
    meta = {COLUMN_NUMBER_KEY: str(ncols)}
    out += struct.pack(">i", len(meta))
    for k, v in meta.items():
        ke, ve = k.encode(), v.encode()
        out += write_vlong(len(ke)) + ke + write_vlong(len(ve)) + ve
    out += sync

    for lo in range(0, max(nrows, 1), rows_per_group):
        hi = min(lo + rows_per_group, nrows)
        n = hi - lo
        if n <= 0 and nrows > 0:
            break
        col_cells = []
        for c in range(ncols):
            cells = []
            for v in columns[c][lo:hi]:
                if v is None:
                    cells.append(NULL_TEXT)
                else:
                    b = str(v).encode("utf-8")
                    # injective NULL escaping: any \...\N-shaped cell gains
                    # one leading backslash so the sentinel never collides
                    # with data (unescaping strips exactly one)
                    if _SENTINEL_FAMILY.fullmatch(b):
                        b = b"\\" + b
                    cells.append(b)
            col_cells.append(cells)
        key = bytearray(write_vlong(n))
        values = bytearray()
        for cells in col_cells:
            raw = b"".join(cells)
            disk = codec(raw)
            lengths = bytearray()
            prev, run = None, 0
            for cell in cells:
                ln = len(cell)
                if ln == prev:
                    run += 1
                else:
                    if run:
                        lengths += write_vlong(-run)
                    lengths += write_vlong(ln)
                    prev, run = ln, 0
            if run:
                lengths += write_vlong(-run)
            key += write_vlong(len(disk))
            key += write_vlong(len(raw))
            key += write_vlong(len(lengths))
            key += bytes(lengths)
            values += disk
        key_raw = bytes(key)
        key_disk = codec(key_raw)
        record_len = 4 + 4 + len(key_disk) + len(values)
        out += struct.pack(">i", -1) + sync
        out += struct.pack(">i", record_len)
        out += struct.pack(">i", len(key_raw))
        out += struct.pack(">i", len(key_disk))
        out += key_disk + values
        if nrows == 0:
            break
    with open(path, "wb") as f:
        f.write(bytes(out))


# ------------------------------------------------------------------ reader

class RcFile:
    """Row groups of text-serde cells; column-pruned, typed decoding."""

    def __init__(self, path: str):
        import mmap

        self.path = path
        # mmap, not read(): the index walk touches only record headers and
        # key buffers; value bytes page in lazily when a scan reads them
        self._file = open(path, "rb")
        try:
            self._buf = mmap.mmap(self._file.fileno(), 0,
                                  access=mmap.ACCESS_READ)
        except ValueError:  # empty file
            self._buf = b""
        cur = _Cursor(self._buf)
        if cur.read(3) != MAGIC:
            raise ValueError(f"{path}: not an RCFile (bad magic)")
        version = cur.read(1)[0]
        if version > 1:
            raise ValueError(f"{path}: unsupported RCFile version {version}")
        self.compressed = cur.read(1)[0] == 1
        if self.compressed:
            codec = cur.read_text()
            if codec != DEFLATE_CODEC:
                raise ValueError(f"{path}: unsupported codec {codec} "
                                 f"(DefaultCodec/deflate only)")
        self.metadata: Dict[str, str] = {}
        for _ in range(cur.read_int()):
            k = cur.read_text()
            v = cur.read_text()
            self.metadata[k] = v
        self.n_columns = int(self.metadata.get(COLUMN_NUMBER_KEY, "0"))
        self.sync = cur.read(16)
        # index the row groups once (offsets + row counts)
        self._groups: List[Tuple[int, int]] = []  # (offset of recordLen, rows)
        self.num_rows = 0
        pos = cur.pos
        while pos < len(self._buf):
            cur.pos = pos
            rec = cur.read_int()
            if rec == -1:
                if cur.read(16) != self.sync:
                    raise ValueError(f"{path}: bad sync marker")
                pos = cur.pos
                continue
            start = cur.pos - 4
            cur.read_int()  # keyLength (uncompressed)
            klen_disk = cur.read_int()
            key = self._decode(cur.read(klen_disk))
            kc = _Cursor(key)
            rows = kc.read_vlong()
            self._groups.append((start, rows))
            self.num_rows += rows
            pos = start + 4 + rec  # recordLen covers key hdr + key + values
        self.n_groups = len(self._groups)

    def _decode(self, data: bytes) -> bytes:
        return zlib.decompress(data) if self.compressed else data

    def group_rows(self, g: int) -> int:
        return self._groups[g][1]

    def read_group(self, g: int, wanted: Sequence[int]
                   ) -> Dict[int, List[Optional[bytes]]]:
        """-> {column index: list of raw cell bytes (None = NULL)} — only
        `wanted` columns are decompressed (RCFile's lazy column skip)."""
        start, rows = self._groups[g]
        cur = _Cursor(self._buf, start)
        cur.read_int()  # recordLen
        cur.read_int()  # keyLength
        klen_disk = cur.read_int()
        key = self._decode(cur.read(klen_disk))
        kc = _Cursor(key)
        n = kc.read_vlong()
        assert n == rows
        cols_meta = []
        for _c in range(self.n_columns):
            disk_len = kc.read_vlong()
            raw_len = kc.read_vlong()
            sect_len = kc.read_vlong()
            sect = _Cursor(kc.read(sect_len))
            lengths: List[int] = []
            while len(lengths) < rows:
                v = sect.read_vlong()
                if v < 0:
                    lengths.extend([lengths[-1]] * (-v))
                else:
                    lengths.append(v)
            cols_meta.append((disk_len, raw_len, lengths))
        want = set(wanted)
        out: Dict[int, List[Optional[bytes]]] = {}
        vpos = cur.pos
        for c, (disk_len, _raw_len, lengths) in enumerate(cols_meta):
            if c in want:
                raw = self._decode(self._buf[vpos:vpos + disk_len])
                cells: List[Optional[bytes]] = []
                o = 0
                for ln in lengths:
                    cell = raw[o:o + ln]
                    o += ln
                    if cell == NULL_TEXT:
                        cells.append(None)
                    elif _SENTINEL_FAMILY.fullmatch(cell):
                        cells.append(cell[1:])  # strip the escape backslash
                    else:
                        cells.append(cell)
                out[c] = cells
            vpos += disk_len
        return out


_OPEN_CACHE: Dict[tuple, "RcFile"] = {}
_OPEN_LOCK = __import__("threading").Lock()


def open_rcfile(path: str) -> "RcFile":
    """Signature-cached open: the connector constructs a reader per split
    and RcFile.__init__ walks + key-decompresses the group index — the
    cache makes a G-group scan index once, not G+1 times. Buffers are
    mmap-backed (page cache, not heap), so cached entries pin only the
    index, and construction happens OUTSIDE the lock."""
    import os

    st = os.stat(path)
    key = (path, st.st_mtime, st.st_size)
    with _OPEN_LOCK:
        f = _OPEN_CACHE.get(key)
    if f is not None:
        return f
    f = RcFile(path)
    with _OPEN_LOCK:
        cur = _OPEN_CACHE.get(key)
        if cur is not None:
            return cur
        stale = [k for k in _OPEN_CACHE if k[0] == path]
        for k in stale:
            del _OPEN_CACHE[k]
        while len(_OPEN_CACHE) > 16:
            del _OPEN_CACHE[next(iter(_OPEN_CACHE))]
        _OPEN_CACHE[key] = f
    return f


class RcTableFile:
    """File-connector adapter (_ExternalFile shape): one chunk per row
    group. The text serde carries NO types, so a sidecar ``<path>.schema``
    JSON (``{"columns": [[name, type_tag, scale], ...]}``) plays the hive
    metastore's role; ``write_rcfile_table`` emits both."""

    def __init__(self, path: str):
        import json

        from .pcol import _type_from_tag

        self.path = path
        self._f = open_rcfile(path)
        with open(path + ".schema") as f:
            doc = json.load(f)
        self.schema = [(n, _type_from_tag(tag, scale))
                       for n, tag, scale in doc["columns"]]
        if len(self.schema) != self._f.n_columns:
            raise ValueError(
                f"{path}: sidecar schema has {len(self.schema)} columns, "
                f"file has {self._f.n_columns}")
        self.num_rows = self._f.num_rows
        self.n_chunks = self._f.n_groups

    def chunk_rows(self, g: int) -> int:
        return self._f.group_rows(g)

    def chunk_stats(self, g: int, col: str):
        return None  # text cells carry no statistics

    def read_chunk(self, g: int, names: Sequence[str]):
        index = {n: i for i, (n, _t) in enumerate(self.schema)}
        wanted = [index[n] for n in names]
        raw = self._f.read_group(g, wanted)
        out = {}
        for n in names:
            i = index[n]
            out[n] = decode_cells(raw[i], self.schema[i][1])
        return out

    def column_distinct_strings(self, name: str):
        return None  # no dictionary pages: the loader decodes the column

    def close(self):
        pass


def write_rcfile_table(path: str, names: Sequence[str],
                       types: Sequence[Type],
                       columns: Sequence[Sequence[Optional[str]]],
                       rows_per_group: int = 4096,
                       compress: bool = True) -> None:
    """RCFile + the sidecar schema the engine's reader needs."""
    import json

    from .pcol import _type_tag

    write_rcfile(path, columns, rows_per_group, compress)
    with open(path + ".schema", "w") as f:
        json.dump({"columns": [[n, *_type_tag(t)]
                               for n, t in zip(names, types)]}, f)


def decode_cells(cells: Sequence[Optional[bytes]], t: Type
                 ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Text cells -> (typed values, null mask). String columns return a
    dtype=object array of str (the caller dictionary-encodes)."""
    n = len(cells)
    nulls = np.fromiter((c is None for c in cells), dtype=np.bool_, count=n)
    if not nulls.any():
        nulls = None
    if is_string(t):
        vals = np.array(["" if c is None else c.decode("utf-8")
                         for c in cells], dtype=object)
        return vals, nulls
    arr = np.zeros(n, dtype=t.np_dtype)
    for i, c in enumerate(cells):
        if c is None:
            continue
        s = c.decode("ascii")
        if isinstance(t, DecimalType):
            from decimal import Decimal
            arr[i] = int(Decimal(s).scaleb(t.scale))
        elif t.name == "date":
            import datetime
            d = datetime.date.fromisoformat(s)
            arr[i] = (d - datetime.date(1970, 1, 1)).days
        elif t.name == "timestamp":
            import datetime
            dt = datetime.datetime.fromisoformat(s)
            arr[i] = int((dt - datetime.datetime(1970, 1, 1)
                          ).total_seconds() * 1000)
        elif t.name == "boolean":
            arr[i] = s.lower() in ("true", "1")
        elif t.name in ("double", "real"):
            arr[i] = float(s)
        else:
            arr[i] = int(s)
    return arr, nulls
