"""ORC writer: the write side of formats/orc.py, from scratch.

Analogue of the reference's OrcWriter (presto-orc/src/main/java/com/facebook/
presto/orc/OrcWriter.java:76 — stripe accumulation, per-column stream
encoders, footer/postscript emission). NOT a pyarrow wrapper: pyarrow appears
only in tests, verifying the files interoperate with liborc.

Covers the reader's feature set (formats/orc.py) so hive/raptor CTAS into ORC
round-trips through the engine's own reader:
- protobuf wire-format writer for PostScript / Footer / Metadata /
  StripeFooter;
- ZLIB (raw deflate) chunk framing, or NONE;
- integer RLEv2 (DIRECT runs, zigzag for signed), byte RLE, boolean bit RLE;
- column types: boolean, short/int/long (DIRECT_V2), float, double, date,
  decimal(<=18) (varint mantissa + SECONDARY scale stream), varchar as
  DICTIONARY_V2 (sorted dictionary, as the hive writer emits) or DIRECT_V2
  for dictionary-less object columns;
- PRESENT streams for nullable columns;
- stripe-level and file-level IntegerStatistics / DoubleStatistics, so the
  connectors' stripe pruning (OrcPredicate analogue) works on files the
  engine wrote itself.

Types map exactly as the reader maps them back: BIGINT->long,
INTEGER->int, SMALLINT->short, DOUBLE->double, REAL->float,
BOOLEAN->boolean, DATE->date, DECIMAL(p<=18,s)->decimal, VARCHAR->string.
"""
from __future__ import annotations

import struct
import zlib
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Dictionary, Page
from ..types import (BOOLEAN, DOUBLE, REAL, DecimalType, Type, is_string)
from .orc import (E_DICTIONARY_V2, E_DIRECT, E_DIRECT_V2, K_NONE, K_ZLIB,
                  MAGIC, S_DATA, S_DICT_DATA, S_LENGTH, S_PRESENT,
                  S_SECONDARY, T_BOOLEAN, T_DATE, T_DECIMAL, T_DOUBLE,
                  T_FLOAT, T_INT, T_LONG, T_SHORT, T_STRING, T_STRUCT,
                  _WIDTH_TABLE, _closest_fixed_bits)

_STRIPE_ROWS = 1 << 20       # rows per stripe
_BLOCK_SIZE = 256 * 1024     # compression chunk size


# ---------------------------------------------------------------------------
# protobuf wire writer (mirror of orc._PBReader)
# ---------------------------------------------------------------------------

class _PBWriter:
    __slots__ = ("out",)

    def __init__(self):
        self.out = bytearray()

    def varint(self, v: int) -> None:
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                self.out.append(b | 0x80)
            else:
                self.out.append(b)
                return

    def field_varint(self, fid: int, v: int) -> None:
        self.varint(fid << 3 | 0)
        self.varint(v)

    def field_svarint(self, fid: int, v: int) -> None:
        """sint64: zigzag varint."""
        self.field_varint(fid, (v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def field_double(self, fid: int, v: float) -> None:
        self.varint(fid << 3 | 1)
        self.out += struct.pack("<d", v)

    def field_bytes(self, fid: int, data: bytes) -> None:
        self.varint(fid << 3 | 2)
        self.varint(len(data))
        self.out += data

    def field_message(self, fid: int, msg: "_PBWriter") -> None:
        self.field_bytes(fid, bytes(msg.out))

    def bytes(self) -> bytes:
        return bytes(self.out)


# ---------------------------------------------------------------------------
# compression framing
# ---------------------------------------------------------------------------

def compress_stream(codec: int, data: bytes) -> bytes:
    """Apply ORC chunk framing: 3-byte headers (len << 1 | is_original)."""
    if codec == K_NONE:
        return data
    out = bytearray()
    for pos in range(0, len(data), _BLOCK_SIZE):
        chunk = data[pos:pos + _BLOCK_SIZE]
        if codec == K_ZLIB:
            comp = zlib.compress(chunk, 6)[2:-4]  # raw deflate
        else:
            raise NotImplementedError(f"orc write codec {codec}")
        if len(comp) < len(chunk):
            header = len(comp) << 1
            out += header.to_bytes(3, "little") + comp
        else:
            header = len(chunk) << 1 | 1
            out += header.to_bytes(3, "little") + chunk
    return bytes(out)


# ---------------------------------------------------------------------------
# run-length encoders
# ---------------------------------------------------------------------------

def encode_byte_rle(vals: np.ndarray) -> bytes:
    """Byte RLE: repeats of 3..130 as (run-3, byte); literals of 1..128 as
    (256-len, bytes)."""
    vals = np.ascontiguousarray(vals, dtype=np.uint8)
    n = len(vals)
    out = bytearray()
    # run boundaries: positions where the value changes
    if n == 0:
        return b""
    change = np.flatnonzero(np.diff(vals)) + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [n]])
    lit_start = None

    def flush_literals(upto: int) -> None:
        nonlocal lit_start
        if lit_start is None:
            return
        pos = lit_start
        while pos < upto:
            k = min(128, upto - pos)
            out.append(256 - k)
            out.extend(vals[pos:pos + k].tobytes())
            pos += k
        lit_start = None

    for s, e in zip(starts, ends):
        run = e - s
        if run >= 3:
            flush_literals(s)
            pos = s
            while pos < e:
                k = min(130, e - pos)
                if k < 3:  # tail too short for a repeat: literal
                    out.append(256 - k)
                    out += vals[pos:pos + k].tobytes()
                else:
                    out.append(k - 3)
                    out.append(int(vals[pos]))
                pos += k
        elif lit_start is None:
            lit_start = s
    flush_literals(n)
    return bytes(out)


def encode_bool_rle(bits: np.ndarray) -> bytes:
    """Boolean stream: bits MSB-first into bytes, then byte RLE."""
    raw = np.packbits(np.asarray(bits, dtype=bool), bitorder="big")
    return encode_byte_rle(raw)


def _pack_bits_be(vals: np.ndarray, width: int) -> bytes:
    """Pack values (uint64 bit patterns) big-endian at `width` bits."""
    v = np.ascontiguousarray(vals).astype(np.uint64, copy=False)
    shifts = np.arange(width - 1, -1, -1, dtype=np.uint64)
    bits = ((v[:, None] >> shifts) & np.uint64(1)).astype(np.uint8)
    return np.packbits(bits.ravel(), bitorder="big").tobytes()


def _width_code(width: int) -> int:
    return _WIDTH_TABLE.index(_closest_fixed_bits(max(width, 1)))


def encode_rlev2(vals: np.ndarray, signed: bool) -> bytes:
    """Integer RLEv2 as DIRECT runs of <=512 values, per-run bit width.

    DIRECT is the universally-decodable sub-format (the reader handles all
    four; the writer emits the one with vectorizable packing)."""
    vals = np.asarray(vals, dtype=np.int64)
    if signed:
        u = (vals << 1) ^ (vals >> 63)
    else:
        u = vals
    u = u.view(np.uint64) if u.dtype == np.int64 else u.astype(np.uint64)
    out = bytearray()
    for pos in range(0, len(vals), 512):
        run = u[pos:pos + 512]
        hi = int(run.max()) if len(run) else 0
        width = _closest_fixed_bits(max(hi.bit_length(), 1))
        code = _width_code(width)
        n1 = len(run) - 1
        out.append((1 << 6) | (code << 1) | (n1 >> 8))
        out.append(n1 & 0xFF)
        out += _pack_bits_be(run, width)
    return bytes(out)


def _encode_varint_stream(vals: np.ndarray) -> bytes:
    """Decimal mantissas: zigzag base-128 varints."""
    out = bytearray()
    for v in vals.astype(np.int64):
        v = int(v)
        z = (v << 1) ^ (v >> 63)
        while True:
            b = z & 0x7F
            z >>= 7
            if z:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
    return bytes(out)


# ---------------------------------------------------------------------------
# column stats
# ---------------------------------------------------------------------------

class _Stats:
    """Accumulates one column's min/max/hasNull over values it sees."""

    __slots__ = ("kind", "min", "max", "has_null", "count")

    def __init__(self, kind: int):
        self.kind = kind
        self.min: Optional[Any] = None
        self.max: Optional[Any] = None
        self.has_null = False
        self.count = 0

    def update(self, data: np.ndarray, nulls: Optional[np.ndarray]) -> None:
        if nulls is not None and nulls.any():
            self.has_null = True
            data = data[~nulls]
        self.count += len(data)
        if len(data) == 0 or self.kind not in (
                T_SHORT, T_INT, T_LONG, T_DATE, T_FLOAT, T_DOUBLE):
            return
        lo, hi = data.min(), data.max()
        if self.kind in (T_FLOAT, T_DOUBLE):
            lo, hi = float(lo), float(hi)
        else:
            lo, hi = int(lo), int(hi)
        self.min = lo if self.min is None else min(self.min, lo)
        self.max = hi if self.max is None else max(self.max, hi)

    def merge(self, other: "_Stats") -> None:
        self.count += other.count
        self.has_null = self.has_null or other.has_null
        for v, pick in ((other.min, min), (other.max, max)):
            if v is None:
                continue
            if pick is min:
                self.min = v if self.min is None else min(self.min, v)
            else:
                self.max = v if self.max is None else max(self.max, v)

    def to_pb(self) -> _PBWriter:
        w = _PBWriter()
        w.field_varint(1, self.count)
        if self.min is not None:
            sub = _PBWriter()
            if self.kind in (T_FLOAT, T_DOUBLE):
                sub.field_double(1, self.min)
                sub.field_double(2, self.max)
                w.field_message(3, sub)
            else:
                sub.field_svarint(1, self.min)
                sub.field_svarint(2, self.max)
                w.field_message(2, sub)
        if self.has_null:
            w.field_varint(10, 1)
        return w


# ---------------------------------------------------------------------------
# per-column encoders
# ---------------------------------------------------------------------------

def _orc_kind(t: Type) -> int:
    if t is BOOLEAN or t.name == "boolean":
        return T_BOOLEAN
    if isinstance(t, DecimalType):
        return T_DECIMAL
    if t is DOUBLE or t.name == "double":
        return T_DOUBLE
    if t is REAL or t.name == "real":
        return T_FLOAT
    if is_string(t):
        return T_STRING
    if t.name == "date":
        return T_DATE
    if t.name == "smallint":
        return T_SHORT
    if t.name == "integer":
        return T_INT
    if t.name in ("bigint",):
        return T_LONG
    raise NotImplementedError(
        f"orc writer: type {t.name} not supported (mirrors the reader's "
        f"flat-schema scope — formats/orc.py rejects it too)")


def _encode_column(kind: int, col_id: int, data: np.ndarray,
                   nulls: Optional[np.ndarray],
                   dictionary: Optional[Dictionary],
                   t: Type) -> Tuple[List[Tuple[int, int, bytes]], int, int]:
    """-> ([(stream_kind, column, raw_bytes)], encoding, dict_size).

    `data` holds the non-null values compacted out already when nulls exist
    (the reader re-expands through PRESENT)."""
    streams: List[Tuple[int, int, bytes]] = []
    if nulls is not None and nulls.any():
        streams.append((S_PRESENT, col_id, encode_bool_rle(~nulls)))
        data = data[~nulls]
    enc = E_DIRECT
    dict_size = 0
    if kind == T_BOOLEAN:
        streams.append((S_DATA, col_id, encode_bool_rle(data.astype(bool))))
    elif kind in (T_SHORT, T_INT, T_LONG, T_DATE):
        enc = E_DIRECT_V2
        streams.append((S_DATA, col_id,
                        encode_rlev2(data.astype(np.int64), signed=True)))
    elif kind == T_FLOAT:
        streams.append((S_DATA, col_id,
                        np.ascontiguousarray(data, dtype="<f4").tobytes()))
    elif kind == T_DOUBLE:
        streams.append((S_DATA, col_id,
                        np.ascontiguousarray(data, dtype="<f8").tobytes()))
    elif kind == T_DECIMAL:
        # DIRECT_V2 so readers decode the SECONDARY scale stream as RLEv2
        enc = E_DIRECT_V2
        streams.append((S_DATA, col_id,
                        _encode_varint_stream(data.astype(np.int64))))
        scale = t.scale if isinstance(t, DecimalType) else 0
        streams.append((S_SECONDARY, col_id, encode_rlev2(
            np.full(len(data), scale, dtype=np.int64), signed=True)))
    elif kind == T_STRING:
        if dictionary is not None and hasattr(dictionary, "values"):
            # DICTIONARY_V2 with a SORTED dictionary (the hive writer's
            # layout); codes remap through the sort permutation
            enc = E_DICTIONARY_V2
            values = [str(v) for v in dictionary.values]
            order = np.argsort(np.asarray(values, dtype=object))
            remap = np.empty(len(values), dtype=np.int64)
            remap[order] = np.arange(len(values))
            svals = [values[i] for i in order]
            blobs = [s.encode("utf-8") for s in svals]
            codes = remap[np.clip(data.astype(np.int64), 0,
                                  max(len(values) - 1, 0))] \
                if len(values) else np.zeros(len(data), dtype=np.int64)
            dict_size = len(svals)
            streams.append((S_DATA, col_id,
                            encode_rlev2(codes, signed=False)))
            streams.append((S_DICT_DATA, col_id, b"".join(blobs)))
            streams.append((S_LENGTH, col_id, encode_rlev2(
                np.asarray([len(b) for b in blobs], dtype=np.int64),
                signed=False)))
        else:
            enc = E_DIRECT_V2
            blobs = [("" if v is None else str(v)).encode("utf-8")
                     for v in data]
            streams.append((S_DATA, col_id, b"".join(blobs)))
            streams.append((S_LENGTH, col_id, encode_rlev2(
                np.asarray([len(b) for b in blobs], dtype=np.int64),
                signed=False)))
    else:
        raise NotImplementedError(f"orc write kind {kind}")
    return streams, enc, dict_size


# ---------------------------------------------------------------------------
# file writer
# ---------------------------------------------------------------------------

def write_orc(path: str, names: Sequence[str], types: Sequence[Type],
              dicts: Sequence[Optional[Dictionary]],
              pages: Sequence[Page], codec: str = "zlib",
              stripe_rows: int = _STRIPE_ROWS) -> int:
    """Write pages (live rows compacted) as one ORC file; returns rows.
    Mirrors write_parquet / write_pcol's contract so the connectors' sinks
    can target any format."""
    codec_id = {"none": K_NONE, "zlib": K_ZLIB}[codec]
    ncols = len(names)
    from .pcol import compact_pages
    total, cols = compact_pages(names, types, pages)
    for c in range(ncols):
        if dicts[c] is not None and not hasattr(dicts[c], "values"):
            raise ValueError(
                f"column {names[c]}: virtual dictionaries cannot be "
                "persisted; decode before writing")
    kinds = [_orc_kind(t) for t in types]

    # column ids: 0 = root struct, 1..ncols = children
    file_stats = [_Stats(T_STRUCT)] + [_Stats(k) for k in kinds]
    stripe_stats_pb: List[_PBWriter] = []
    stripe_infos = []  # (offset, index_len, data_len, footer_len, rows)

    with open(path, "wb") as f:
        f.write(MAGIC)
        offset = len(MAGIC)
        for lo in range(0, total, stripe_rows):
            hi = min(lo + stripe_rows, total)
            n = hi - lo
            row_stats = [_Stats(T_STRUCT)] + [_Stats(k) for k in kinds]
            row_stats[0].count = n
            all_streams: List[Tuple[int, int, bytes]] = []
            encodings = [(E_DIRECT, 0)]  # root struct
            for c in range(ncols):
                data, nulls = cols[c]
                d = data[lo:hi]
                nl = None if nulls is None else nulls[lo:hi]
                row_stats[c + 1].update(d, nl)
                streams, enc, dsz = _encode_column(
                    kinds[c], c + 1, d, nl, dicts[c], types[c])
                all_streams.extend(streams)
                encodings.append((enc, dsz))
            # data region: streams ordered by (column, kind) like the reader
            # walks them (any fixed order works — lengths drive offsets)
            data_blobs = [(sk, col, compress_stream(codec_id, raw))
                          for (sk, col, raw) in all_streams]
            data_len = sum(len(b) for _, _, b in data_blobs)
            # stripe footer
            sf = _PBWriter()
            for sk, col, blob in data_blobs:
                st = _PBWriter()
                st.field_varint(1, sk)
                st.field_varint(2, col)
                st.field_varint(3, len(blob))
                sf.field_message(1, st)
            for enc, dsz in encodings:
                ce = _PBWriter()
                ce.field_varint(1, enc)
                if dsz:
                    ce.field_varint(2, dsz)
                sf.field_message(2, ce)
            footer_blob = compress_stream(codec_id, sf.bytes())
            for _, _, blob in data_blobs:
                f.write(blob)
            f.write(footer_blob)
            stripe_infos.append((offset, 0, data_len, len(footer_blob), n))
            offset += data_len + len(footer_blob)
            # roll stripe stats into file stats + metadata section
            ss = _PBWriter()
            for st_ in row_stats:
                ss.field_message(1, st_.to_pb())
            stripe_stats_pb.append(ss)
            for fs, rs in zip(file_stats, row_stats):
                fs.merge(rs)

        # metadata (stripe statistics)
        meta = _PBWriter()
        for ss in stripe_stats_pb:
            meta.field_message(1, ss)
        meta_blob = compress_stream(codec_id, meta.bytes())
        f.write(meta_blob)

        # footer
        ft = _PBWriter()
        ft.field_varint(1, len(MAGIC))          # headerLength
        ft.field_varint(2, offset)              # contentLength
        for (soff, ilen, dlen, flen, rows) in stripe_infos:
            si = _PBWriter()
            si.field_varint(1, soff)
            si.field_varint(2, ilen)
            si.field_varint(3, dlen)
            si.field_varint(4, flen)
            si.field_varint(5, rows)
            ft.field_message(3, si)
        root = _PBWriter()
        root.field_varint(1, T_STRUCT)
        for c in range(ncols):
            root.field_varint(2, c + 1)
        for c in range(ncols):
            root.field_bytes(3, names[c].encode("utf-8"))
        ft.field_message(4, root)
        for c in range(ncols):
            tp = _PBWriter()
            tp.field_varint(1, kinds[c])
            if kinds[c] == T_DECIMAL:
                t = types[c]
                tp.field_varint(5, t.precision)
                tp.field_varint(6, t.scale)
            ft.field_message(4, tp)
        ft.field_varint(6, total)               # numberOfRows
        for fs in file_stats:
            ft.field_message(7, fs.to_pb())
        ft.field_varint(8, 0)                   # rowIndexStride: no indexes
        footer_blob = compress_stream(codec_id, ft.bytes())
        f.write(footer_blob)

        # postscript (uncompressed by definition)
        ps = _PBWriter()
        ps.field_varint(1, len(footer_blob))
        ps.field_varint(2, codec_id)
        ps.field_varint(3, _BLOCK_SIZE)
        ver = _PBWriter()
        ver.varint(0)
        ver.varint(12)
        ps.field_bytes(4, ver.bytes())          # version [0,12] packed
        ps.field_varint(5, len(meta_blob))
        ps.field_varint(6, 1)                   # writerVersion
        ps.field_bytes(8000, MAGIC)
        ps_blob = ps.bytes()
        f.write(ps_blob)
        f.write(bytes([len(ps_blob)]))
    return total
