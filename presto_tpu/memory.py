"""Hierarchical memory accounting and pools.

Analogue of presto-memory-context (context/AggregatedMemoryContext.java, 669 LoC) and
presto-main memory/MemoryPool.java:43 + memory/ClusterMemoryManager.java:92.

On TPU the scarce resource is HBM, and XLA owns the allocator — so unlike the JVM
reference, accounting here is *advisory metadata driving scheduling decisions*
(admission, spill-to-host triggers, OOM-kill policies), not an allocator. The shape is
kept: operator-local contexts aggregate into task/query contexts which draw from a
per-chip pool (GENERAL/RESERVED), and a revocation scheduler asks operators to release
revocable bytes (execution/MemoryRevokingScheduler.java:46) by walking the spill ladder
device HBM -> host RAM -> disk (exec/spill.py writes PCOL runs, the
FileSingleStreamSpiller analogue). Disk bytes are tracked in a separate pool ledger
(`reserve_spill`/`spill_by_query`) so the true footprint stays visible while spilling
still *relieves* memory pressure rather than re-creating it on another axis.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional


class ExceededMemoryLimitException(RuntimeError):
    def __init__(self, what: str, limit: int):
        super().__init__(f"Query exceeded {what} memory limit of {limit} bytes")


class LocalMemoryContext:
    """Leaf context owned by one operator (context/SimpleLocalMemoryContext analogue)."""

    def __init__(self, parent: "AggregatedMemoryContext", tag: str = ""):
        self._parent = parent
        self._bytes = 0
        self.tag = tag

    def set_bytes(self, new_bytes: int) -> None:
        delta = new_bytes - self._bytes
        if delta:
            self._parent._update(delta)
            self._bytes = new_bytes

    def add_bytes(self, delta: int) -> None:
        self.set_bytes(self._bytes + delta)

    def get_bytes(self) -> int:
        return self._bytes

    def close(self) -> None:
        self.set_bytes(0)


class AggregatedMemoryContext:
    """Interior node aggregating children (context/AggregatedMemoryContext.java)."""

    def __init__(self, parent: Optional["AggregatedMemoryContext"] = None,
                 reservation_handler: Optional[Callable[[int, int], None]] = None):
        self._parent = parent
        self._bytes = 0
        self._handler = reservation_handler
        self._lock = threading.Lock()

    def _update(self, delta: int) -> None:
        with self._lock:
            self._bytes += delta
        if self._handler is not None:
            self._handler(delta, self._bytes)
        if self._parent is not None:
            self._parent._update(delta)

    def get_bytes(self) -> int:
        return self._bytes

    def new_local_memory_context(self, tag: str = "") -> LocalMemoryContext:
        return LocalMemoryContext(self, tag)

    def new_aggregated_memory_context(self) -> "AggregatedMemoryContext":
        return AggregatedMemoryContext(self)


class MemoryTrackingContext:
    """Bundle of user/revocable/system contexts carried by each operator context
    (presto-memory-context context/MemoryTrackingContext.java)."""

    def __init__(self, user: AggregatedMemoryContext, revocable: AggregatedMemoryContext,
                 system: AggregatedMemoryContext):
        self.user = user
        self.revocable = revocable
        self.system = system

    def fork(self) -> "MemoryTrackingContext":
        return MemoryTrackingContext(
            self.user.new_aggregated_memory_context(),
            self.revocable.new_aggregated_memory_context(),
            self.system.new_aggregated_memory_context())

    def total_bytes(self) -> int:
        return self.user.get_bytes() + self.revocable.get_bytes() + self.system.get_bytes()


class MemoryPool:
    """Per-chip (per-worker) pool: GENERAL or RESERVED (memory/MemoryPool.java:43).

    `reserve` blocks nothing (advisory); exceeding the pool marks it over-committed so
    the revoking scheduler / low-memory killer can act.
    """

    def __init__(self, pool_id: str, max_bytes: int):
        self.id = pool_id
        self.max_bytes = max_bytes
        self._reserved: Dict[str, int] = {}  # query_id -> bytes
        self._revocable: Dict[str, int] = {}
        # disk-spill ledger: bytes a query holds in on-disk runs
        # (exec/spill.py). Deliberately EXCLUDED from reserved_bytes()/
        # free_bytes()/query_bytes(): spilling to disk must relieve memory
        # pressure, not keep the revoker and OOM killer latched on bytes
        # that no longer occupy RAM — but the footprint stays visible to
        # status/admission via spill_by_query().
        self._spill: Dict[str, int] = {}
        self._lock = threading.Lock()

    def reserve(self, query_id: str, delta: int, revocable: bool = False) -> None:
        with self._lock:
            d = self._revocable if revocable else self._reserved
            d[query_id] = d.get(query_id, 0) + delta
            if d[query_id] <= 0:
                d.pop(query_id)

    def reserve_spill(self, query_id: str, delta: int) -> None:
        """Charge (or, with a negative delta, release) disk-spill bytes."""
        with self._lock:
            self._spill[query_id] = self._spill.get(query_id, 0) + delta
            if self._spill[query_id] <= 0:
                self._spill.pop(query_id)

    def clear_query(self, query_id: str) -> None:
        """Drop every reservation of one query — the end-of-query backstop
        for the SHARED pool: an operator path that failed to release (error
        teardown, abandoned drivers) must not leak phantom pressure into
        every later tenant's admission and revocation decisions."""
        with self._lock:
            self._reserved.pop(query_id, None)
            self._revocable.pop(query_id, None)
            self._spill.pop(query_id, None)

    def by_query(self) -> Dict[str, int]:
        """{query_id: total bytes} — what /v1/status ships to the cluster
        memory manager's OOM policy."""
        with self._lock:
            totals: Dict[str, int] = dict(self._reserved)
            for q, b in self._revocable.items():
                totals[q] = totals.get(q, 0) + b
            return totals

    def revocable_by_query(self) -> Dict[str, int]:
        """{query_id: revocable bytes} — what /v1/status ships so the
        cluster OOM killer can tell a spillable query from a doomed one."""
        with self._lock:
            return dict(self._revocable)

    def spill_by_query(self) -> Dict[str, int]:
        """{query_id: on-disk spill bytes} — the disk rung of the ladder."""
        with self._lock:
            return dict(self._spill)

    def reserved_bytes(self) -> int:
        return sum(self._reserved.values()) + sum(self._revocable.values())

    def revocable_bytes(self) -> int:
        return sum(self._revocable.values())

    def spilled_bytes(self) -> int:
        return sum(self._spill.values())

    def spill_bytes(self, query_id: str) -> int:
        return self._spill.get(query_id, 0)

    def free_bytes(self) -> int:
        return self.max_bytes - self.reserved_bytes()

    def query_bytes(self, query_id: str) -> int:
        return self._reserved.get(query_id, 0) + self._revocable.get(query_id, 0)

    def largest_query(self) -> Optional[str]:
        if not self._reserved and not self._revocable:
            return None
        totals: Dict[str, int] = dict(self._reserved)
        for q, b in self._revocable.items():
            totals[q] = totals.get(q, 0) + b
        return max(totals, key=totals.get)


GENERAL_POOL = "general"
RESERVED_POOL = "reserved"

# ---------------------------------------------------------------------------
# the process-shared GENERAL pool: one accounting surface for every
# concurrent query on this engine instance (multi-tenant serving). Before
# this, each query made itself a private pool — N tenants never competed,
# the revoker and the OOM killer each saw one query's world.
# ---------------------------------------------------------------------------

_SHARED_LOCK = threading.Lock()
_SHARED_POOL: Optional[MemoryPool] = None


def shared_general_pool(max_bytes: Optional[int] = None) -> MemoryPool:
    """The process-wide GENERAL pool. Sized at first use; later callers can
    only GROW it (a tenant's session knob must not shrink the budget under
    every other live query). Scan prefetch, exchange in-flight bytes and
    operator state all reserve here per query, so admission control
    (server/resource_groups), the revoker and the cluster OOM killer see
    one unified footprint."""
    global _SHARED_POOL
    with _SHARED_LOCK:
        if _SHARED_POOL is None:
            _SHARED_POOL = MemoryPool(GENERAL_POOL, int(max_bytes or 8 << 30))
        elif max_bytes:
            _SHARED_POOL.max_bytes = max(_SHARED_POOL.max_bytes,
                                         int(max_bytes))
        return _SHARED_POOL


class QueryContextMemory:
    """Per-query memory root with a hard user-memory limit
    (memory/QueryContext.java analogue)."""

    def __init__(self, query_id: str, pool: MemoryPool, max_user_bytes: int):
        self.query_id = query_id
        self.pool = pool
        self.max_user_bytes = max_user_bytes
        self.memory = MemoryTrackingContext(
            AggregatedMemoryContext(reservation_handler=self._on_user),
            AggregatedMemoryContext(reservation_handler=self._on_revocable),
            AggregatedMemoryContext())

    def _on_user(self, delta: int, total: int) -> None:
        if total > self.max_user_bytes:
            # journal BEFORE raising: the exception may surface far away
            # (through a consumer's poisoned queue) with the byte evidence
            # long gone — the event pins query id, limit and actual bytes
            from .utils import events
            events.emit("query.memory_exceeded", severity=events.ERROR,
                        query_id=self.query_id,
                        limit_bytes=self.max_user_bytes, reserved_bytes=total)
            raise ExceededMemoryLimitException("per-query user", self.max_user_bytes)
        self.pool.reserve(self.query_id, delta, revocable=False)

    def _on_revocable(self, delta: int, total: int) -> None:
        self.pool.reserve(self.query_id, delta, revocable=True)


class MemoryRevoker:
    """Asks operators to spill when the pool is over target
    (execution/MemoryRevokingScheduler.java:46,168-205).

    Each registered operator's `start_memory_revoke` walks the full ladder
    itself: device HBM -> host RAM, then host RAM -> disk when a
    SpillManager is attached (exec/spill.py) — so one revoke round here
    escalates as far down the hierarchy as the operator can go, and the
    cluster OOM killer only fires after this has been given a beat."""

    def __init__(self, pool: MemoryPool, target_fraction: float = 0.9):
        self.pool = pool
        self.target_fraction = target_fraction
        self._revocables: List = []  # objects exposing revocable_bytes()/start_memory_revoke()

    def register(self, op) -> None:
        self._revocables.append(op)

    def maybe_revoke(self) -> int:
        """Revoke largest-first until under target; returns bytes requested."""
        target = int(self.pool.max_bytes * self.target_fraction)
        over = self.pool.reserved_bytes() - target
        if over <= 0:
            return 0
        requested = 0
        for op in sorted(self._revocables, key=lambda o: -o.revocable_bytes()):
            if requested >= over:
                break
            b = op.revocable_bytes()
            if b > 0:
                op.start_memory_revoke()
                requested += b
        if requested:
            from .utils import events
            events.emit("memory.revoke", severity=events.WARN,
                        requested_bytes=requested,
                        pool_reserved_bytes=self.pool.reserved_bytes(),
                        pool_max_bytes=self.pool.max_bytes)
        return requested
