"""Type system for the TPU-native engine.

Analogue of the reference SPI type layer (presto-spi/.../spi/type/Type.java:25 and the
~60 concrete types under presto-spi/src/main/java/com/facebook/presto/spi/type/).

Design (tpu-first, not a translation):
- Every type maps to a fixed-width on-device representation (a jax dtype) so pages are
  dense arrays XLA can tile onto the MXU/VPU. Variable-width SQL types (VARCHAR) are
  dictionary-encoded at ingest: int32 codes on device, the byte dictionary stays host-side
  (mirroring how the reference leans on spi/block/DictionaryBlock.java for the same reason).
- DECIMAL(p,s) with p<=18 is exact int64 scaled integers (the reference's short decimal,
  spi/type/DecimalType.java) — int64 is XLA-emulated on TPU but only touches the narrow
  final-aggregation path; hot kernels run on int32/float32.
"""
from __future__ import annotations

import dataclasses
from typing import Any, ClassVar, Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """Base SQL type. Compare by name (like TypeSignature equality in the reference)."""

    name: ClassVar[str] = "unknown"

    @property
    def np_dtype(self) -> np.dtype:
        raise NotImplementedError

    @property
    def comparable(self) -> bool:
        return True

    @property
    def orderable(self) -> bool:
        return True

    @property
    def fixed_width(self) -> bool:
        return True

    def display_name(self) -> str:
        return self.name

    def __str__(self) -> str:  # pragma: no cover - debug aid
        return self.display_name()

    # Python-value conversion used by the client protocol / oracle comparisons.
    def to_python(self, raw: Any) -> Any:
        return raw


@dataclasses.dataclass(frozen=True)
class BigintType(Type):
    name: ClassVar[str] = "bigint"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)

    def to_python(self, raw):
        return int(raw)


@dataclasses.dataclass(frozen=True)
class IntegerType(Type):
    name: ClassVar[str] = "integer"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int32)

    def to_python(self, raw):
        return int(raw)


@dataclasses.dataclass(frozen=True)
class SmallintType(Type):
    name: ClassVar[str] = "smallint"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int16)

    def to_python(self, raw):
        return int(raw)


@dataclasses.dataclass(frozen=True)
class DoubleType(Type):
    name: ClassVar[str] = "double"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.float64)

    def to_python(self, raw):
        return float(raw)


@dataclasses.dataclass(frozen=True)
class RealType(Type):
    name: ClassVar[str] = "real"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.float32)

    def to_python(self, raw):
        return float(raw)


@dataclasses.dataclass(frozen=True)
class BooleanType(Type):
    name: ClassVar[str] = "boolean"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.bool_)

    def to_python(self, raw):
        return bool(raw)


@dataclasses.dataclass(frozen=True)
class DateType(Type):
    """Days since epoch in int32 (matches spi/type/DateType.java representation)."""

    name: ClassVar[str] = "date"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int32)

    def to_python(self, raw):
        import datetime

        return datetime.date(1970, 1, 1) + datetime.timedelta(days=int(raw))


@dataclasses.dataclass(frozen=True)
class TimestampType(Type):
    """Millis since epoch in int64 (spi/type/TimestampType.java)."""

    name: ClassVar[str] = "timestamp"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)


@dataclasses.dataclass(frozen=True)
class DecimalType(Type):
    """Short decimal: unscaled int64 value, compile-time scale.

    Reference: spi/type/DecimalType.java (short decimal path). Long decimals (p>18)
    are out of scope for the TPC workloads and rejected at analysis time.
    """

    precision: int = 12
    scale: int = 2
    name: ClassVar[str] = "decimal"

    def __post_init__(self):
        if self.precision > 18:
            raise ValueError("long decimals (precision > 18) not supported on device")

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64)

    def display_name(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def to_python(self, raw):
        from decimal import Decimal

        return Decimal(int(raw)) / (10 ** self.scale)


@dataclasses.dataclass(frozen=True)
class VarcharType(Type):
    """Dictionary-encoded strings: device side is int32 codes, bytes live host-side.

    Reference: spi/type/VarcharType.java + spi/block/DictionaryBlock.java. On TPU there
    is no efficient variable-width representation, so *all* varchar blocks are dictionary
    blocks; string-typed expressions either (a) evaluate on the dictionary host-side and
    broadcast as code predicates, or (b) compare codes directly when dictionaries match.
    """

    length: Optional[int] = None  # None == unbounded
    # wide=True: int64 codes, for synthesized dictionaries whose code space exceeds
    # 31 bits (packed word combinations, formatted id strings — see the tpch
    # generator's PackedWordsDictionary / FormattedDictionary)
    wide: bool = False
    name: ClassVar[str] = "varchar"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int64 if self.wide else np.int32)  # dictionary code

    @property
    def fixed_width(self) -> bool:
        return False

    def display_name(self) -> str:
        return "varchar" if self.length is None else f"varchar({self.length})"


@dataclasses.dataclass(frozen=True)
class CharType(VarcharType):
    name: ClassVar[str] = "char"

    def display_name(self) -> str:
        return f"char({self.length})" if self.length is not None else "char"


@dataclasses.dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(element) — spi/type/ArrayType.java analogue.

    TPU-first stance: ragged array VALUES never ride the device as
    variable-width blocks. Static ARRAY[..] constructors exist at PLAN time
    only (unnest/cardinality lower to unions/constants). DYNAMIC arrays
    (array_agg output) use the same design as varchar: the device column is
    an int32 HANDLE into a host-side ArrayValues store (block.ArrayValues);
    the ragged (offsets, values) pair is computed on device by the collect
    aggregation and materialized host-side at the output boundary."""

    element: Type = None

    def __post_init__(self):
        object.__setattr__(self, "name", f"array({self.element.name})")

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int32)  # handle into a host ArrayValues store

    def display_name(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True)
class MapType(Type):
    """MAP(key, value) — spi/type/MapType.java analogue. Device
    representation is the same int32 handle scheme as ArrayType (map_agg /
    histogram outputs decode through block.ArrayValues)."""

    key: Type = None
    value: Type = None

    def __post_init__(self):
        object.__setattr__(self, "name",
                           f"map({self.key.name}, {self.value.name})")

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.int32)

    def display_name(self) -> str:
        return self.name


@dataclasses.dataclass(frozen=True)
class GeometryType(Type):
    """Planar POINT geometry (presto-geospatial's GEOMETRY, narrowed).

    TPU-first representation: a point is ONE complex128 lane (x + iy) — two
    doubles packed per value, so point columns ride the same dense-array
    page substrate as every scalar type. Polygons/linestrings exist only as
    PLAN-TIME constants (WKT literals folded by the analyzer); per-row
    polygon values have no device representation and are rejected there."""

    name: ClassVar[str] = "geometry"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.complex128)

    def to_python(self, raw):
        c = complex(raw)
        return f"POINT ({c.real:g} {c.imag:g})"


@dataclasses.dataclass(frozen=True)
class UnknownType(Type):
    """Type of NULL literals before coercion (spi/type/UnknownType analogue)."""

    name: ClassVar[str] = "unknown"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(np.bool_)


BIGINT = BigintType()
INTEGER = IntegerType()
SMALLINT = SmallintType()
DOUBLE = DoubleType()
REAL = RealType()
BOOLEAN = BooleanType()
DATE = DateType()
TIMESTAMP = TimestampType()
VARCHAR = VarcharType()
WIDE_VARCHAR = VarcharType(wide=True)
UNKNOWN = UnknownType()
GEOMETRY = GeometryType()


def decimal_type(precision: int = 12, scale: int = 2) -> DecimalType:
    return DecimalType(precision, scale)


_PARSE_TABLE = {
    "bigint": BIGINT,
    "integer": INTEGER,
    "int": INTEGER,
    "smallint": SMALLINT,
    "double": DOUBLE,
    "real": REAL,
    "boolean": BOOLEAN,
    "date": DATE,
    "timestamp": TIMESTAMP,
    "varchar": VARCHAR,
    "unknown": UNKNOWN,
}


def parse_type(text: str) -> Type:
    """Parse a type signature string (TypeSignature.parse analogue, simplified)."""
    text = text.strip().lower()
    if text in _PARSE_TABLE:
        return _PARSE_TABLE[text]
    if text.startswith("decimal"):
        inner = text[len("decimal"):].strip("() ")
        if not inner:
            return DecimalType()
        p, s = (int(x) for x in inner.split(","))
        return DecimalType(p, s)
    if text.startswith("varchar"):
        inner = text[len("varchar"):].strip("() ")
        return VarcharType(int(inner)) if inner else VARCHAR
    if text.startswith("char"):
        inner = text[len("char"):].strip("() ")
        return CharType(int(inner)) if inner else CharType()
    raise ValueError(f"unknown type: {text}")


def is_string(t: Type) -> bool:
    return isinstance(t, VarcharType)


def is_numeric(t: Type) -> bool:
    return isinstance(t, (BigintType, IntegerType, SmallintType, DoubleType, RealType, DecimalType))


def is_integral(t: Type) -> bool:
    return isinstance(t, (BigintType, IntegerType, SmallintType))


def is_floating(t: Type) -> bool:
    return isinstance(t, (DoubleType, RealType))


def common_super_type(a: Type, b: Type) -> Type:
    """Implicit-coercion lattice (sql/analyzer TypeCoercion analogue, numeric subset)."""
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    if is_string(a) and is_string(b):
        return VARCHAR
    order = {"smallint": 0, "integer": 1, "bigint": 2, "decimal": 3, "real": 4, "double": 5}
    if is_numeric(a) and is_numeric(b):
        if isinstance(a, DecimalType) and is_integral(b):
            return a
        if isinstance(b, DecimalType) and is_integral(a):
            return b
        if isinstance(a, DecimalType) and is_floating(b):
            return DOUBLE
        if isinstance(b, DecimalType) and is_floating(a):
            return DOUBLE
        if isinstance(a, DecimalType) and isinstance(b, DecimalType):
            scale = max(a.scale, b.scale)
            prec = min(18, max(a.precision - a.scale, b.precision - b.scale) + scale)
            return DecimalType(prec, scale)
        return a if order[a.name] >= order[b.name] else b
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return b
    if isinstance(b, DateType) and isinstance(a, TimestampType):
        return a
    raise TypeError(f"no common type for {a} and {b}")
