"""Shared hand-built query kernels.

The fused TPC-H Q1 page kernel (filter + decimal projections + direct grouped
aggregation) is the engine's flagship single-chip program — the analogue of
presto-benchmark's HandTpchQuery1.java pipeline. Two generations live here:

- `q1_partials`: the general int64 scaled-decimal form, shared with the
  distributed Q1 stage (parallel/distributed.dist_q1_step) and the compile
  check (__graft_entry__.entry).
- `q1_lane_step` / `q1_stream`: the TPU-native form. TPU v5e has no native
  int64 (or f64) — every 64-bit op is a multi-instruction 32-bit-limb
  emulation — so this kernel never touches a 64-bit element-wise value:

  * the host uploads NARROW dtypes (ep int32, qty/shipdate int16, the rest
    int8: 12 bytes/row vs 44 for the int64 page form — host->HBM transfer is
    the wall for a streaming scan);
  * disc_price = ep*(100-disc) fits int32 exactly (<= 1.05e9 for TPC-H's
    value domains), charge = disc_price*(100+tax) would NOT — so rows are
    grouped by (returnflag x linestatus x tax) = 54 segments and
    sum_charge[g] is recovered exactly as sum_t (100+t) * sum_dp[g,t]
    (tax has 9 scaled values 0..8);
  * segment reduction runs on the MXU: int32 metrics are split into 8-bit
    lanes, each exactly representable in f32, and a (C x 55) one-hot group
    matrix contracts a (C x L) lane matrix per chunk of C=65536 rows —
    lane sums <= 255*65536 < 2^24 stay exact in f32;
  * only the (55 x L) per-chunk results accumulate in f64 (emulated, but on
    605 elements — nothing).

  The reference's HandTpchQuery1 runs the same arithmetic via compiled
  accumulators (operator/aggregation/AccumulatorCompiler.java); the lane
  matmul is this engine's MXU-shaped equivalent.
"""
from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import kernel_cache
from ..utils.batching import take_rows

# 1998-12-01 minus 90 days, as days since epoch (the Q1 shipdate cutoff)
Q1_CUTOFF_DAYS = 10471
Q1_N_FLAGS = 3    # l_returnflag domain: A N R
Q1_N_STATUS = 2   # l_linestatus domain: F O

_N_TAX = 9            # l_tax scaled values 0..8
_N_GROUPS = Q1_N_FLAGS * Q1_N_STATUS
_N_SEG = _N_GROUPS * _N_TAX + 1          # +1 dump segment for filtered rows
_CHUNK = 1 << 16                          # 255*65536 < 2^24: exact f32 lane sums

# lane layout of the (C x L) metric matrix: (metric, #8-bit lanes)
_LANES = (("dp", 4), ("ep", 3), ("qty", 2), ("disc", 1), ("count", 1))
_L = sum(n for _, n in _LANES)


def q1_partials(rf, ls, qty, ep, disc, tax, sd, mask,
                cutoff=Q1_CUTOFF_DAYS, n_flags=Q1_N_FLAGS, n_status=Q1_N_STATUS):
    """One page of TPC-H Q1 -> per-group partial sums (dense direct grouping).

    Inputs: rf/ls int32 dictionary codes, qty/ep/disc/tax int64 scaled decimals
    (cents), sd int32 days, mask live rows. Returns a tuple of 6 int64 arrays of
    shape (n_flags*n_status,): sum_qty, sum_base_price, sum_disc_price(scale 4),
    sum_charge(scale 6), sum_disc, count.
    """
    D = n_flags * n_status
    keep = mask & (sd <= jnp.int32(cutoff))
    gid = jnp.where(keep, rf * n_status + ls, D).astype(jnp.int32)
    one = jnp.where(keep, jnp.int64(1), jnp.int64(0))
    disc_price = ep * (100 - disc)        # scale 2+2 = 4
    charge = disc_price * (100 + tax)     # scale 4+2 = 6
    cols = (jnp.where(keep, qty, 0), jnp.where(keep, ep, 0),
            jnp.where(keep, disc_price, 0), jnp.where(keep, charge, 0),
            jnp.where(keep, disc, 0), one)
    return tuple(jax.ops.segment_sum(c, gid, num_segments=D + 1)[:D] for c in cols)


def q1_lane_step(ep, qty, sd, disc, tax, rf, ls, acc):
    """One fixed-size batch of Q1 -> (55 x L) f64 lane accumulator.

    ep int32, qty/sd int16, disc/tax/rf/ls int8, all shape (B,) with B a
    multiple of _CHUNK (pad rows carry sd > cutoff so they fall in the dump
    segment — the count lane is constant 1, the dump row absorbs it).
    `acc` is the running (55 x L) f64 accumulator (donated by the caller).
    """
    B = ep.shape[0]
    k = B // _CHUNK
    keep = sd <= jnp.int16(Q1_CUTOFF_DAYS)
    tax32 = tax.astype(jnp.int32)
    gid = rf.astype(jnp.int32) * Q1_N_STATUS + ls.astype(jnp.int32)
    seg = jnp.where(keep, gid * _N_TAX + tax32, _N_SEG - 1)
    dp = ep * (100 - disc.astype(jnp.int32))      # exact in int32 (<= 1.05e9)
    qty32 = qty.astype(jnp.int32)
    disc32 = disc.astype(jnp.int32)

    lanes = []
    for name, n in _LANES:
        if name == "dp":
            v = dp
        elif name == "ep":
            v = ep
        elif name == "qty":
            v = qty32
        elif name == "disc":
            v = disc32
        else:  # count
            lanes.append(jnp.ones(B, dtype=jnp.float32))
            continue
        for i in range(n):
            lanes.append(((v >> (8 * i)) & 0xFF).astype(jnp.float32))
    X = jnp.stack(lanes, axis=-1).reshape(k, _CHUNK, _L)
    seg = seg.reshape(k, _CHUNK)
    seg_iota = jnp.arange(_N_SEG, dtype=jnp.int32)

    def body(a, xs):
        x, s = xs
        onehot = (s[:, None] == seg_iota[None, :]).astype(jnp.float32)
        # (55 x C) @ (C x L) on the MXU; each entry <= 255*65536 < 2^24: exact
        chunk = jax.lax.dot_general(
            onehot, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return a + chunk.astype(jnp.float64), None

    acc, _ = jax.lax.scan(body, acc, (X, seg))
    return acc


def q1_lane_finish(acc: np.ndarray) -> Dict[str, np.ndarray]:
    """(55 x L) lane accumulator -> exact per-group Q1 sums (host, int arith).

    Returns int64 arrays of shape (6,): sum_qty (scale 2), sum_base_price
    (scale 2), sum_disc_price (scale 4), sum_charge (scale 6), sum_disc
    (scale 2), count — the same contract as `q1_partials`.
    """
    acc = np.asarray(acc)
    seg = acc[:-1].reshape(_N_GROUPS, _N_TAX, _L)  # drop dump segment
    out: Dict[str, np.ndarray] = {}
    col = 0
    per_metric: Dict[str, np.ndarray] = {}
    for name, n in _LANES:
        # exact: f64 lane sums are integers < 2^53; recombine in python ints
        m = np.zeros(( _N_GROUPS, _N_TAX), dtype=object)
        for i in range(n):
            m = m + seg[:, :, col].astype(np.int64).astype(object) * (1 << (8 * i))
            col += 1
        per_metric[name] = m
    tax_vals = np.arange(_N_TAX, dtype=object)
    out["sum_qty"] = per_metric["qty"].sum(axis=1).astype(np.int64)
    out["sum_base_price"] = per_metric["ep"].sum(axis=1).astype(np.int64)
    out["sum_disc_price"] = per_metric["dp"].sum(axis=1).astype(np.int64)
    out["sum_charge"] = (per_metric["dp"] * (100 + tax_vals)[None, :]).sum(axis=1).astype(np.int64)
    out["sum_disc"] = per_metric["disc"].sum(axis=1).astype(np.int64)
    out["count"] = per_metric["count"].sum(axis=1).astype(np.int64)
    return out


_Q1_STREAM_COLS = ["l_returnflag", "l_linestatus", "l_quantity",
                   "l_extendedprice", "l_discount", "l_tax", "l_shipdate"]


def _narrow(data: Dict[str, np.ndarray]) -> Tuple[np.ndarray, ...]:
    """Host-side dtype narrowing: the wire format of the streaming scan."""
    return (data["l_extendedprice"].astype(np.int32),
            data["l_quantity"].astype(np.int16),
            data["l_shipdate"].astype(np.int16),
            data["l_discount"].astype(np.int8),
            data["l_tax"].astype(np.int8),
            data["l_returnflag"].astype(np.int8),
            data["l_linestatus"].astype(np.int8))


def q1_stream(sf: float, seconds_budget: float = 60.0,
              batch_rows: int = 1 << 21, gen_threads: int = 3,
              max_rows: Optional[int] = None):
    """Streaming Q1 over generated lineitem data with generation/compute overlap.

    Producer threads generate order-range chunks and narrow their dtypes; the
    consumer re-batches them into fixed-size (static-shape) buffers, uploads,
    and dispatches `q1_lane_step` — XLA's async dispatch overlaps upload+compute
    of batch N with host generation of batch N+1.

    Returns (rows, wall_s, gen_stall_s, first_compile_s, finish_dict).
    """
    import queue
    import threading

    from ..connectors.tpch import generator as g

    assert batch_rows % _CHUNK == 0
    orders = g.TPCH_TABLES["orders"].row_count(sf)
    chunk_orders = 1 << 17

    q: queue.Queue = queue.Queue(maxsize=gen_threads * 2)
    stop = threading.Event()
    producer_errors: list = []

    def producer(tid: int):
        try:
            for lo in range(tid * chunk_orders, orders, gen_threads * chunk_orders):
                if stop.is_set():
                    break
                hi = min(lo + chunk_orders, orders)
                data = g.lineitem_for_orders(lo, hi, sf, _Q1_STREAM_COLS)
                q.put(_narrow(data))
        except BaseException as e:  # noqa: BLE001 - surfaced to the caller below
            producer_errors.append(e)
        finally:
            q.put(None)

    threads = [threading.Thread(target=producer, args=(t,), daemon=True)
               for t in range(gen_threads)]
    for t in threads:
        t.start()

    step = kernel_cache.get_or_install(
        ("q1-lane-step", "donate"),
        lambda: jax.jit(q1_lane_step, donate_argnums=(7,)))
    acc = jnp.zeros((_N_SEG, _L), dtype=jnp.float64)

    pend: list = []           # leftover numpy chunks, re-batched to batch_rows
    pend_rows = 0
    done_producers = 0
    total_rows = 0
    gen_stall = 0.0
    first_compile = None
    t0 = time.perf_counter()

    def assemble(n_target: int):
        """Take exactly n_target rows from pend (callers ensured enough)."""
        nonlocal pend_rows
        pend_rows -= n_target
        return tuple(take_rows(pend, n_target))

    def dispatch(args, nrows):
        nonlocal acc, first_compile, total_rows
        if first_compile is None:
            tc = time.perf_counter()
            acc = step(*args, acc)
            jax.block_until_ready(acc)
            first_compile = time.perf_counter() - tc
        else:
            acc = step(*args, acc)
        total_rows += nrows

    while done_producers < len(threads):
        ts = time.perf_counter()
        item = q.get()
        gen_stall += time.perf_counter() - ts
        if item is None:
            done_producers += 1
            continue
        pend.append(item)
        pend_rows += len(item[0])
        while pend_rows >= batch_rows:
            dispatch(assemble(batch_rows), batch_rows)
        if time.perf_counter() - t0 > seconds_budget or \
                (max_rows is not None and total_rows >= max_rows):
            stop.set()
            # drain queue so producers can exit
            while done_producers < len(threads):
                if q.get() is None:
                    done_producers += 1
            break
    # tail: pad the final partial batch into the dump segment (sd > cutoff)
    if pend_rows > 0 and not stop.is_set():
        n = pend_rows
        args = assemble(n)
        padded = n + (-n) % _CHUNK
        if padded != n:
            pad = padded - n
            ep, qty, sd, disc, tax, rf, ls = args
            args = (np.concatenate([ep, np.zeros(pad, np.int32)]),
                    np.concatenate([qty, np.zeros(pad, np.int16)]),
                    np.concatenate([sd, np.full(pad, 32767, np.int16)]),
                    np.concatenate([disc, np.zeros(pad, np.int8)]),
                    np.concatenate([tax, np.zeros(pad, np.int8)]),
                    np.concatenate([rf, np.zeros(pad, np.int8)]),
                    np.concatenate([ls, np.zeros(pad, np.int8)]))
        dispatch(args, n)
    jax.block_until_ready(acc)
    wall = time.perf_counter() - t0
    if producer_errors:
        raise RuntimeError("q1_stream producer failed") from producer_errors[0]
    return total_rows, wall, gen_stall, first_compile, q1_lane_finish(np.asarray(acc))


def q1_resident(sf: float, batch_rows: int = 1 << 22, runs: int = 10):
    """Warm-table Q1 throughput: the presto-benchmark LocalQueryRunner pattern
    (data already in memory — here, resident in HBM as narrow columns).

    Uploads one fixed batch once, then times `runs` chained `q1_lane_step`
    calls — the accumulator chains through every call (without donation), so
    each execution has distinct inputs and measures real device work.

    Returns (rows_per_sec, batch_rows, per_step_ms, finish_dict_for_one_batch).
    """
    from ..connectors.tpch import generator as g

    assert batch_rows % _CHUNK == 0
    need_orders = int(batch_rows / g.AVG_LINES_PER_ORDER) + 1
    orders = min(need_orders, g.TPCH_TABLES["orders"].row_count(max(sf, 1.0)))
    data = g.lineitem_for_orders(0, orders, max(sf, 1.0), _Q1_STREAM_COLS)
    args = _narrow(data)
    n = len(args[0])
    reps = batch_rows // n + 1
    args = tuple(np.tile(a, reps)[:batch_rows] for a in args)
    dev = jax.devices()[0]
    args = tuple(jax.device_put(a, dev) for a in args)
    jax.block_until_ready(args)

    step = kernel_cache.get_or_install(
        ("q1-lane-step", "plain"), lambda: jax.jit(q1_lane_step))
    acc = jnp.zeros((_N_SEG, _L), dtype=jnp.float64)
    acc = step(*args, acc)
    jax.block_until_ready(acc)          # compile + one warm batch
    one_batch = q1_lane_finish(np.asarray(acc))
    t0 = time.perf_counter()
    for _ in range(runs):
        acc = step(*args, acc)
    jax.block_until_ready(acc)
    dt = (time.perf_counter() - t0) / runs
    return batch_rows / dt, batch_rows, dt * 1000.0, one_batch
