"""Shared hand-built query kernels.

The fused TPC-H Q1 page kernel (filter + decimal projections + direct grouped
aggregation) is the engine's flagship single-chip program — the analogue of
presto-benchmark's HandTpchQuery1.java pipeline. It is defined ONCE here and wrapped
by the bench (bench.py), the compile-check entry (__graft_entry__.entry) and the
distributed Q1 stage (parallel/distributed.dist_q1_step), so the arithmetic can
never diverge between them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

# 1998-12-01 minus 90 days, as days since epoch (the Q1 shipdate cutoff)
Q1_CUTOFF_DAYS = 10471
Q1_N_FLAGS = 3    # l_returnflag domain: A N R
Q1_N_STATUS = 2   # l_linestatus domain: F O


def q1_partials(rf, ls, qty, ep, disc, tax, sd, mask,
                cutoff=Q1_CUTOFF_DAYS, n_flags=Q1_N_FLAGS, n_status=Q1_N_STATUS):
    """One page of TPC-H Q1 -> per-group partial sums (dense direct grouping).

    Inputs: rf/ls int32 dictionary codes, qty/ep/disc/tax int64 scaled decimals
    (cents), sd int32 days, mask live rows. Returns a tuple of 6 int64 arrays of
    shape (n_flags*n_status,): sum_qty, sum_base_price, sum_disc_price(scale 4),
    sum_charge(scale 6), sum_disc, count.
    """
    D = n_flags * n_status
    keep = mask & (sd <= jnp.int32(cutoff))
    gid = jnp.where(keep, rf * n_status + ls, D).astype(jnp.int32)
    one = jnp.where(keep, jnp.int64(1), jnp.int64(0))
    disc_price = ep * (100 - disc)        # scale 2+2 = 4
    charge = disc_price * (100 + tax)     # scale 4+2 = 6
    cols = (jnp.where(keep, qty, 0), jnp.where(keep, ep, 0),
            jnp.where(keep, disc_price, 0), jnp.where(keep, charge, 0),
            jnp.where(keep, disc, 0), one)
    return tuple(jax.ops.segment_sum(c, gid, num_segments=D + 1)[:D] for c in cols)
