"""Hand-built TPC-H operator pipelines.

Analogue of presto-benchmark's hand-coded pipelines (HandTpchQuery1.java,
HandTpchQuery6.java, BenchmarkSuite.java:32): the same physical plans the SQL planner
will produce, constructed directly. These are the engine's flagship "models" — the
driver's __graft_entry__ compiles the Q1 kernel as the representative forward step.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..block import Page
from ..connectors.tpch.connector import TpchConnector
from ..connectors.tpch import generator as g
from ..ops.aggregates import AggregateCall, resolve_aggregate
from ..ops.expressions import (InputLayout, RowExpression, call, constant,
                               days_from_civil, input_ref, special)
from ..ops.filter_project import PageProcessor
from ..ops.hash_agg import SINGLE, HashAggregationOperatorFactory
from ..ops.scan import TableScanOperatorFactory
from ..exec.driver import Driver
from ..spi.connector import ConnectorPageSource, Constraint, SchemaTableName
from ..types import BIGINT, BOOLEAN, DATE, DOUBLE, VARCHAR, DecimalType
from ..utils.testing import PageConsumerFactory

DEC = DecimalType(12, 2)


class ConcatPageSource(ConnectorPageSource):
    def __init__(self, sources):
        self.sources = list(sources)

    def __iter__(self):
        for s in self.sources:
            yield from s


def _lineitem_source(schema: str, columns: List[str], page_capacity: int,
                     n_splits: int = 8) -> Tuple[ConnectorPageSource, InputLayout]:
    return _table_source(schema, "lineitem", columns, page_capacity, n_splits)


def build_q6(schema: str = "sf1", page_capacity: int = 1 << 20):
    """TPC-H Q6: sum(extendedprice*discount) under date/discount/quantity filter."""
    columns = ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"]
    source, layout = _lineitem_source(schema, columns, page_capacity)
    sd, disc, qty, ep = (input_ref(i, layout.types[i]) for i in range(4))
    pred = special(
        "AND", BOOLEAN,
        call("greater_than_or_equal", BOOLEAN, sd, constant(days_from_civil(1994, 1, 1), DATE)),
        call("less_than", BOOLEAN, sd, constant(days_from_civil(1995, 1, 1), DATE)),
        special("BETWEEN", BOOLEAN, disc, constant(5, DEC), constant(7, DEC)),
        call("less_than", BOOLEAN, qty, constant(2400, DEC)),
    )
    revenue = call("multiply", DecimalType(18, 4), ep, disc)
    processor = PageProcessor(layout, pred, [revenue])
    scan = TableScanOperatorFactory(0, [source], processor.output_types, processor)
    sum_fn = resolve_aggregate("sum", [DecimalType(18, 4)])
    agg = HashAggregationOperatorFactory(
        1, [], [], [], None,
        [AggregateCall(sum_fn, [0])], SINGLE, page_capacity)
    sink = PageConsumerFactory(2, agg_output_types(agg))
    ops = [scan.create_operator(), agg.create_operator(), sink.create_operator()]
    return Driver(ops), sink


def build_q1(schema: str = "sf1", page_capacity: int = 1 << 20):
    """TPC-H Q1: grouped aggregation over returnflag x linestatus (direct strategy)."""
    columns = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
               "l_discount", "l_tax", "l_shipdate"]
    source, layout = _lineitem_source(schema, columns, page_capacity)
    rf, ls, qty, ep, disc, tax, sd = (input_ref(i, layout.types[i]) for i in range(7))
    cutoff = days_from_civil(1998, 12, 1) - 90
    pred = call("less_than_or_equal", BOOLEAN, sd, constant(cutoff, DATE))
    one = constant(100, DEC)  # literal 1 at scale 2
    disc_price = call("multiply", DecimalType(18, 4), ep,
                      call("subtract", DEC, one, disc))
    charge = call("multiply", DecimalType(18, 6), disc_price,
                  call("add", DEC, one, tax))
    projections = [rf, ls, qty, ep, disc, disc_price, charge]
    processor = PageProcessor(layout, pred, projections)
    scan = TableScanOperatorFactory(0, [source], processor.output_types, processor)
    calls = [
        AggregateCall(resolve_aggregate("sum", [DEC]), [2]),                 # sum qty
        AggregateCall(resolve_aggregate("sum", [DEC]), [3]),                 # sum base price
        AggregateCall(resolve_aggregate("sum", [DecimalType(18, 4)]), [5]),  # sum disc price
        AggregateCall(resolve_aggregate("sum", [DecimalType(18, 6)]), [6]),  # sum charge
        AggregateCall(resolve_aggregate("avg", [DEC]), [2]),                 # avg qty
        AggregateCall(resolve_aggregate("avg", [DEC]), [3]),                 # avg price
        AggregateCall(resolve_aggregate("avg", [DEC]), [4]),                 # avg discount
        AggregateCall(resolve_aggregate("count", []), []),                   # count(*)
    ]
    agg = HashAggregationOperatorFactory(
        2, [0, 1], [VARCHAR, VARCHAR], [g.DICT_RETURNFLAG, g.DICT_LINESTATUS],
        [len(g.DICT_RETURNFLAG), len(g.DICT_LINESTATUS)],
        calls, SINGLE, page_capacity)
    sink = PageConsumerFactory(3, agg_output_types(agg))
    ops = [scan.create_operator(), agg.create_operator(), sink.create_operator()]
    return Driver(ops), sink


def _table_source(schema: str, table: str, columns: List[str], page_capacity: int,
                  n_splits: int = 8):
    conn = TpchConnector("tpch")
    meta = conn.metadata()
    th = meta.get_table_handle(SchemaTableName(schema, table))
    handles = meta.get_column_handles(th)
    cols = [handles[c] for c in columns]
    splits = conn.split_manager().get_splits(th, Constraint.all(), n_splits)
    sources = [conn.page_source_provider().create_page_source(s, cols, page_capacity)
               for s in splits]
    tm = meta.get_table_metadata(th)
    if table == "lineitem":
        info = {n: (t, d) for (n, t, d) in g.LINEITEM_COLUMNS}
    else:
        info = {c.name: (c.type, g.TPCH_TABLES[table].column(c.name).dictionary)
                for c in tm.columns}
    layout = InputLayout([info[c][0] for c in columns], [info[c][1] for c in columns])
    return ConcatPageSource(sources), layout


def build_q3(schema: str = "sf1", page_capacity: int = 1 << 20):
    """TPC-H Q3: customer semi-> orders build -> lineitem probe -> group -> TopN.

    Physical plan (what the SQL planner will emit for the single-chip case):
      pipeline 1: scan customer [c_mktsegment='BUILDING'] -> build semi set (custkey)
      pipeline 2: scan orders [o_orderdate < 1995-03-15] -> semi join customer
                  -> build lookup (o_orderkey -> o_orderdate, o_shippriority)
      pipeline 3: scan lineitem [l_shipdate > 1995-03-15] -> lookup join
                  -> project revenue -> hash agg by (okey, odate, oprio) -> TopN 10
    """
    from ..exec.driver import Driver
    from ..ops.hash_join import (INNER, SEMI, JoinBuildOperatorFactory,
                                 LookupJoinOperatorFactory)
    from ..ops.topn import SortOrder, TopNOperatorFactory

    cutoff = days_from_civil(1995, 3, 15)

    # pipeline 1: customer build (semi set of custkeys in BUILDING segment)
    csrc, clayout = _table_source(schema, "customer", ["c_custkey", "c_mktsegment"],
                                  page_capacity)
    cpred = call("equal", BOOLEAN, input_ref(1, VARCHAR), constant("BUILDING", VARCHAR))
    cproc = PageProcessor(clayout, cpred, [input_ref(0, BIGINT)])
    cscan = TableScanOperatorFactory(0, [csrc], cproc.output_types, cproc)
    cbuild = JoinBuildOperatorFactory(1, [0], [], [], strategy="sorted", unique=False)
    d1 = Driver([cscan.create_operator(), cbuild.create_operator()])

    # pipeline 2: orders filtered + semi-joined, then built as lookup source
    osrc, olayout = _table_source(schema, "orders",
                                  ["o_orderkey", "o_custkey", "o_orderdate",
                                   "o_shippriority"], page_capacity)
    opred = call("less_than", BOOLEAN, input_ref(2, DATE), constant(cutoff, DATE))
    oproc = PageProcessor(olayout, opred,
                          [input_ref(0, BIGINT), input_ref(1, BIGINT),
                           input_ref(2, DATE), input_ref(3, olayout.types[3])])
    oscan = TableScanOperatorFactory(2, [osrc], oproc.output_types, oproc)
    osemi = LookupJoinOperatorFactory(
        3, cbuild.lookup_factory, [1], [0, 1, 2, 3],
        [(BIGINT, None), (BIGINT, None), (DATE, None), (olayout.types[3], None)],
        [], [], SEMI)
    obuild = JoinBuildOperatorFactory(4, [0], [2, 3],
                                      [(DATE, None), (olayout.types[3], None)],
                                      strategy="sorted", unique=True)
    d2 = Driver([oscan.create_operator(), osemi.create_operator(),
                 obuild.create_operator()])

    # pipeline 3: lineitem probe -> revenue -> agg -> topn
    lsrc, llayout = _table_source(schema, "lineitem",
                                  ["l_orderkey", "l_shipdate", "l_extendedprice",
                                   "l_discount"], page_capacity)
    lpred = call("greater_than", BOOLEAN, input_ref(1, DATE), constant(cutoff, DATE))
    revenue = call("multiply", DecimalType(18, 4), input_ref(2, DEC),
                   call("subtract", DEC, constant(100, DEC), input_ref(3, DEC)))
    lproc = PageProcessor(llayout, lpred, [input_ref(0, BIGINT), revenue])
    lscan = TableScanOperatorFactory(5, [lsrc], lproc.output_types, lproc)
    ljoin = LookupJoinOperatorFactory(
        6, obuild.lookup_factory, [0], [0, 1],
        [(BIGINT, None), (DecimalType(18, 4), None)],
        [0, 1], [(DATE, None), (olayout.types[3], None)], INNER)
    calls = [AggregateCall(resolve_aggregate("sum", [DecimalType(18, 4)]), [1])]
    agg = HashAggregationOperatorFactory(
        7, [0, 2, 3], [BIGINT, DATE, olayout.types[3]], [None, None, None], None,
        calls, SINGLE, page_capacity)
    out_types = [BIGINT, DATE, olayout.types[3], DecimalType(18, 4)]
    # final order: l_orderkey, revenue, o_orderdate, o_shippriority
    topn = TopNOperatorFactory(8, 10, [SortOrder(3, descending=True), SortOrder(1)],
                               out_types)
    sink = PageConsumerFactory(9, out_types)
    d3 = Driver([lscan.create_operator(), ljoin.create_operator(),
                 agg.create_operator(), topn.create_operator(),
                 sink.create_operator()])
    return [d1, d2, d3], sink


def run_q3(schema: str = "sf1", page_capacity: int = 1 << 20):
    drivers, sink = build_q3(schema, page_capacity)
    for d in drivers:  # build pipelines first, then probe (scheduler ordering)
        d.run_to_completion()
    # reorder output columns to the SQL shape: orderkey, revenue, orderdate, shippriority
    rows = sink.rows()
    return [[r[0], r[3], r[1], r[2]] for r in rows]


def agg_output_types(factory: HashAggregationOperatorFactory):
    op = None
    # cheap: compute from factory fields without instantiating a builder twice
    out = list(factory.key_types)
    for c in factory.calls:
        out.append(c.function.output_type)
    return out


def run_query(builder, *args, **kw):
    driver, sink = builder(*args, **kw)
    driver.run_to_completion()
    return sink.rows()


_QUERY_TABLES = {"q1": ["lineitem"], "q6": ["lineitem"],
                 "q3": ["lineitem", "orders", "customer"]}


def source_rows(query: str, schema: str) -> int:
    """Total input rows a query scans (the presto-benchmark rows/sec denominator)."""
    from ..connectors.tpch.connector import SCHEMAS

    sf = SCHEMAS[schema]
    return sum(g.table_row_count(t, sf) for t in _QUERY_TABLES[query])
