"""TPC-DS benchmark query texts (north-star pair Q64 + Q72, plus a breadth
set: Q3, Q7, Q19, Q25, Q36, Q42, Q52, Q55).

Spec-defined queries (TPC-DS v2 templates; reference copies live in
presto-benchto-benchmarks/src/main/resources/sql/presto/tpcds/qNN.sql)
adapted to bare table names, this engine's dialect, and this generator's
column subset: name-valued dimension attributes the generator does not
synthesize (i_brand, i_category, s_state, i_manager_id, promotion channel
flags) are replaced by their id-valued columns or dropped filters — the
query SHAPES (join trees, aggregations, rollups, TopN) are preserved, and
every query is verified against the sqlite oracle over identical data.
"""

Q64 = """
with cs_ui as (
  select cs_item_sk,
         sum(cs_ext_list_price) as sale,
         sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit) as refund
  from catalog_sales, catalog_returns
  where cs_item_sk = cr_item_sk
    and cs_order_number = cr_order_number
  group by cs_item_sk
  having sum(cs_ext_list_price) >
         2 * sum(cr_refunded_cash + cr_reversed_charge + cr_store_credit)
),
cross_sales as (
  select i_product_name as product_name,
         i_item_sk as item_sk,
         s_store_name as store_name,
         s_zip as store_zip,
         ad1.ca_street_number as b_street_number,
         ad1.ca_street_name as b_street_name,
         ad1.ca_city as b_city,
         ad1.ca_zip as b_zip,
         ad2.ca_street_number as c_street_number,
         ad2.ca_street_name as c_street_name,
         ad2.ca_city as c_city,
         ad2.ca_zip as c_zip,
         d1.d_year as syear,
         d2.d_year as fsyear,
         d3.d_year as s2year,
         count(*) as cnt,
         sum(ss_wholesale_cost) as s1,
         sum(ss_list_price) as s2,
         sum(ss_coupon_amt) as s3
  from store_sales, store_returns, cs_ui,
       date_dim d1, date_dim d2, date_dim d3,
       store, customer,
       customer_demographics cd1, customer_demographics cd2,
       promotion,
       household_demographics hd1, household_demographics hd2,
       customer_address ad1, customer_address ad2,
       income_band ib1, income_band ib2, item
  where ss_store_sk = s_store_sk
    and ss_sold_date_sk = d1.d_date_sk
    and ss_customer_sk = c_customer_sk
    and ss_cdemo_sk = cd1.cd_demo_sk
    and ss_hdemo_sk = hd1.hd_demo_sk
    and ss_addr_sk = ad1.ca_address_sk
    and ss_item_sk = i_item_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and ss_item_sk = cs_ui.cs_item_sk
    and c_current_cdemo_sk = cd2.cd_demo_sk
    and c_current_hdemo_sk = hd2.hd_demo_sk
    and c_current_addr_sk = ad2.ca_address_sk
    and c_first_sales_date_sk = d2.d_date_sk
    and c_first_shipto_date_sk = d3.d_date_sk
    and ss_promo_sk = p_promo_sk
    and hd1.hd_income_band_sk = ib1.ib_income_band_sk
    and hd2.hd_income_band_sk = ib2.ib_income_band_sk
    and cd1.cd_marital_status <> cd2.cd_marital_status
    and i_color in ('purple', 'burlywood', 'indian', 'spring', 'floral',
                    'medium')
    and i_current_price between 64 and 64 + 10
    and i_current_price between 64 + 1 and 64 + 15
  group by i_item_sk, i_product_name, s_store_name, s_zip,
           ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip,
           ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip,
           d1.d_year, d2.d_year, d3.d_year
)
select cs1.product_name,
       cs1.store_name,
       cs1.store_zip,
       cs1.b_street_number,
       cs1.b_street_name,
       cs1.b_city,
       cs1.b_zip,
       cs1.c_street_number,
       cs1.c_street_name,
       cs1.c_city,
       cs1.c_zip,
       cs1.syear,
       cs1.cnt,
       cs1.s1 as s11,
       cs1.s2 as s21,
       cs1.s3 as s31,
       cs2.s1 as s12,
       cs2.s2 as s22,
       cs2.s3 as s32,
       cs2.syear as syear2,
       cs2.cnt as cnt2
from cross_sales cs1, cross_sales cs2
where cs1.item_sk = cs2.item_sk
  and cs1.syear = 1999
  and cs2.syear = 1999 + 1
  and cs2.cnt <= cs1.cnt
  and cs1.store_name = cs2.store_name
  and cs1.store_zip = cs2.store_zip
order by cs1.product_name, cs1.store_name, cs2.cnt, 14, 15, 16, 17, 18
"""

Q72 = """
select i_item_desc,
       w_warehouse_name,
       d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) as no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) as promo,
       count(*) as total_cnt
from catalog_sales
inner join inventory on cs_item_sk = inv_item_sk
inner join warehouse on w_warehouse_sk = inv_warehouse_sk
inner join item on i_item_sk = cs_item_sk
inner join customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
inner join household_demographics on cs_bill_hdemo_sk = hd_demo_sk
inner join date_dim d1 on cs_sold_date_sk = d1.d_date_sk
inner join date_dim d2 on inv_date_sk = d2.d_date_sk
inner join date_dim d3 on cs_ship_date_sk = d3.d_date_sk
left join promotion on cs_promo_sk = p_promo_sk
left join catalog_returns on cr_item_sk = cs_item_sk
                         and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + interval '5' day
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq
limit 100
"""

Q3 = """
select d_year, i_brand_id, sum(ss_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id = 128
  and d_moy = 11
group by d_year, i_brand_id
order by d_year, sum_agg desc, i_brand_id
limit 100
"""

Q7 = """
select i_item_id,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and p_response_target = 1
  and d_year = 1999
group by i_item_id
order by i_item_id
limit 100
"""

Q19 = """
select i_brand_id, i_manufact_id, sum(ss_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_category_id = 7
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ss_store_sk = s_store_sk
  and ca_zip <> s_zip
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_manufact_id
order by ext_price desc, i_brand_id, i_manufact_id
limit 100
"""

Q25 = """
select i_item_id, i_item_desc, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_return_amt) as store_returns_loss,
       sum(cs_sales_price) as catalog_sales_price
from store_sales, store_returns, catalog_sales, date_dim, store, item
where ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and ss_sold_date_sk = d_date_sk
  and d_moy = 4
  and d_year = 1999
  and ss_store_sk = s_store_sk
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, s_store_name
order by i_item_id, i_item_desc, s_store_name
limit 100
"""

Q36 = """
select sum(ss_net_profit) as total_profit,
       i_category_id, i_class_id,
       grouping(i_category_id) + grouping(i_class_id) as lochierarchy,
       count(*) as cnt
from store_sales, date_dim, item, store
where d_year = 1999
  and d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
group by rollup(i_category_id, i_class_id)
order by lochierarchy desc, i_category_id, i_class_id
limit 100
"""

Q42 = """
select d_year, i_category_id, sum(ss_sales_price) as total_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and d_moy = 11
  and d_year = 1999
group by d_year, i_category_id
order by total_price desc, d_year, i_category_id
limit 100
"""

Q52 = """
select d_year, i_brand_id, sum(ss_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and d_moy = 12
  and d_year = 1998
group by d_year, i_brand_id
order by d_year, ext_price desc, i_brand_id
limit 100
"""

Q55 = """
select i_brand_id, sum(ss_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_class_id = 5
  and d_moy = 11
  and d_year = 1999
group by i_brand_id
order by ext_price desc, i_brand_id
limit 100
"""

Q21 = """
select w_warehouse_name, i_item_id,
       sum(case when d_date < date '1999-03-11'
                then inv_quantity_on_hand else 0 end) as inv_before,
       sum(case when d_date >= date '1999-03-11'
                then inv_quantity_on_hand else 0 end) as inv_after
from inventory, warehouse, item, date_dim
where i_current_price between 0.99 and 1.49
  and i_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk
  and inv_date_sk = d_date_sk
  and d_date between date '1999-02-10' and date '1999-04-10'
group by w_warehouse_name, i_item_id
order by w_warehouse_name, i_item_id
limit 100
"""

Q82 = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 30 and 60
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '1999-05-25' and date '1999-07-24'
  and i_manufact_id in (129, 270, 821, 423, 500, 501, 502, 503)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

Q13 = """
select avg(ss_quantity), avg(ss_ext_sales_price), avg(ss_ext_wholesale_cost),
       sum(ss_ext_wholesale_cost)
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 50.00 and 100.00 and hd_dep_count = 1)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'W'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 150.00 and 200.00 and hd_dep_count = 1))
  and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
  and ca_country = 'United States'
  and ((ca_state in ('TX','OH','TX') and ss_net_profit between 100 and 200)
    or (ca_state in ('OR','NM','KY') and ss_net_profit between 150 and 300)
    or (ca_state in ('VA','TX','MS') and ss_net_profit between 50 and 250))
"""
Q15 = """
select ca_zip, sum(cs_sales_price)
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('85669','86197','88274','83405','86475',
                                '85392','85460','80348','81792')
       or ca_state in ('CA','WA','GA') or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk and d_qoy = 2 and d_year = 2001
group by ca_zip order by ca_zip limit 100
"""
Q26 = """
select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N') and d_year = 2000
group by i_item_id order by i_item_id limit 100
"""
Q43 = """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk
  and s_gmt_offset = -5 and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales, wed_sales,
         thu_sales, fri_sales, sat_sales
limit 100
"""
Q48 = """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'D'
        and cd_education_status = '2 yr Degree'
        and ss_sales_price between 50.00 and 100.00)
    or (cd_demo_sk = ss_cdemo_sk and cd_marital_status = 'S'
        and cd_education_status = 'College'
        and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('CO','OH','TX') and ss_net_profit between 0 and 2000)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('OR','MN','KY') and ss_net_profit between 150 and 3000)
    or (ss_addr_sk = ca_address_sk and ca_country = 'United States'
        and ca_state in ('VA','CA','MS') and ss_net_profit between 50 and 25000))
"""
Q50 = """
select s_store_name, s_company_id, s_street_number, s_street_name,
       s_street_type, s_suite_number, s_city, s_county, s_state, s_zip,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk <= 30) then 1
                else 0 end) as d30,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 30) and
                     (sr_returned_date_sk - ss_sold_date_sk <= 60) then 1
                else 0 end) as d60,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 60) and
                     (sr_returned_date_sk - ss_sold_date_sk <= 90) then 1
                else 0 end) as d90,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 90) and
                     (sr_returned_date_sk - ss_sold_date_sk <= 120) then 1
                else 0 end) as d120,
       sum(case when (sr_returned_date_sk - ss_sold_date_sk > 120) then 1
                else 0 end) as dmore
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
  and ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
  and ss_sold_date_sk = d1.d_date_sk and sr_returned_date_sk = d2.d_date_sk
  and ss_customer_sk = sr_customer_sk and ss_store_sk = s_store_sk
group by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
order by s_store_name, s_company_id, s_street_number, s_street_name,
         s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
limit 100
"""
Q46 = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics, customer_address
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk and ss_addr_sk = ca_address_sk
        and (hd_dep_count = 4 or hd_vehicle_count = 3)
        and d_dow in (6, 0) and d_year = 1999
        and s_city in ('Fairview', 'Midway')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
"""
Q73 = """
select c_birth_year, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and d_dom between 1 and 2
        and (hd_buy_potential = '>10000' or hd_buy_potential = 'Unknown')
        and hd_vehicle_count > 0
        and d_year = 2000 and s_county in ('Williamson County', 'Walker County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk and cnt between 1 and 5
order by cnt desc, c_birth_year
limit 100
"""
Q79 = """
select c_birth_year, s_city, ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk and ss_store_sk = s_store_sk
        and ss_hdemo_sk = hd_demo_sk
        and (hd_dep_count = 6 or hd_vehicle_count > 2)
        and d_dow = 1 and d_year = 2000
        and s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_store_sk, s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_birth_year, amt, profit, ss_ticket_number
limit 100
"""
Q27 = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2000
group by rollup (i_item_id, s_state)
order by i_item_id, s_state
limit 100
"""

QUERIES = {3: Q3, 7: Q7, 13: Q13, 15: Q15, 19: Q19, 21: Q21, 25: Q25,
           26: Q26, 27: Q27, 36: Q36, 42: Q42, 43: Q43, 46: Q46, 48: Q48, 50: Q50,
           52: Q52, 55: Q55, 64: Q64, 72: Q72, 73: Q73, 79: Q79,
           82: Q82}
