"""ClusterQueryRunner: SQL over a real multi-process worker cluster.

The third execution tier, completing the engine's runner family:
  - runner.LocalQueryRunner          — one process, one device
  - parallel.DistributedQueryRunner  — SPMD over the ICI mesh (one host)
  - cluster.ClusterQueryRunner       — coordinator + worker PROCESSES over
    HTTP (the DCN tier): fragments become remote tasks, pages ship as
    serialized frames between hosts

Analogue of the coordinator role of server/PrestoServer.java with
execution/SqlQueryExecution.java:329 (plan -> fragment -> planDistribution ->
schedule -> pull root output). The same SubPlan the mesh runner lowers to
collectives is here lowered to remote tasks — AddExchanges and the fragmenter
are shared, which is the plugin-boundary discipline the reference gets from
its SPI.

Fault tolerance (retry_policy session property — see cluster/retry.py):
under QUERY/TASK policy a retryable failure (dead worker, dropped exchange,
transport fault) transparently re-plans and re-executes the query on the
surviving nodes — failed nodes are excluded from the next attempt's
placement, attempts are bounded by query_retry_attempts, and attempts are
separated by the shared jittered Backoff. Retry observability lands in
QueryResult.stats and the /v1/metrics counters (cluster.query_retries,
cluster.task_retries, cluster.faults_injected, cluster.backoff_seconds)."""
from __future__ import annotations

import itertools
import json
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Set

from ..metadata import CatalogManager, Session
from ..runner import LocalQueryRunner, QueryResult
from ..sql import tree as t
from ..sql.planner.add_exchanges import add_exchanges
from ..sql.planner.fragmenter import SubPlan, fragment_plan
from ..sql.planner.optimizer import optimize
from ..sql.planner.planner import LogicalPlanner
from ..utils import trace
from ..utils.metrics import METRICS
from . import faults, retry
from .discovery import DiscoveryNodeManager, HeartbeatFailureDetector, NodeInfo
from .exchange_client import StreamingRemoteSource
from .retry import Backoff
from .scheduler import SqlQueryScheduler
from .task import FINISHED, plan_subplan


class ClusterQueryRunner:
    """Coordinator engine: plans locally, executes on announced workers."""

    def __init__(self, session: Optional[Session] = None,
                 catalogs: Optional[CatalogManager] = None,
                 min_workers: int = 1,
                 worker_wait_s: float = 30.0,
                 cluster_memory_limit_bytes: Optional[int] = None):
        faults.install_from_env()  # PRESTO_TPU_FAULTS chaos knob (no-op unset)
        self.local = LocalQueryRunner(session, catalogs)
        self.nodes = DiscoveryNodeManager()
        self.detector = HeartbeatFailureDetector(self.nodes).start()
        self.min_workers = min_workers
        self.worker_wait_s = worker_wait_s
        self._ids = itertools.count(1)
        self._schedulers: Dict[str, SqlQueryScheduler] = {}
        self.memory_manager = None
        if cluster_memory_limit_bytes is not None:
            from .memory_manager import ClusterMemoryManager

            self.memory_manager = ClusterMemoryManager(
                self.nodes, kill_query=self._kill_query,
                limit_bytes=cluster_memory_limit_bytes).start()

    def _kill_query(self, query_id: str) -> None:
        """OOM-killer target: abort every task of the victim query
        (ClusterMemoryManager -> LowMemoryKiller -> fail query)."""
        sched = self._schedulers.get(query_id)
        if sched is not None:
            sched.abort()

    # ------------------------------------------------------- cluster lifecycle

    def drain_worker(self, node_id: str, signal: Optional[dict] = None,
                     wait_s: float = 60.0) -> dict:
        """Gracefully remove one worker with zero queries lost: mark it
        unschedulable, tell it to DRAIN (refuse new tasks, pin spools),
        proactively hand its live tasks to replacements through the
        mid-stream replay path (exactly-once splice — a PLANNED drain never
        410-escalates), wait for the node to report DRAINED, then deregister
        it from discovery. `signal` is journaled on `node.draining` so the
        record says WHY the node was drained (autoscaler pressure reading,
        rolling upgrade, operator action)."""
        from ..utils import events

        node = self.nodes.get(node_id)
        if node is None:
            raise ValueError(f"unknown worker node {node_id!r}")
        self.nodes.set_draining(node_id, True)
        events.emit("node.draining", severity=events.WARN, node=node_id,
                    signal=signal or {})
        try:
            req = urllib.request.Request(f"{node.uri}/v1/info/state",
                                         data=b'"DRAINING"', method="PUT")
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:  # noqa: BLE001 - unreachable worker: the
            pass           # schedulable gate + task sweep below still drain it

        def sweep() -> tuple:
            moved = left = 0
            active = self.nodes.active_nodes()
            for sched in list(self._schedulers.values()):
                m, l_ = sched.drain_node(node_id, active)
                moved += m
                left += l_
            return moved, left

        from .retry import Backoff

        moved, left = sweep()
        state = self._worker_state(node)
        deadline = time.monotonic() + wait_s
        backoff = Backoff(initial_delay_s=0.05, max_delay_s=0.25)
        while state == "DRAINING" and time.monotonic() < deadline:
            backoff.failure()
            backoff.wait()
            # keep sweeping: a task created between the gate and the first
            # sweep, or one whose handoff was refused, must not wedge the
            # drain while its query still runs
            m, left = sweep()
            moved += m
            state = self._worker_state(node)
        drained = state in ("DRAINED", "SHUT_DOWN")
        self.nodes.remove(node_id)
        events.emit("node.drained", severity=events.INFO, node=node_id,
                    drained=drained, state=state or "UNREACHABLE",
                    tasks_handed_off=moved, signal=signal or {})
        return {"node": node_id, "drained": drained,
                "state": state or "UNREACHABLE", "tasks_handed_off": moved,
                "tasks_left_in_place": left}

    @staticmethod
    def _worker_state(node: NodeInfo) -> Optional[str]:
        """GET /v1/info/state — the drain-progress poll. None = unreachable
        (a worker that died mid-drain; discovery expiry owns that case)."""
        try:
            with urllib.request.urlopen(f"{node.uri}/v1/info/state",
                                        timeout=2.0) as resp:
                return json.loads(resp.read()).get("state")
        except Exception:  # noqa: BLE001 - dead node reads as UNREACHABLE
            return None

    @property
    def metadata(self):
        return self.local.metadata

    @property
    def session(self):
        return self.local.session

    # ------------------------------------------------------------- planning

    def plan_sql(self, sql: str) -> SubPlan:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            raise ValueError(f"cannot cluster-plan {type(stmt).__name__}")
        return self.plan_statement(stmt)

    def plan_statement(self, stmt: t.Query) -> SubPlan:
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        n = max(len(self.nodes.schedulable_nodes()), 1)
        plan = add_exchanges(plan, planner.symbols, self.metadata, self.session,
                             n_workers=n)
        return fragment_plan(plan)

    # ------------------------------------------------------------ execution

    def _wait_for_workers(self, min_needed: Optional[int] = None,
                          exclude: Optional[Set[str]] = None) -> List[NodeInfo]:
        from .retry import Backoff

        min_needed = self.min_workers if min_needed is None else min_needed
        deadline = time.monotonic() + self.worker_wait_s
        backoff = Backoff(initial_delay_s=0.02, max_delay_s=0.25)
        while True:
            # placement view: draining nodes are alive (they keep serving
            # their spooled streams) but never receive new tasks
            nodes = self.nodes.schedulable_nodes()
            if exclude:
                eligible = [n for n in nodes if n.node_id not in exclude]
                # all survivors excluded = exclusion starved placement;
                # trying suspect nodes beats certain failure
                nodes = eligible or nodes
            if len(nodes) >= min_needed:
                return sorted(nodes, key=lambda n: n.node_id)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"only {len(nodes)} active workers "
                    f"(need {min_needed})")
            backoff.failure()
            backoff.wait()

    def execute(self, sql: str, user=None) -> QueryResult:
        stmt = self.local.parser.parse(sql)
        # access control is enforced at the coordinator for EVERY statement
        # (the local engine re-checks the ones it executes itself)
        self.local._check_access(stmt, user)
        explain_analyze = isinstance(stmt, t.Explain) and stmt.analyze and \
            isinstance(stmt.statement, t.Query)
        if not explain_analyze and not isinstance(stmt, t.Query):
            # DDL/DML/EXPLAIN/SHOW run on the coordinator's local engine
            return self.local.execute(sql, user=user)
        session = self.local.session
        spec = session.get("fault_injection")
        # session-spec injectors are scoped to THIS query: a process-global
        # leak would keep injecting chaos into every later query. A
        # programmatically installed injector (tests) or the env-var one
        # (worker processes) always wins and is left alone.
        installed_here = False
        if spec and faults.active() is None:
            faults.install(faults.FaultInjector.from_spec(
                str(spec), seed=int(session.get("fault_seed") or 0)))
            installed_here = True
        try:
            if explain_analyze:
                # distributed EXPLAIN ANALYZE: run on the workers and roll
                # their TaskInfo operator stats up per fragment (before
                # this, ANALYZE profiled the coordinator's local engine)
                return self._instrumented(
                    session, lambda: self._explain_analyze(stmt.statement))
            return self._execute_query(sql, session)
        finally:
            if installed_here:
                faults.clear()

    def _instrumented(self, session: Session, run) -> QueryResult:
        """Trace + wall-histogram wrapper: the coordinator's flight recorder
        captures lifecycle spans plus every task-create/poll and result-pull
        HTTP call (the `http` category). The lifecycle span only opens when
        THIS query's recorder actually installed — an untraced query running
        concurrently with a traced one must not write into its timeline.

        Correlation: with the protocol layer in front, the ambient progress
        scope already carries the client-visible query id and the recorder
        inherits it. Used directly (embedded coordinator, tests), no scope
        exists — bind the recorder's id so the internal per-attempt cq* ids
        journaled below it pick up a corr_id and one filter finds both."""
        import time as _time

        from ..exec import progress

        scope = None
        t0 = _time.perf_counter()
        rec = trace.maybe_recorder(session)
        installed = rec is not None and trace.install(rec)
        try:
            if rec is not None and rec.query_id \
                    and progress.current_query_id() is None:
                # bind scope only after a successful __enter__: the finally
                # below must not __exit__ a scope that was never entered
                s = progress.query_scope(rec.query_id)
                s.__enter__()
                scope = s
            if installed:
                with rec.span(trace.LIFECYCLE, "query"):
                    result = run()
            else:
                result = run()
        except BaseException as e:
            # failure forensics: a FAILED / OOM-killed / retry-exhausted
            # query dumps its always-on coarse ring (task-create/poll HTTP,
            # result pulls, retry lifecycle) pinned to the exception — the
            # protocol layer serves it at GET /v1/query/{id}/trace
            if installed:
                trace.attach_failure(e, rec, session)
            raise
        finally:
            if scope is not None:
                scope.__exit__(None, None, None)
            if installed:
                trace.uninstall(rec)
        METRICS.histogram("query.wall_s", _time.perf_counter() - t0)
        if installed and not rec.coarse:
            result.trace_path = trace.export(rec, session)
        return result

    def _execute_query(self, sql: str, session: Session) -> QueryResult:
        return self._instrumented(
            session, lambda: self._execute_with_retries(sql, session))

    def _execute_with_retries(self, sql: str, session: Session) -> QueryResult:
        def prop(name, default):
            # Session.DEFAULTS (metadata.py) is the canonical source; the
            # fallback here only guards a property explicitly set to None.
            # 0 is a valid value for every retry knob
            value = session.get(name)
            return default if value is None else value

        policy = retry.retry_policy(session)
        max_retries = int(prop("query_retry_attempts", 2)) \
            if policy != retry.NONE else 0
        backoff = Backoff(
            max_failure_interval_s=float("inf"),
            initial_delay_s=float(prop("retry_initial_delay_s", 0.1)),
            max_delay_s=float(prop("retry_max_delay_s", 2.0)))
        excluded: Set[str] = set()
        injector = faults.active()
        faults_before = injector.total_fired if injector else 0
        stats = {"retry_policy": policy, "query_attempts": 0,
                 "task_attempts": 0, "task_retries": 0,
                 "task_speculations": 0, "faults_injected": 0,
                 "backoff_s": 0.0}
        failure_trace: Optional[str] = None
        while True:
            stats["query_attempts"] += 1
            try:
                result = self._execute_attempt(
                    sql, policy, excluded, stats,
                    first_attempt=stats["query_attempts"] == 1)
                break
            except BaseException as e:  # noqa: BLE001 — classified below
                retryable = retry.is_retryable(e)
                # exclude on NODE-level evidence (death, rejected creates) —
                # a TaskFailedError's node is usually just where a dead
                # peer's stream failure SURFACED, not the culprit
                if isinstance(e, retry.ClusterExecutionError) and e.node_id \
                        and not isinstance(e, retry.TaskFailedError):
                    excluded.add(e.node_id)
                if not retryable \
                        or stats["query_attempts"] > max_retries:
                    raise
                METRICS.count("cluster.query_retries")
                # the query will be retried and may well SUCCEED — dump the
                # failed attempt's coarse ring now (first failure wins, it
                # saw the original fault) so the eventual QueryResult still
                # carries the forensic of what went wrong mid-flight
                rec = trace.active()
                if failure_trace is None and rec is not None:
                    try:
                        failure_trace = trace.export(rec, session,
                                                     suffix="-forensic")
                    except Exception:  # noqa: BLE001 - forensics best-effort
                        pass
                from ..utils import events
                events.emit("query.retry", severity=events.WARN,
                            attempt=stats["query_attempts"],
                            error=type(e).__name__, message=str(e)[:300],
                            excluded_nodes=sorted(excluded))
                backoff.failure()
                backoff.wait()
        stats["backoff_s"] = round(
            stats["backoff_s"] + backoff.total_backoff_s, 3)
        stats["faults_injected"] = (injector.total_fired - faults_before) \
            if injector else 0
        METRICS.count("cluster.backoff_seconds", stats["backoff_s"])
        result.stats = stats
        result.failure_trace_path = failure_trace
        return result

    def _execute_attempt(self, sql: str, policy: str, excluded: Set[str],
                         stats: dict, first_attempt: bool) -> QueryResult:
        """One full plan->schedule->pull attempt. Re-planning per attempt is
        deliberate: the surviving node count changes the exchange layout."""
        # a retry only needs SOME healthy workers, not the original quorum
        nodes = self._wait_for_workers(
            min_needed=self.min_workers if first_attempt else 1,
            exclude=excluded)
        sub = self.plan_sql(sql)
        query_id = f"cq{next(self._ids)}_{int(time.time())}"
        scheduler = SqlQueryScheduler(query_id, sub, nodes,
                                      self.local.session,
                                      retry_policy=policy,
                                      excluded_nodes=excluded)
        self._schedulers[query_id] = scheduler
        unregister = self._register_progress(query_id, scheduler)
        try:
            scheduler.schedule()
            return self._pull_results(scheduler, sub)
        except BaseException as e:
            from ..utils import events
            events.emit("query.attempt_failed", severity=events.ERROR,
                        query_id=query_id, error=type(e).__name__,
                        message=str(e)[:300])
            scheduler.abort()
            raise
        finally:
            unregister()
            stats["task_attempts"] += scheduler.task_attempts
            stats["task_retries"] += scheduler.task_retries
            stats["task_speculations"] += scheduler.task_speculations
            stats["backoff_s"] += scheduler.backoff_s
            self._schedulers.pop(query_id, None)
            # free finished tasks' buffers/state on the workers
            for task in scheduler.all_tasks():
                task.cancel(abort=False)

    @staticmethod
    def _register_progress(query_id: str, scheduler: SqlQueryScheduler):
        """Live progress (exec/progress.py): while the attempt runs, serve
        the freshest TaskInfo.operator_stats the monitor's 0.5s polls
        already collect, rolled up cluster-side — per-operator rows/blocked
        counters of a RUNNING query at GET /v1/query/{id}. No extra RPCs:
        the provider re-reads the cached infos."""
        from ..exec import progress

        def live() -> dict:
            ops = []
            for task in scheduler.all_tasks():
                info = task.info
                if info is not None and info.operator_stats:
                    ops.extend(info.operator_stats)
            return {"operators": ops}
        return progress.register(live)

    def _explain_analyze(self, stmt: t.Query) -> QueryResult:
        """Distributed EXPLAIN ANALYZE: schedule the inner query on the
        workers, pull its results, then render per-fragment per-operator
        stats (rows / wall / blocked / peak-mem) rolled up from every
        task's TaskInfo.operator_stats — the same table the local runner's
        _explain_analyze prints, via the shared exec/explain renderer.

        Deliberately single-attempt (no query-level retry): ANALYZE's whole
        point is the profile of the run that happened — transparently
        re-running after a mid-query failure would report a retry's stats
        as if they were the query's. A retryable failure surfaces to the
        caller, who re-issues for a fresh profile."""
        import time as _time

        from ..exec.explain import rollup, table

        session = self.local.session
        nodes = self._wait_for_workers()
        sub = self.plan_statement(stmt)
        query_id = f"cq{next(self._ids)}_{int(time.time())}"
        scheduler = SqlQueryScheduler(query_id, sub, nodes, session)
        self._schedulers[query_id] = scheduler
        t0 = _time.perf_counter()
        try:
            scheduler.schedule()
            self._pull_results(scheduler, sub)
            wall = _time.perf_counter() - t0
            lines = [f"Query: {wall * 1000:.0f}ms wall, "
                     f"{len(sub.fragments)} fragments, "
                     f"{len(scheduler.all_tasks())} tasks on "
                     f"{len(nodes)} workers", ""]
            # one shared re-poll budget for the whole stats render
            deadline = time.monotonic() + 5.0
            for frag in sub.fragments:
                stage = scheduler.stages.get(frag.id)
                tasks = stage.tasks if stage is not None else []
                head = f"Fragment {frag.id} [{frag.partitioning}]"
                if frag.output_kind:
                    head += f" output={frag.output_kind}"
                head += f" tasks={len(tasks)}"
                lines.append(head)
                stats = []
                for task in tasks:
                    # deterministic final-state stats: the cached info is
                    # usually a MID-RUN monitor poll (racing the scan's
                    # input accounting — the old `TableScan In=0` flake);
                    # re-poll until the task reports a DONE state, whose
                    # TaskInfo carries the stats snapshot SqlTask froze
                    # before its terminal transition. The budget is shared
                    # across the WHOLE render (one deadline, not 5s per
                    # task): tasks legitimately still RUNNING at render
                    # time (abandoned producers of a satisfied LIMIT) fall
                    # back to their freshest mid-run stats, as before.
                    info = self._final_task_info(task, deadline=deadline)
                    if info is not None and info.operator_stats:
                        stats.extend(info.operator_stats)
                if stats:
                    lines += table(rollup(stats), indent="  ")
                else:
                    lines.append("  (no operator stats reported)")
                lines.append("")
            return QueryResult([[line] for line in lines], ["Query Plan"])
        except BaseException:
            scheduler.abort()
            raise
        finally:
            self._schedulers.pop(query_id, None)
            for task in scheduler.all_tasks():
                task.cancel(abort=False)

    @staticmethod
    def _final_task_info(task, deadline: Optional[float] = None,
                         budget_s: float = 5.0):
        """The task's DONE-state TaskInfo (deterministic final stats), or
        the freshest available when `deadline` (shared by the caller across
        ALL its tasks — a per-task budget would stack) passes first. The
        root output was already fully consumed when this runs, so tasks are
        normally finishing and the re-poll window is one round trip; a task
        legitimately still RUNNING (an abandoned producer of a satisfied
        LIMIT) falls back to its freshest mid-run stats."""
        from .task import DONE_STATES

        info = task.info
        if info is not None and info.state in DONE_STATES:
            return info
        backoff = Backoff(initial_delay_s=0.01, max_delay_s=0.2)
        if deadline is None:
            deadline = time.monotonic() + budget_s
        while time.monotonic() < deadline:
            polled = task.poll_info()
            if polled is not None:
                info = polled
                if info.state in DONE_STATES:
                    return info
            backoff.failure()
            backoff.wait()
        return info

    def _root_schema(self, scheduler: SqlQueryScheduler, sub: SubPlan):
        """Derive the root fragment's output types + dictionaries by running
        the same deterministic local planning every worker runs — schema is a
        plan-time property, never shipped (see cluster.task.plan_subplan)."""
        task_counts = {f.id: len(s.tasks)
                       for f, s in ((st.fragment, st)
                                    for st in scheduler.stages.values())}
        plans = plan_subplan(sub, self.metadata, self.local.session,
                             task_counts)
        ep = plans[sub.root_fragment.id][1]
        return ep.output_types, ep.output_dicts

    def _pull_results(self, scheduler: SqlQueryScheduler,
                      sub: SubPlan) -> QueryResult:
        root = scheduler.root_task()
        types, dicts = self._root_schema(scheduler, sub)
        rows: List[list] = []
        done = threading.Event()
        error: List[BaseException] = []

        from .exchange_client import _MAX_ERROR_S
        budget = self.session.get("exchange_error_budget_s")

        def pull():
            try:
                source = StreamingRemoteSource(
                    [root.location], 0, types, dicts,
                    int(self.session.get("page_capacity") or (1 << 16)),
                    error_budget_s=float(
                        _MAX_ERROR_S if budget is None else budget))
                # hand the in-process consumer to the scheduler: root-task
                # recovery rewires its chunk cursor directly (there is no
                # worker-side /sources endpoint for the coordinator)
                scheduler.register_root_consumer(source)
                for page in source:
                    rows.extend(page.to_pylists())
            except BaseException as e:  # noqa: BLE001
                error.append(e)
            finally:
                done.set()

        puller = threading.Thread(target=pull, name="result-pull",
                                  daemon=True)
        puller.start()
        while not done.wait(timeout=0.5):
            active = self.nodes.active_nodes()
            scheduler.check_failures(active_nodes=active)
            scheduler.maybe_speculate(active)
        # `done` is set in pull()'s finally, so the thread is exiting: the
        # bounded join keeps it from outliving the query (and from racing a
        # teardown of `rows`/`error`, which it captured by closure)
        puller.join(timeout=5.0)
        if error:
            # surface the task/node failure that CAUSED the stream error if
            # there is one — it names the node, which retry placement and
            # fail-fast diagnostics both need. Diagnosis only: this attempt
            # is already lost, recovering a task here would be wasted work
            # that also swallows the node id
            scheduler.check_failures(active_nodes=self.nodes.active_nodes(),
                                     recover=False)
            raise error[0]
        info = root.poll_info()
        if info is not None and info.state != FINISHED:
            scheduler.check_failures()
        return QueryResult(rows, sub.column_names, types)
