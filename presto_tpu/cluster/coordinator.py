"""ClusterQueryRunner: SQL over a real multi-process worker cluster.

The third execution tier, completing the engine's runner family:
  - runner.LocalQueryRunner          — one process, one device
  - parallel.DistributedQueryRunner  — SPMD over the ICI mesh (one host)
  - cluster.ClusterQueryRunner       — coordinator + worker PROCESSES over
    HTTP (the DCN tier): fragments become remote tasks, pages ship as
    serialized frames between hosts

Analogue of the coordinator role of server/PrestoServer.java with
execution/SqlQueryExecution.java:329 (plan -> fragment -> planDistribution ->
schedule -> pull root output). The same SubPlan the mesh runner lowers to
collectives is here lowered to remote tasks — AddExchanges and the fragmenter
are shared, which is the plugin-boundary discipline the reference gets from
its SPI.
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional

from ..metadata import CatalogManager, Session
from ..runner import LocalQueryRunner, QueryResult
from ..sql import tree as t
from ..sql.planner.add_exchanges import add_exchanges
from ..sql.planner.fragmenter import SubPlan, fragment_plan
from ..sql.planner.optimizer import optimize
from ..sql.planner.planner import LogicalPlanner
from .discovery import DiscoveryNodeManager, HeartbeatFailureDetector
from .exchange_client import StreamingRemoteSource
from .scheduler import SqlQueryScheduler
from .task import FINISHED, plan_subplan


class ClusterQueryRunner:
    """Coordinator engine: plans locally, executes on announced workers."""

    def __init__(self, session: Optional[Session] = None,
                 catalogs: Optional[CatalogManager] = None,
                 min_workers: int = 1,
                 worker_wait_s: float = 30.0,
                 cluster_memory_limit_bytes: Optional[int] = None):
        self.local = LocalQueryRunner(session, catalogs)
        self.nodes = DiscoveryNodeManager()
        self.detector = HeartbeatFailureDetector(self.nodes).start()
        self.min_workers = min_workers
        self.worker_wait_s = worker_wait_s
        self._ids = itertools.count(1)
        self._schedulers: Dict[str, SqlQueryScheduler] = {}
        self.memory_manager = None
        if cluster_memory_limit_bytes is not None:
            from .memory_manager import ClusterMemoryManager

            self.memory_manager = ClusterMemoryManager(
                self.nodes, kill_query=self._kill_query,
                limit_bytes=cluster_memory_limit_bytes).start()

    def _kill_query(self, query_id: str) -> None:
        """OOM-killer target: abort every task of the victim query
        (ClusterMemoryManager -> LowMemoryKiller -> fail query)."""
        sched = self._schedulers.get(query_id)
        if sched is not None:
            sched.abort()

    @property
    def metadata(self):
        return self.local.metadata

    @property
    def session(self):
        return self.local.session

    # ------------------------------------------------------------- planning

    def plan_sql(self, sql: str) -> SubPlan:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            raise ValueError(f"cannot cluster-plan {type(stmt).__name__}")
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        n = max(len(self.nodes.active_nodes()), 1)
        plan = add_exchanges(plan, planner.symbols, self.metadata, self.session,
                             n_workers=n)
        return fragment_plan(plan)

    # ------------------------------------------------------------ execution

    def _wait_for_workers(self) -> List:
        deadline = time.monotonic() + self.worker_wait_s
        while True:
            nodes = self.nodes.active_nodes()
            if len(nodes) >= self.min_workers:
                return sorted(nodes, key=lambda n: n.node_id)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"only {len(nodes)} active workers "
                    f"(need {self.min_workers})")
            time.sleep(0.1)

    def execute(self, sql: str, user=None) -> QueryResult:
        stmt = self.local.parser.parse(sql)
        # access control is enforced at the coordinator for EVERY statement
        # (the local engine re-checks the ones it executes itself)
        self.local._check_access(stmt, user)
        if not isinstance(stmt, t.Query):
            # DDL/DML/EXPLAIN/SHOW run on the coordinator's local engine
            return self.local.execute(sql, user=user)
        sub = self.plan_sql(sql)
        nodes = self._wait_for_workers()
        query_id = f"cq{next(self._ids)}_{int(time.time())}"
        scheduler = SqlQueryScheduler(query_id, sub, nodes,
                                      self.local.session)
        self._schedulers[query_id] = scheduler
        scheduler.schedule()
        try:
            return self._pull_results(scheduler, sub)
        except BaseException:
            scheduler.abort()
            raise
        finally:
            self._schedulers.pop(query_id, None)
            # free finished tasks' buffers/state on the workers
            for task in scheduler.all_tasks():
                task.cancel(abort=False)

    def _root_schema(self, scheduler: SqlQueryScheduler, sub: SubPlan):
        """Derive the root fragment's output types + dictionaries by running
        the same deterministic local planning every worker runs — schema is a
        plan-time property, never shipped (see cluster.task.plan_subplan)."""
        task_counts = {f.id: len(s.tasks)
                       for f, s in ((st.fragment, st)
                                    for st in scheduler.stages.values())}
        plans = plan_subplan(sub, self.metadata, self.local.session,
                             task_counts)
        ep = plans[sub.root_fragment.id][1]
        return ep.output_types, ep.output_dicts

    def _pull_results(self, scheduler: SqlQueryScheduler,
                      sub: SubPlan) -> QueryResult:
        root = scheduler.root_task()
        types, dicts = self._root_schema(scheduler, sub)
        rows: List[list] = []
        done = threading.Event()
        error: List[BaseException] = []

        def pull():
            try:
                source = StreamingRemoteSource(
                    [root.location], 0, types, dicts,
                    int(self.session.get("page_capacity") or (1 << 16)))
                for page in source:
                    rows.extend(page.to_pylists())
            except BaseException as e:  # noqa: BLE001
                error.append(e)
            finally:
                done.set()

        threading.Thread(target=pull, name="result-pull", daemon=True).start()
        while not done.wait(timeout=0.5):
            active = {n.node_id for n in self.nodes.active_nodes()}
            scheduler.check_failures(active_node_ids=active)
        if error:
            scheduler.check_failures()  # surface a task failure if one caused it
            raise error[0]
        info = root.poll_info()
        if info is not None and info.state != FINISHED:
            scheduler.check_failures()
        return QueryResult(rows, sub.column_names, types)
