"""Worker task runtime: task state machine, output partitioning, task manager.

Analogues (/root/reference/presto-main):
  - execution/TaskStateMachine.java + TaskState (PLANNED/RUNNING/FLUSHING/
    FINISHED/CANCELED/ABORTED/FAILED)
  - execution/SqlTaskManager.java:84,351 (create-or-update semantics, cleanup)
  - execution/SqlTaskExecution.java:82 (fragment -> local plan -> drivers)
  - operator/PartitionedOutputOperator.java:297,380-440 (the sink that routes
    rows to consumer buffers) and TaskOutputOperator.java:149 (single buffer)

A task executes ONE fragment of a query on ONE worker: it locally plans the
shipped SubPlan bottom-up (so string-dictionary identities stay coherent within
this process — the plan, not pickled dictionaries, is the source of truth),
wires RemoteSourceNodes to streaming HTTP exchange clients, replaces the sink
with a partitioned output buffer, and drives the pipelines on the worker's
task executor threads."""
from __future__ import annotations

import dataclasses
import threading
import time
import traceback
import uuid
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..block import Dictionary, Page
from . import codec
from ..exec.local_planner import LocalExecutionPlanner
from ..exec.task_executor import TaskExecutor
from ..metadata import MetadataManager, Session
from ..ops.operator import Operator, OperatorContext, OperatorFactory
from ..sql.planner.fragmenter import SINGLE_PART, SubPlan
from ..sql.planner.plan import BROADCAST, GATHER, OutputNode, REPARTITION
from ..types import Type
from . import buffers, faults
from .exchange_client import StreamingRemoteSource
from .serde import pages_to_columns, serialize_columns

# TaskState vocabulary (execution/TaskState.java)
PLANNED = "PLANNED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
CANCELED = "CANCELED"
ABORTED = "ABORTED"
FAILED = "FAILED"
DONE_STATES = {FINISHED, CANCELED, ABORTED, FAILED}


def mix64_np(x: np.ndarray) -> np.ndarray:
    """numpy twin of ops/hash_join._mix64 — same constants, so cluster routing
    and kernel hashing can never disagree."""
    x = x.astype(np.uint64)
    x = (x ^ (x >> np.uint64(33))) * np.uint64(0xFF51AFD7ED558CCD)
    x = (x ^ (x >> np.uint64(33))) * np.uint64(0xC4CEB9FE1A85EC53)
    return x ^ (x >> np.uint64(33))


def combined_key_np(keys: Sequence[np.ndarray]) -> np.ndarray:
    if len(keys) == 1:
        return keys[0].astype(np.int64)
    acc = mix64_np(keys[0].astype(np.int64))
    for k in keys[1:]:
        acc = mix64_np(acc ^ (k.astype(np.int64).astype(np.uint64)
                              * np.uint64(0x9E3779B97F4A7C15)))
    return acc.astype(np.int64)


def partition_ids_np(key: np.ndarray, n_parts: int) -> np.ndarray:
    return (mix64_np(key) % np.uint64(n_parts)).astype(np.int32)


@codec.register
@dataclasses.dataclass
class TaskUpdateRequest:
    """POST /v1/task/{taskId} body (JSON via cluster/codec) — the
    fragment+wiring a worker needs (server/TaskUpdateRequest.java analogue)."""
    task_id: str
    query_id: str
    subplan: SubPlan                      # the WHOLE query's fragments
    fragment_id: int                      # which fragment THIS task runs
    worker_index: int                     # this task's index in the fragment
    task_counts: Dict[int, int]           # fragment id -> task count
    # fragment id -> ordered producer-task result locations (".../results" base)
    input_locations: Dict[int, List[str]]
    session: Session
    output_buffers: int = 1               # consumer count for this task's output


@codec.register
@dataclasses.dataclass
class TaskInfo:
    task_id: str
    state: str
    error: Optional[dict] = None
    rows_out: int = 0
    instance_id: str = ""
    # per-operator stats of this task's drivers (OperatorStats.to_dict
    # dicts, tagged with their pipeline index) — the worker half of
    # distributed EXPLAIN ANALYZE: the coordinator rolls every task's list
    # up per fragment, exactly as the reference ships OperatorStats inside
    # TaskStatus for its coordinator-side QueryStats roll-up
    operator_stats: Optional[List[dict]] = None


@codec.register
@dataclasses.dataclass
class SourceUpdateRequest:
    """POST /v1/task/{taskId}/sources body: rewire one exchange input from a
    failed producer to its replacement (task-level retry). The worker accepts
    only while the affected stream is virgin — nothing consumed yet."""
    fragment_id: int
    old_location: str
    new_location: str


def plan_subplan(subplan: SubPlan, metadata: MetadataManager, session: Session,
                 task_counts: Dict[int, int], target_fragment_id=None,
                 sink_factory=None, memory=None, pool_key=None):
    """Locally plan every fragment bottom-up, threading producer output
    dictionaries into consumers (the mesh runner's pattern). Returns
    {fragment_id: (LocalExecutionPlanner, LocalExecutionPlan)}.

    Every cluster participant runs this same deterministic planning over its
    own metadata — schema (types + dictionary identities) is a plan-time
    property agreed by construction, so neither types nor dictionaries ever
    ride the wire (the reference ships block encodings instead)."""
    from ..sql.planner.plan import MERGE, SortNode

    merge_frags = {f.id: f for f in subplan.fragments
                   if f.output_kind == MERGE and f.output_orderings}
    frag_dicts: Dict[int, List[Optional[Dictionary]]] = {}
    plans = {}
    for frag in subplan.fragments:
        # consumer half first: a Sort directly over a MERGE remote source is
        # the N-way merge — drop the full re-sort, record the merge spec so
        # the task wires a MergingRemoteSource into the slot
        merge_slots: Dict[int, list] = {}
        body = _strip_merge_sorts(frag.root, merge_frags, merge_slots)
        if frag.id in merge_frags:
            # producer half of the distributed sort: each task sorts ITS
            # rows locally so the consumer can heap-merge streams instead
            # of re-sorting everything (MergeOperator.java's contract)
            body = SortNode(body, list(frag.output_orderings))
        if frag is subplan.root_fragment:
            root = OutputNode(body, subplan.column_names,
                              subplan.output_symbols)
        else:
            syms = body.outputs()
            root = OutputNode(body, [s.name for s in syms], syms)
        lp = LocalExecutionPlanner(metadata, session,
                                   n_workers=task_counts.get(frag.id, 1),
                                   remote_dicts=frag_dicts,
                                   pool_key=pool_key)
        if memory is not None:
            # worker-side unified accounting: operator state AND scan
            # prefetch of this task reserve in the worker's shared pool
            # under the query id, which /v1/status ships to the cluster
            # memory manager's OOM policy
            lp.attach_memory(*memory)
        sf = sink_factory if frag.id == target_fragment_id else None
        ep = lp.plan(root, sink_factory=sf)
        for fid, orderings in merge_slots.items():
            slot = lp.remote_slots.get(fid)
            if slot is not None:
                producer_syms = merge_frags[fid].root.outputs()
                names = [s.name for s in producer_syms]
                slot.merge_orderings = [
                    (names.index(o.symbol.name), o.descending, o.nulls_first)
                    for o in orderings]
        frag_dicts[frag.id] = ep.output_dicts
        plans[frag.id] = (lp, ep)
    return plans


def _strip_merge_sorts(node, merge_frags, out: Dict[int, list]):
    """Replace SortNode(RemoteSourceNode(fid)) with the bare remote source
    when fragment fid's output is MERGE (its tasks pre-sorted), recording
    the orderings per fragment id."""
    from ..sql.planner.plan import RemoteSourceNode, SortNode

    if isinstance(node, SortNode) and \
            isinstance(node.source, RemoteSourceNode) and \
            node.source.fragment_id in merge_frags:
        out[node.source.fragment_id] = list(node.orderings)
        return node.source
    children = node.children()
    if not children:
        return node
    new_children = [_strip_merge_sorts(c, merge_frags, out) for c in children]
    if all(a is b for a, b in zip(children, new_children)):
        return node
    return node.with_children(new_children)


class TaskOutputOperator(Operator):
    """Sink: partition/broadcast this task's output pages into its
    OutputBuffer as serialized frames (PartitionedOutputOperator analogue;
    rows accumulate per partition and flush at page granularity)."""

    def __init__(self, context: OperatorContext, types: List[Type],
                 output: buffers.OutputBuffer, kind: str,
                 key_idx: Optional[List[int]], flush_rows: int):
        super().__init__(context)
        self._types = types
        self.output = output
        self.kind = kind
        self.key_idx = key_idx
        self.flush_rows = flush_rows
        ncols = len(types)
        self._acc: List[List[List[np.ndarray]]] = [
            [[] for _ in range(2 * ncols)] for _ in range(output.n_buffers)]
        self._acc_rows = [0] * output.n_buffers
        self.rows_out = 0

    @property
    def output_types(self) -> List[Type]:
        return self._types

    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        datas, nulls, nrows = pages_to_columns([page], self._types)
        if nrows == 0:
            return
        self.rows_out += nrows
        ncols = len(self._types)
        nulls = [n if n is not None else np.zeros(nrows, dtype=bool)
                 for n in nulls]
        if self.kind == BROADCAST:
            frame = serialize_columns(datas, [n if n.any() else None
                                              for n in nulls], nrows)
            self.output.enqueue_broadcast(frame)
            return
        if self.kind == GATHER or self.output.n_buffers == 1 or \
                self.key_idx is None:
            # GATHER, and MERGE on the HTTP tier (key_idx is None): funnel to
            # consumer 0, which then does the whole sort — the range-split
            # distributed sort is an SPMD-tier feature (parallel/runner.py);
            # the HTTP data plane keeps the reference's single-merger shape
            self._append(0, datas, nulls, None)
        else:
            keys = [np.where(nulls[i], 0, datas[i]).astype(np.int64)
                    for i in self.key_idx]
            pid = partition_ids_np(combined_key_np(keys),
                                   self.output.n_buffers)
            order = np.argsort(pid, kind="stable")
            pid_s = pid[order]
            bounds = np.searchsorted(pid_s, np.arange(self.output.n_buffers + 1))
            for b in range(self.output.n_buffers):
                sel = order[bounds[b]:bounds[b + 1]]
                if len(sel):
                    self._append(b, datas, nulls, sel)
        for b in range(self.output.n_buffers):
            if self._acc_rows[b] >= self.flush_rows:
                self._flush(b)

    def _append(self, b: int, datas, nulls, sel) -> None:
        ncols = len(self._types)
        for c in range(ncols):
            self._acc[b][c].append(datas[c] if sel is None else datas[c][sel])
            self._acc[b][ncols + c].append(
                nulls[c] if sel is None else nulls[c][sel])
        self._acc_rows[b] += len(datas[0]) if sel is None else len(sel)

    def _flush(self, b: int) -> None:
        if self._acc_rows[b] == 0:
            return
        ncols = len(self._types)
        datas = [np.concatenate(self._acc[b][c]) for c in range(ncols)]
        nulls = [np.concatenate(self._acc[b][ncols + c]) for c in range(ncols)]
        # emit in flush_rows-sized chunks: one frame = one replayable chunk
        # of the exchange protocol, so chunk granularity tracks the
        # exchange_flush_rows knob even when a single upstream page carries
        # the whole partition (an AGG flushing everything at finish)
        total = self._acc_rows[b]
        for lo in range(0, total, self.flush_rows):
            hi = min(lo + self.flush_rows, total)
            sliced = [n[lo:hi] for n in nulls]
            frame = serialize_columns(
                [d[lo:hi] for d in datas],
                [n if n.any() else None for n in sliced], hi - lo)
            self.output.enqueue(b, frame)
        self._acc[b] = [[] for _ in range(2 * ncols)]
        self._acc_rows[b] = 0

    def get_output(self) -> Optional[Page]:
        return None

    def finish(self) -> None:
        if not self._finishing:
            for b in range(self.output.n_buffers):
                self._flush(b)
            self.output.set_no_more_pages()
        super().finish()


class TaskOutputFactory(OperatorFactory):
    def __init__(self, operator_id: int, types: List[Type],
                 output: buffers.OutputBuffer, kind: str,
                 key_idx: Optional[List[int]], flush_rows: int = 1 << 14):
        super().__init__(operator_id, "TaskOutput")
        self.types = types
        self.output = output
        self.kind = kind
        self.key_idx = key_idx
        self.flush_rows = flush_rows
        self.operators: List[TaskOutputOperator] = []

    def create_operator(self, worker: int = 0) -> TaskOutputOperator:
        op = TaskOutputOperator(
            OperatorContext(self.operator_id, self.name, worker=worker),
            self.types, self.output, self.kind, self.key_idx, self.flush_rows)
        self.operators.append(op)
        if len(self.operators) > 1:
            # two sink drivers interleave their flushes nondeterministically:
            # a re-run would produce a DIFFERENT frame sequence, so cursor
            # replay against this stream would corrupt data — refuse loudly
            self.output.mark_nonreplayable(
                "multiple sink drivers (nondeterministic frame order)")
        return op


class SqlTask:
    """One fragment execution on this worker (execution/SqlTask.java:69)."""

    def __init__(self, request: TaskUpdateRequest, metadata: MetadataManager):
        self.request = request
        self.metadata = metadata
        self.task_id = request.task_id
        # a recreated task restarts result tokens at 0; the instance id lets a
        # consumer detect that (reference: PRESTO_TASK_INSTANCE_ID header)
        self.instance_id = uuid.uuid4().hex
        self.state = PLANNED
        # guards `state`: _run (the task thread) and cancel (an HTTP handler
        # thread) both transition it; unguarded, a cancel landing between
        # _run's cancelled-check and its final assignment could resurrect a
        # CANCELED task as FINISHED (found by prestocheck shared-state-race)
        self._state_lock = threading.Lock()
        self.error: Optional[dict] = None
        self.created = time.time()
        self.cancelled = threading.Event()
        self.output_types: List[Type] = []
        self.output_dicts: List[Optional[Dictionary]] = []
        self._sink: Optional[TaskOutputFactory] = None
        # exchange inputs are rewireable for task-level retry: the scheduler
        # may replace a failed producer's location (update_sources); sources
        # not yet constructed pick up the current list, live ones are reset
        self._src_lock = threading.Lock()
        self._input_locations: Dict[int, List[str]] = {
            fid: list(locs) for fid, locs in request.input_locations.items()}
        self._live_sources: Dict[int, List[object]] = {}
        # kept after planning so info() can report per-operator stats
        # (reads of the plain-int stat fields race benignly mid-run)
        self._drivers: List[object] = []
        # this task's disk-spill manager (exec/spill.py), created lazily by
        # _query_memory when the session enables the disk tier; closed in
        # _run's finally so spill files never outlive the task
        self._spill = None
        # final-state stats snapshot, frozen BEFORE the terminal transition:
        # any TaskInfo that reports a DONE state carries COMPLETE operator
        # stats — a roll-up (distributed EXPLAIN ANALYZE) that polled the
        # task mid-run can re-poll after completion and never again render
        # a TableScan whose input accounting was still in flight
        self._final_stats: Optional[List[dict]] = None
        kind = self._output_kind()
        # acked frames retire into a bounded spool so consumers (or their
        # replacements) can replay from a chunk cursor; spooled bytes are
        # reserved in the worker's process-shared pool under the QUERY id —
        # admission and the OOM killer see replay state as real footprint
        from ..memory import shared_general_pool
        pool = shared_general_pool(
            int(request.session.get("memory_pool_bytes")))
        self.output = buffers.OutputBuffer(
            buffers.BROADCAST if kind == BROADCAST else
            (buffers.GATHER if request.output_buffers == 1
             else buffers.PARTITIONED),
            request.output_buffers,
            spool_max_bytes=int(
                request.session.get("exchange_spool_bytes") or 0),
            reserve=lambda delta: pool.reserve(request.query_id, delta))
        self.thread = threading.Thread(
            target=self._run, name=f"task-{self.task_id}", daemon=True)

    def _output_kind(self) -> str:
        frag = self._fragment()
        return frag.output_kind or GATHER

    def _query_memory(self):
        """This task's memory root in the worker's process-shared pool,
        keyed by QUERY id — every task of one query aggregates into one
        reservation the OOM killer can weigh (runner._query_memory's shape,
        worker-side). The task's disk tier rides along as `memory.spill`:
        PER TASK (concurrent tasks of one query spill into distinct
        directories), but charged to the pool's spill ledger under the
        QUERY id; `_run`'s ``finally`` closes it, releasing exactly this
        task's files and bytes."""
        from ..exec.spill import SpillManager
        from ..memory import QueryContextMemory, shared_general_pool

        req = self.request
        session_bytes = int(req.session.get("memory_pool_bytes"))
        pool = shared_general_pool(session_bytes)
        qmem = QueryContextMemory(
            req.query_id, pool,
            int(req.session.get("query_max_memory_bytes")))
        target = float(req.session.get("revoke_target_fraction"))
        if self._spill is None and bool(req.session.get("spill_to_disk")):
            self._spill = SpillManager(
                req.query_id, pool,
                spill_dir=str(req.session.get("spill_dir") or ""),
                max_bytes=int(req.session.get("spill_max_bytes") or 0),
                tag=str(self.task_id))
        qmem.memory.spill = self._spill

        def over_target() -> bool:
            # pool-wide pressure, or this query alone over its session's
            # budget (the shared pool is grow-only — a small session budget
            # must still trigger revocation while the pool has room)
            return (pool.reserved_bytes() > pool.max_bytes * target
                    or pool.query_bytes(req.query_id)
                    > session_bytes * target)
        return qmem.memory, over_target

    def _fragment(self):
        for f in self.request.subplan.fragments:
            if f.id == self.request.fragment_id:
                return f
        raise KeyError(f"fragment {self.request.fragment_id} not in subplan")

    def start(self) -> None:
        self.thread.start()

    # ------------------------------------------------------------ lifecycle

    def _transition(self, state: str) -> bool:
        """Move to `state` unless already terminal (a cancel/abort that beat
        this transition wins — it already poisoned the output buffer)."""
        with self._state_lock:
            if self.state in DONE_STATES:
                return False
            self.state = state
            return True

    def _run(self) -> None:
        from ..utils.metrics import METRICS
        t0 = time.perf_counter()
        try:
            self._transition(RUNNING)
            faults.fire("worker.task_run", task_id=self.task_id,
                        query_id=self.request.query_id)
            drivers = self._plan_drivers()
            self._drivers = drivers
            if self.cancelled.is_set():
                raise RuntimeError("task cancelled")
            concurrency = int(self.request.session.get("task_concurrency"))
            TaskExecutor(concurrency).execute(drivers)
            if self._sink is not None and not self._sink.operators:
                # fragment produced no sink operator (shouldn't happen) —
                # still close the buffer so consumers terminate
                self.output.set_no_more_pages()
            # freeze the operator stats BEFORE the terminal transition: the
            # drivers are all done here, so this snapshot is the complete
            # final accounting — consumers that observe a DONE state get
            # deterministic stats, never a racing mid-run read
            self._snapshot_final_stats()
            METRICS.histogram("task.wall_s", time.perf_counter() - t0)
            self._transition(FINISHED if not self.cancelled.is_set()
                             else CANCELED)
        except Exception as e:  # noqa: BLE001 — reported via TaskInfo
            self.error = {"message": str(e), "type": type(e).__name__,
                          "stack": traceback.format_exc()[-2000:]}
            self._snapshot_final_stats()
            self._transition(FAILED)
            from ..utils import events
            events.emit("task.failed", severity=events.ERROR,
                        query_id=self.request.query_id,
                        task_id=self.task_id, error=type(e).__name__,
                        message=str(e)[:500])
            # abandoned drivers must release their pipelines + memory
            # reservations (the pool is process-shared across queries now).
            # Set `cancelled` first: it marks the teardown abnormal, so
            # exchange sources skip their final acks — a failed consumer
            # must not release producer buffers its replacement still needs
            self.cancelled.set()
            for d in self._drivers:
                try:
                    d.close()
                except Exception:  # noqa: BLE001 - teardown best effort
                    pass
            self.output.fail(str(e))
        finally:
            # spill files must not outlive the task no matter how it ended
            # (close is idempotent and releases only THIS task's ledger
            # bytes — sibling tasks of the query keep theirs)
            if self._spill is not None:
                self._spill.close()

    def _snapshot_final_stats(self) -> None:
        from ..exec.explain import driver_stats
        try:
            if self._drivers:
                self._final_stats = driver_stats(self._drivers)
        except Exception:  # noqa: BLE001 - stats must never mask the run's outcome
            pass

    def _plan_drivers(self):
        req = self.request
        frag = self._fragment()
        plans = plan_subplan(req.subplan, self.metadata, req.session,
                             req.task_counts, target_fragment_id=req.fragment_id,
                             sink_factory=self._make_sink(frag),
                             memory=self._query_memory(),
                             # one fairness slot per QUERY on this worker:
                             # every fragment of every task of one query
                             # shares it (keys are refcounted per pool)
                             pool_key=f"cluster-{req.query_id}")
        own_lp, own_plan = plans[req.fragment_id]
        self.output_types = own_plan.output_types
        self.output_dicts = own_plan.output_dicts
        # wire remote sources to streaming HTTP pulls
        from ..metadata import default_page_capacity
        page_cap = int(req.session.get("page_capacity")
                       or default_page_capacity())
        from .exchange_client import _MAX_ERROR_S
        budget = req.session.get("exchange_error_budget_s")
        error_budget_s = float(_MAX_ERROR_S if budget is None else budget)
        for fid, slot in own_lp.remote_slots.items():
            dicts = plans[fid][1].output_dicts
            types = [s.type for s in self._producer_outputs(fid)]

            merge = getattr(slot, "merge_orderings", None)

            def factory(worker, _fid=fid, _t=types, _d=dicts, _m=merge):
                with self._src_lock:
                    locs = list(self._input_locations.get(_fid, []))
                    if _m:
                        from .exchange_client import MergingRemoteSource

                        src = MergingRemoteSource(
                            locs, req.worker_index, _t, _d, page_cap, _m,
                            cancelled=self.cancelled,
                            error_budget_s=error_budget_s)
                    else:
                        src = StreamingRemoteSource(
                            locs, req.worker_index, _t, _d, page_cap,
                            cancelled=self.cancelled,
                            error_budget_s=error_budget_s)
                    self._live_sources.setdefault(_fid, []).append(src)
                return src
            slot.source_factory = factory
        return own_plan.create_drivers(req.worker_index)

    def _producer_outputs(self, fragment_id: int):
        for f in self.request.subplan.fragments:
            if f.id == fragment_id:
                return f.root.outputs()
        raise KeyError(fragment_id)

    def _make_sink(self, frag):
        def make(types: List[Type], dicts) -> TaskOutputFactory:
            key_idx = None
            if frag.output_kind == REPARTITION and frag.output_keys:
                names = [s.name for s in frag.root.outputs()]
                key_idx = [names.index(k.name) for k in frag.output_keys]
            flush = self.request.session.get("exchange_flush_rows")
            self._sink = TaskOutputFactory(
                999, types, self.output, frag.output_kind or GATHER, key_idx,
                flush_rows=int(flush) if flush else 1 << 14)
            return self._sink
        return make

    # ------------------------------------------------------------------ api

    def update_sources(self, update: "SourceUpdateRequest") -> bool:
        """Rewire one exchange input to a replacement producer location.
        True only if EVERY live source for that fragment accepted the reset
        (virgin streams) — one consumed frame makes the rewire unsound (the
        replacement re-produces from token 0) and the scheduler must
        escalate to a query-level retry instead."""
        with self._src_lock:
            locs = self._input_locations.get(update.fragment_id)
            if locs is None:
                return False
            old = update.old_location.rstrip("/")
            if not any(loc.rstrip("/") == old for loc in locs):
                return False
            live = self._live_sources.get(update.fragment_id, [])
            # check-then-apply so a rejection mutates nothing (a concurrent
            # first-frame commit between the phases can still fail the
            # apply — that residual partial rewire is torn down by the
            # query-level retry the caller escalates to)
            if not all(src.can_reset_location(update.old_location)
                       for src in live):
                return False
            for src in live:
                if not src.reset_location(update.old_location,
                                          update.new_location):
                    return False
            for i, loc in enumerate(locs):
                if loc.rstrip("/") == old:
                    locs[i] = update.new_location
        return True

    def cancel(self, abort: bool = False) -> None:
        self.cancelled.set()
        self._transition(ABORTED if abort else CANCELED)
        if abort:
            # poison BEFORE freeing: an aborted stream must read as a
            # failure, never as a clean end-of-stream — consumers that saw
            # a silent `complete` here would truncate their input and
            # report partial rows as a successful result
            self.output.fail(f"task {self.task_id} aborted")
        self.output.destroy()

    def info(self) -> TaskInfo:
        from ..exec.explain import driver_stats

        rows = self._sink.operators[0].rows_out \
            if self._sink and self._sink.operators else 0
        # DONE state -> the frozen final snapshot (deterministic); mid-run
        # -> a live racy read (what /v1/query live progress wants)
        stats = self._final_stats
        if stats is None:
            stats = driver_stats(self._drivers) if self._drivers else None
        return TaskInfo(self.task_id, self.state, self.error, rows,
                        self.instance_id, operator_stats=stats)


class WorkerTaskManager:
    """execution/SqlTaskManager.java:84 — owns this worker's tasks."""

    def __init__(self, metadata: MetadataManager,
                 max_done_tasks: int = 200):
        self.metadata = metadata
        self.tasks: Dict[str, SqlTask] = {}
        self._lock = threading.Lock()
        self.max_done_tasks = max_done_tasks

    def create_or_update(self, request: TaskUpdateRequest) -> TaskInfo:
        created = False
        with self._lock:
            task = self.tasks.get(request.task_id)
            if task is None:
                task = SqlTask(request, self.metadata)
                self.tasks[request.task_id] = task
                task.start()
                self._cleanup_locked()
                created = True
            elif (request.query_id, request.fragment_id,
                  request.worker_index) != (task.request.query_id,
                                            task.request.fragment_id,
                                            task.request.worker_index):
                # an update must describe the SAME work; silently returning
                # the old task's info would strand a rescheduled fragment
                raise ValueError(
                    f"task {request.task_id} exists with different content "
                    f"(instance {task.instance_id})")
        if created:
            # journaled OUTSIDE the manager lock (the journal's file sink
            # does I/O under its own lock; never nest that under ours)
            from ..utils import events
            events.emit("task.created", query_id=request.query_id,
                        task_id=request.task_id,
                        fragment=request.fragment_id)
        return task.info()

    def get(self, task_id: str) -> Optional[SqlTask]:
        return self.tasks.get(task_id)

    def cancel(self, task_id: str, abort: bool = False) -> bool:
        task = self.tasks.get(task_id)
        if task is None:
            return False
        task.cancel(abort)
        return True

    def cancel_query(self, query_id: str) -> None:
        for task in list(self.tasks.values()):
            if task.request.query_id == query_id:
                task.cancel(abort=True)

    def _cleanup_locked(self) -> None:
        done = [t for t in self.tasks.values() if t.state in DONE_STATES]
        if len(done) <= self.max_done_tasks:
            return
        done.sort(key=lambda t: t.created)
        for t in done[:len(done) - self.max_done_tasks]:
            self.tasks.pop(t.task_id, None)
