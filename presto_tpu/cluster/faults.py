"""Deterministic, seedable fault injection for the cluster tier.

Every retry path in cluster/ exists to survive a fault that unit tests cannot
produce on demand — a worker dying mid-query, a dropped connection, a 5xx
blip. This harness makes those faults first-class test inputs instead of
hoped-for production events: hook points in the worker HTTP server, the
remote-task client, the exchange client, the announcer, and the task runtime
call :func:`fire`, and an installed :class:`FaultInjector` decides — by
deterministic call counts (``after``/``times``) or a seeded RNG
(``probability``) — whether to inject a delay, a connection reset, an HTTP
error, a task error, or a caller-supplied callback (e.g. kill a worker).

Nothing fires unless an injector is installed (production cost: one module
attribute read per hook). Install programmatically (tests), from the
``PRESTO_TPU_FAULTS`` env var (worker processes), or via the
``fault_injection`` session property (coordinator).

Spec grammar (env var / session property), rules separated by ``;``::

    point:kind[:key=value[,key=value...]]

    worker.results:disconnect:after=2,times=1
    worker.task_create:http_error:code=503,times=3
    client.results:delay:delay_s=0.05,probability=0.5,seed=7

Fire points:
  worker.task_create / worker.task_info / worker.results / worker.status
  worker.task_run   (inside SqlTask._run — fails the task itself)
  client.task_create / client.task_poll / client.results / client.announce
  spill.write / spill.read   (exec/spill.py disk-run I/O — fails the owning
                              query only; shared pools and tenants survive)
"""
from __future__ import annotations

import fnmatch
import io
import os
import re
import threading
import time
import urllib.error
from typing import Callable, Dict, List, Optional

from ..utils.metrics import METRICS

# fault kinds
DELAY = "delay"            # sleep delay_s, then continue normally
DISCONNECT = "disconnect"  # raise InjectedDisconnect (a ConnectionResetError)
HTTP_ERROR = "http_error"  # worker hooks answer `code`; client hooks raise
ERROR = "error"            # raise InjectedFault (plain exception)
CALLBACK = "callback"      # run rule.callback(ctx); it may itself raise
KINDS = (DELAY, DISCONNECT, HTTP_ERROR, ERROR, CALLBACK)

# every fire() site in the tree — the spec grammar's point vocabulary.
# from_spec validates each rule's point pattern against this list, so a
# typo'd chaos spec ("worker.resutls:...") fails LOUDLY at install time
# instead of silently injecting nothing for the whole run
FIRE_POINTS = (
    "worker.task_create", "worker.task_info", "worker.results",
    "worker.status", "worker.task_run",
    "client.task_create", "client.task_poll", "client.results",
    "client.announce",
    "spill.write", "spill.read",
)


class InjectedFault(Exception):
    """Base class for injected failures (classified retryable)."""


class InjectedDisconnect(InjectedFault, ConnectionResetError):
    """Injected peer reset — an OSError, so existing transient-failure
    handling on the clients catches it like a real dropped connection."""


class InjectedHTTPError(InjectedFault, urllib.error.HTTPError):
    """Injected HTTP failure. Doubles as a REAL urllib HTTPError so that at
    client-side hook points it flows through exactly the except clauses a
    genuine 5xx would (RemoteTask.create's transient branch,
    PageBufferClient.poll's 5xx-transient branch); worker-side hooks catch
    it explicitly and answer the request with `code` instead."""

    def __init__(self, code: int = 503, body: str = "injected fault"):
        urllib.error.HTTPError.__init__(
            self, "injected://fault", code, body, None,
            io.BytesIO(body.encode()))
        self.body = body


class FaultRule:
    """One match-and-fire rule. Matching is by fire point (fnmatch pattern),
    plus optional node id and task/location regexes. The rule fires on
    matched calls number (after, after+times]; ``times=None`` = unbounded.
    ``probability`` additionally gates each firing through the injector's
    seeded RNG (deterministic for a single-threaded call sequence)."""

    def __init__(self, point: str, kind: str, after: int = 0,
                 times: Optional[int] = 1, probability: Optional[float] = None,
                 delay_s: float = 0.0, code: int = 503,
                 node_id: Optional[str] = None,
                 task_re: Optional[str] = None,
                 location_re: Optional[str] = None,
                 callback: Optional[Callable[[dict], None]] = None):
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {KINDS})")
        self.point = point
        self.kind = kind
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.probability = probability
        self.delay_s = float(delay_s)
        self.code = int(code)
        self.node_id = node_id
        self.task_re = re.compile(task_re) if task_re else None
        self.location_re = re.compile(location_re) if location_re else None
        self.callback = callback
        self.matched = 0
        self.fired = 0

    def matches(self, point: str, ctx: dict) -> bool:
        if not fnmatch.fnmatch(point, self.point):
            return False
        if self.node_id is not None and ctx.get("node_id") != self.node_id:
            return False
        if self.task_re is not None and \
                not self.task_re.search(str(ctx.get("task_id") or "")):
            return False
        if self.location_re is not None and \
                not self.location_re.search(str(ctx.get("location") or "")):
            return False
        return True

    def __repr__(self):
        return (f"FaultRule({self.point}:{self.kind} after={self.after} "
                f"times={self.times} matched={self.matched} "
                f"fired={self.fired})")


class FaultInjector:
    """A seeded rule set; thread-safe match counting so concurrent hooks
    (worker handler threads, exchange pullers) see one deterministic window
    per rule."""

    def __init__(self, seed: int = 0):
        import random
        self.rules: List[FaultRule] = []
        self.rng = random.Random(seed)
        self.seed = seed
        self.total_fired = 0
        self._lock = threading.Lock()
        self._sleep = time.sleep

    def add(self, point: str, kind: str, **kw) -> FaultRule:
        rule = FaultRule(point, kind, **kw)
        self.rules.append(rule)
        return rule

    def fire(self, point: str, **ctx) -> None:
        """Called from a hook point; raises the injected failure, if any."""
        for rule in self.rules:
            with self._lock:
                if not rule.matches(point, ctx):
                    continue
                rule.matched += 1
                in_window = rule.matched > rule.after and (
                    rule.times is None
                    or rule.fired < rule.times)
                if not in_window:
                    continue
                if rule.probability is not None \
                        and self.rng.random() >= rule.probability:
                    continue
                rule.fired += 1
                self.total_fired += 1
            METRICS.count("cluster.faults_injected")
            ctx = dict(ctx, point=point, rule=rule)
            if rule.kind == DELAY:
                self._sleep(rule.delay_s)
            elif rule.kind == DISCONNECT:
                raise InjectedDisconnect(
                    f"injected disconnect at {point} ({ctx.get('node_id')})")
            elif rule.kind == HTTP_ERROR:
                raise InjectedHTTPError(rule.code)
            elif rule.kind == ERROR:
                raise InjectedFault(f"injected fault at {point}")
            elif rule.kind == CALLBACK and rule.callback is not None:
                rule.callback(ctx)

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultInjector":
        """Parse the ``point:kind[:k=v,...][;rule...]`` grammar above."""
        injector = cls(seed=seed)
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            pieces = part.split(":", 2)
            if len(pieces) < 2:
                raise ValueError(f"bad fault rule {part!r} "
                                 "(want point:kind[:k=v,...])")
            point, kind = pieces[0].strip(), pieces[1].strip()
            if not any(fnmatch.fnmatch(p, point) for p in FIRE_POINTS):
                raise ValueError(
                    f"unknown fault point {point!r}: pattern matches no "
                    f"fire point (one of {', '.join(FIRE_POINTS)})")
            if kind not in KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (one of {', '.join(KINDS)})")
            kw: Dict[str, object] = {}
            if len(pieces) == 3 and pieces[2].strip():
                for item in pieces[2].split(","):
                    key, _, value = item.partition("=")
                    key = key.strip()
                    value = value.strip()
                    if key == "seed":
                        import random
                        injector.rng = random.Random(int(value))
                        injector.seed = int(value)
                        continue
                    if key in ("node_id", "task_re", "location_re"):
                        kw[key] = value
                    elif key in ("after", "times", "code"):
                        kw[key] = int(value)
                    elif key in ("delay_s", "probability"):
                        kw[key] = float(value)
                    else:
                        raise ValueError(f"unknown fault rule key {key!r}")
            injector.add(point, kind, **kw)
        return injector


# ------------------------------------------------------------ process global

_INJECTOR: Optional[FaultInjector] = None


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    global _INJECTOR
    _INJECTOR = injector
    return injector


def active() -> Optional[FaultInjector]:
    return _INJECTOR


def clear() -> None:
    install(None)


def fire(point: str, **ctx) -> None:
    """The hook call: no-op unless an injector is installed."""
    injector = _INJECTOR
    if injector is not None:
        injector.fire(point, **ctx)


def install_from_env(environ=None) -> Optional[FaultInjector]:
    """Install from PRESTO_TPU_FAULTS / PRESTO_TPU_FAULT_SEED if set and no
    injector is active (worker processes parse this at server start)."""
    environ = os.environ if environ is None else environ
    spec = environ.get("PRESTO_TPU_FAULTS")
    if not spec or _INJECTOR is not None:
        return _INJECTOR
    seed = int(environ.get("PRESTO_TPU_FAULT_SEED", "0"))
    return install(FaultInjector.from_spec(spec, seed=seed))
