"""Coordinator autoscaler: grow/shrink a managed worker set under load.

The elastic-lifecycle loop on top of the drain state machine (worker.py) and
`ClusterQueryRunner.drain_worker`: scale-UP when the admission queue backs up
or the pools saturate, scale-DOWN only through a graceful drain — a shrink
must never OOM-kill a query or 410-escalate a live stream, so the victim
worker's tasks are handed to replacements (mid-stream replay splice) and the
node leaves the cluster only after reporting DRAINED.

Signals (read, not invented — the loop consumes what the engine already
journals and polls):
  - admission-queue depth: `query.queued` events (resource_groups.py emits
    them with `queue_depth`) and `pool.saturated` events since the last poll
  - memory pressure: ClusterMemoryManager.saturation() — the same
    /v1/status poll the OOM ladder runs on (the per-node feed
    GET /v1/cluster/metrics merges); without a memory manager the
    autoscaler polls worker /v1/status itself at its own cadence
  - per-worker activity: activeTasks from the same status feed

The worker factory is injected (`spawn_worker() -> handle with
.node_id/.uri/.stop()`) so tests, the churn bench and a real deployment can
each decide what "start a worker" means. Managed workers are re-announced by
the poll loop itself — a spawned worker needs no announcer of its own."""
from __future__ import annotations

import json
import threading
import urllib.request
from typing import Callable, Dict, List, Optional

from ..utils import events


class WorkerPoolAutoscaler:
    def __init__(self, runner, spawn_worker: Callable[[], object],
                 min_workers: int = 1, max_workers: int = 4,
                 poll_period_s: float = 1.0,
                 queue_depth_up: int = 1,
                 saturation_up: float = 0.8,
                 tasks_per_worker_up: float = 4.0,
                 idle_polls_down: int = 5,
                 drain_wait_s: float = 60.0):
        """`runner` is the ClusterQueryRunner (nodes + drain_worker +
        optional memory_manager). Scale-up triggers when ANY pressure signal
        fires; scale-down requires `idle_polls_down` consecutive quiet polls
        — growing is cheap and urgent, shrinking is neither."""
        self.runner = runner
        self.spawn_worker = spawn_worker
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.poll_period_s = poll_period_s
        self.queue_depth_up = queue_depth_up
        self.saturation_up = saturation_up
        self.tasks_per_worker_up = tasks_per_worker_up
        self.idle_polls_down = idle_polls_down
        self.drain_wait_s = drain_wait_s
        # all `managed` access goes through _managed_lock: adopt() runs on
        # the caller's thread, scale decisions on the poll loop's
        self._managed_lock = threading.Lock()
        self.managed: Dict[str, object] = {}
        self.scale_ups = 0
        self.scale_downs = 0
        self._idle_polls = 0
        self._last_seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="autoscaler", daemon=True)

    # ------------------------------------------------------------------ api

    def adopt(self, handle) -> None:
        """Place an already-running worker under autoscaler management (the
        initial fleet; scale-down may later drain it)."""
        with self._managed_lock:
            self.managed[handle.node_id] = handle
        self.runner.nodes.announce(handle.node_id, handle.uri)

    def start(self) -> "WorkerPoolAutoscaler":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    # -------------------------------------------------------------- signals

    def read_signals(self) -> dict:
        """One pressure reading. Journal events are consumed since the last
        poll (the cursor advances even on quiet polls, so stale queueing
        never re-triggers); saturation and activity come from the status
        feed."""
        queued = events.JOURNAL.events(since=self._last_seq,
                                       kind="query.queued")
        saturated = events.JOURNAL.events(since=self._last_seq,
                                          kind="pool.saturated")
        self._last_seq = events.JOURNAL.last_seq()
        queue_depth = max((int(e.get("queue_depth") or 0) for e in queued),
                          default=0)
        mm = getattr(self.runner, "memory_manager", None)
        if mm is not None:
            saturation = mm.saturation()
            active_tasks = dict(mm.last_active_tasks)
        else:
            saturation = 0.0
            active_tasks = self._poll_active_tasks()
        n = max(len(self._schedulable_managed()), 1)
        return {
            "queue_depth": queue_depth,
            "pool_saturated_events": len(saturated),
            "memory_saturation": round(saturation, 3),
            "active_tasks": active_tasks,
            "tasks_per_worker": round(
                sum(active_tasks.values()) / n, 2) if active_tasks else 0.0,
        }

    def _poll_active_tasks(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for node in self.runner.nodes.active_nodes():
            try:
                with urllib.request.urlopen(f"{node.uri}/v1/status",
                                            timeout=2.0) as resp:
                    out[node.node_id] = int(
                        json.loads(resp.read()).get("activeTasks") or 0)
            except Exception:  # noqa: BLE001 - dead node: detector's job
                continue
        return out

    def _schedulable_managed(self) -> List[str]:
        draining = {n.node_id for n in self.runner.nodes.all_nodes()
                    if n.draining}
        with self._managed_lock:
            return [nid for nid in self.managed if nid not in draining]

    # --------------------------------------------------------------- policy

    def poll_once(self) -> Optional[str]:
        """One observe→decide→act step; returns "scale_up"/"scale_down"
        or None. Exposed for deterministic tests — the background loop just
        calls this on its period."""
        self._announce_managed()
        signal = self.read_signals()
        pressure = (signal["queue_depth"] >= self.queue_depth_up
                    or signal["pool_saturated_events"] > 0
                    or signal["memory_saturation"] >= self.saturation_up
                    or signal["tasks_per_worker"] >= self.tasks_per_worker_up)
        n = len(self._schedulable_managed())
        if pressure:
            self._idle_polls = 0
            if n < self.max_workers:
                return self._scale_up(signal)
            return None
        self._idle_polls += 1
        if self._idle_polls >= self.idle_polls_down and n > self.min_workers:
            self._idle_polls = 0
            return self._scale_down(signal)
        return None

    def _scale_up(self, signal: dict) -> Optional[str]:
        try:
            handle = self.spawn_worker()
        except Exception as e:  # noqa: BLE001 - spawn failure must not kill the loop
            events.emit("autoscaler.spawn_failed", severity=events.ERROR,
                        error=repr(e)[:200])
            return None
        with self._managed_lock:
            self.managed[handle.node_id] = handle
            workers = len(self.managed)
        self.runner.nodes.announce(handle.node_id, handle.uri)
        self.scale_ups += 1
        events.emit("autoscaler.scale_up", severity=events.INFO,
                    node=handle.node_id, workers=workers, signal=signal)
        return "scale_up"

    def _scale_down(self, signal: dict) -> Optional[str]:
        """Shrink by ONE worker, always through the drain path: pick the
        least-loaded managed node, drain it (tasks handed off via replay,
        node removed at DRAINED), then stop the process. Never a kill."""
        candidates = self._schedulable_managed()
        if not candidates:
            return None
        loads = signal.get("active_tasks") or {}
        victim = min(candidates, key=lambda nid: loads.get(nid, 0))
        with self._managed_lock:
            handle = self.managed.pop(victim)
        try:
            self.runner.drain_worker(
                victim, signal={"trigger": "autoscaler.scale_down", **signal},
                wait_s=self.drain_wait_s)
        except ValueError:
            # already gone from discovery (expired / operator-drained):
            # stopping the handle is all that is left
            pass
        handle.stop()
        self.scale_downs += 1
        with self._managed_lock:
            workers = len(self.managed)
        events.emit("autoscaler.scale_down", severity=events.INFO,
                    node=victim, workers=workers, signal=signal)
        return "scale_down"

    # ------------------------------------------------------------- internal

    def _announce_managed(self) -> None:
        """Keep managed workers fresh in discovery. announce() refreshes
        liveness without clearing a drain flag, so a node an operator is
        draining stays visible (it still serves its streams) — but a node
        already REMOVED (post-DRAINED) must not be resurrected, so only
        still-registered nodes are refreshed; new spawns are announced by
        _scale_up itself."""
        known = {n.node_id for n in self.runner.nodes.all_nodes()}
        with self._managed_lock:
            snapshot = list(self.managed.items())
        for nid, handle in snapshot:
            if nid in known:
                self.runner.nodes.announce(nid, handle.uri)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 - the loop must survive one bad poll
                events.emit("autoscaler.poll_failed", severity=events.ERROR,
                            error=repr(e)[:200])
