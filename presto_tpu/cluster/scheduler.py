"""Coordinator-side scheduling: node selection, remote tasks, stage execution.

Analogues (/root/reference/presto-main):
  - execution/scheduler/NodeScheduler.java:59 + SimpleNodeSelector.java:45 —
    pick worker nodes for a stage's tasks
  - server/remotetask/HttpRemoteTask.java:103,491-541 — the coordinator's
    proxy for one worker task: POST updates, poll status with backoff
  - execution/scheduler/SqlQueryScheduler.java:114,549 + SqlStageExecution —
    create every stage's tasks (all-at-once policy: data streams between
    stages, so all tasks start together) and monitor them to completion
  - server/remotetask/Backoff.java — transient-failure retry budget
"""
from __future__ import annotations

import dataclasses
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..metadata import Session
from ..sql.planner.fragmenter import Fragment, SINGLE_PART, SubPlan
from ..sql.planner.plan import RemoteSourceNode
from . import codec
from .discovery import NodeInfo
from .task import (DONE_STATES, FAILED, FINISHED, TaskInfo,
                   TaskUpdateRequest)


class RemoteTask:
    """Coordinator proxy for one worker task (HttpRemoteTask analogue)."""

    def __init__(self, task_id: str, node: NodeInfo):
        self.task_id = task_id
        self.node = node
        self.location = f"{node.uri}/v1/task/{task_id}"
        self.info: Optional[TaskInfo] = None

    def create(self, request: TaskUpdateRequest, retries: int = 3) -> TaskInfo:
        body = codec.dumps(request)
        last: Optional[Exception] = None
        for attempt in range(retries):
            req = urllib.request.Request(
                self.location, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30.0) as resp:
                    self.info = codec.loads(resp.read())
                    return self.info
            except urllib.error.HTTPError as e:
                # 4xx = the worker REJECTED the request (bad body / conflicting
                # task content) — deterministic, so surface its diagnostic body
                # instead of retrying it as if it were a network blip
                detail = e.read().decode("utf-8", "replace")[:500]
                if 400 <= e.code < 500:
                    raise RuntimeError(
                        f"worker {self.node.node_id} rejected task "
                        f"{self.task_id} ({e.code}): {detail}") from None
                last = RuntimeError(f"HTTP {e.code}: {detail}")
                time.sleep(0.2 * (attempt + 1))
            except (urllib.error.URLError, OSError) as e:
                last = e
                time.sleep(0.2 * (attempt + 1))
        raise RuntimeError(
            f"cannot create task {self.task_id} on {self.node.node_id}: {last}")

    def poll_info(self) -> Optional[TaskInfo]:
        req = urllib.request.Request(self.location, method="GET")
        try:
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                self.info = codec.loads(resp.read())
                return self.info
        except (urllib.error.URLError, OSError):
            return None  # judged by the failure detector, not one lost poll

    def cancel(self, abort: bool = True) -> None:
        try:
            req = urllib.request.Request(
                self.location + ("?abort=true" if abort else ""),
                method="DELETE")
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:
            pass


class NodeScheduler:
    """SimpleNodeSelector.java:45 (narrowed): every active node runs one task
    of each distributed fragment; single-task fragments rotate over nodes by
    fragment id so consecutive SINGLE stages spread."""

    def __init__(self, nodes: List[NodeInfo]):
        assert nodes, "no active worker nodes"
        self.nodes = nodes

    def select(self, fragment: Fragment) -> List[NodeInfo]:
        if fragment.partitioning == SINGLE_PART:
            return [self.nodes[fragment.id % len(self.nodes)]]
        return list(self.nodes)


@dataclasses.dataclass
class StageExecution:
    fragment: Fragment
    tasks: List[RemoteTask]


class SqlQueryScheduler:
    """Create all stages' tasks, monitor to completion, expose root location.

    Stages are created bottom-up (producers first) so consumers' first pulls
    mostly find their sources; data still STREAMS between stages — no stage
    waits for another to finish before starting (all-at-once policy,
    AllAtOnceExecutionPolicy.java)."""

    def __init__(self, query_id: str, subplan: SubPlan,
                 nodes: List[NodeInfo], session: Session):
        self.query_id = query_id
        self.subplan = subplan
        self.session = session
        self.selector = NodeScheduler(nodes)
        self.stages: Dict[int, StageExecution] = {}
        self._consumer_tasks = self._consumer_task_counts()

    def _consumer_task_counts(self) -> Dict[int, int]:
        """fragment id -> number of tasks of its consuming fragment."""
        counts: Dict[int, int] = {}
        for frag in self.subplan.fragments:
            n_tasks = 1 if frag.partitioning == SINGLE_PART \
                else len(self.selector.nodes)
            for fid in _remote_source_ids(frag.root):
                counts[fid] = n_tasks
        counts[self.subplan.root_fragment.id] = 1  # the coordinator pulls root
        return counts

    def schedule(self) -> None:
        task_counts = {
            f.id: (1 if f.partitioning == SINGLE_PART
                   else len(self.selector.nodes))
            for f in self.subplan.fragments}
        for frag in self.subplan.fragments:  # bottom-up order from fragmenter
            nodes = self.selector.select(frag)
            tasks = [RemoteTask(f"{self.query_id}.{frag.id}.{i}", node)
                     for i, node in enumerate(nodes)]
            input_locations = {
                fid: [t.location for t in self.stages[fid].tasks]
                for fid in _remote_source_ids(frag.root)}
            for i, task in enumerate(tasks):
                task.create(TaskUpdateRequest(
                    task_id=task.task_id,
                    query_id=self.query_id,
                    subplan=self.subplan,
                    fragment_id=frag.id,
                    worker_index=i,
                    task_counts=task_counts,
                    input_locations=input_locations,
                    session=self.session,
                    output_buffers=self._consumer_tasks[frag.id]))
            self.stages[frag.id] = StageExecution(frag, tasks)

    # ------------------------------------------------------------ monitoring

    def root_task(self) -> RemoteTask:
        return self.stages[self.subplan.root_fragment.id].tasks[0]

    def all_tasks(self) -> List[RemoteTask]:
        return [t for s in self.stages.values() for t in s.tasks]

    def check_failures(self, active_node_ids: Optional[set] = None) -> None:
        """Poll task infos; raise on any FAILED task or dead node (queries with
        tasks on failed nodes fail — the reference has no intra-query retry
        either, SURVEY §5)."""
        for task in self.all_tasks():
            info = task.poll_info()
            if info is not None and info.state == FAILED:
                err = info.error or {}
                raise RuntimeError(
                    f"task {task.task_id} failed on {task.node.node_id}: "
                    f"{err.get('message')}\n{err.get('stack', '')[-800:]}")
            if active_node_ids is not None \
                    and task.node.node_id not in active_node_ids \
                    and (info is None or info.state not in DONE_STATES):
                raise RuntimeError(
                    f"worker {task.node.node_id} died with task "
                    f"{task.task_id} in state "
                    f"{info.state if info else 'UNREACHABLE'}")

    def is_finished(self) -> bool:
        info = self.root_task().info
        return info is not None and info.state == FINISHED

    def abort(self) -> None:
        for task in self.all_tasks():
            task.cancel(abort=True)


def _remote_source_ids(node) -> List[int]:
    out: List[int] = []

    def walk(n):
        if isinstance(n, RemoteSourceNode):
            out.append(n.fragment_id)
            return
        for c in n.children():
            walk(c)
    walk(node)
    return out
