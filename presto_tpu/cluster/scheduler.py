"""Coordinator-side scheduling: node selection, remote tasks, stage execution.

Analogues (/root/reference/presto-main):
  - execution/scheduler/NodeScheduler.java:59 + SimpleNodeSelector.java:45 —
    pick worker nodes for a stage's tasks
  - server/remotetask/HttpRemoteTask.java:103,491-541 — the coordinator's
    proxy for one worker task: POST updates, poll status with backoff
  - execution/scheduler/SqlQueryScheduler.java:114,549 + SqlStageExecution —
    create every stage's tasks (all-at-once policy: data streams between
    stages, so all tasks start together) and monitor them to completion
  - server/remotetask/Backoff.java — transient-failure retry budget
    (cluster/retry.Backoff here, shared by every retry loop on this tier)

Fault tolerance (retry_policy session property, cluster/retry.py):
  - every RemoteTask.create retries transient failures under one shared
    Backoff budget; 4xx rejections stay deterministic hard errors
  - TASK policy re-places a task whose create exhausted its budget onto
    another healthy node (consumers are created after producers, so their
    input_locations simply use the new location), and recovers failed tasks
    in place mid-query — leaf AND interior, mid-stream included, now that
    upstream buffers spool acked chunks (cluster/buffers.py): the
    replacement re-pulls its inputs from sequence 0, re-produces the same
    deterministic frame sequence, and every consumer keeps its chunk cursor
    across the POST /v1/task/{id}/sources rewire (the coordinator's own
    root pull rewires through register_root_consumer). A stream whose
    replay window was retired (HTTP 410) escalates to a query retry.
  - straggler speculation (speculative_execution knob): a task running
    far past its finished siblings gets a duplicate on another node;
    first to FINISH wins — the loser is aborted and the decision is
    journaled `task.speculated`
  - placement weighs the failure detector's decayed failure ratio
    (NodeScheduler.select / _pick_node) instead of excluding-or-round-robin
  - check_failures raises NodeDiedError/TaskFailedError with the node id
    so the coordinator can exclude failed nodes from the next attempt
"""
from __future__ import annotations

import dataclasses
import http.client
import statistics
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set, Tuple

from ..metadata import Session
from ..sql.planner.fragmenter import Fragment, SINGLE_PART, SubPlan
from ..sql.planner.plan import RemoteSourceNode
from ..utils import trace
from ..utils.metrics import METRICS
from . import codec, faults, retry
from .discovery import NodeInfo
from .retry import Backoff, NodeDiedError, TaskFailedError
from .task import (DONE_STATES, FAILED, FINISHED, SourceUpdateRequest,
                   TaskInfo, TaskUpdateRequest)


class RemoteTask:
    """Coordinator proxy for one worker task (HttpRemoteTask analogue)."""

    def __init__(self, task_id: str, node: NodeInfo, attempt: int = 0):
        self.task_id = task_id
        self.node = node
        self.attempt = attempt
        self.location = f"{node.uri}/v1/task/{task_id}"
        self.info: Optional[TaskInfo] = None
        self.request: Optional[TaskUpdateRequest] = None
        # wall tracking for straggler speculation: created here, done when a
        # poll first observes a terminal state
        self.created_mono = time.monotonic()
        self.done_mono: Optional[float] = None

    def wall_s(self) -> float:
        end = self.done_mono if self.done_mono is not None \
            else time.monotonic()
        return end - self.created_mono

    def create(self, request: TaskUpdateRequest,
               backoff: Optional[Backoff] = None) -> TaskInfo:
        """POST the task; transient failures (5xx, connection errors) retry
        under the shared Backoff budget, 4xx rejections are deterministic
        hard errors."""
        self.request = request
        body = codec.dumps(request)
        backoff = backoff or Backoff(max_failure_interval_s=10.0,
                                     initial_delay_s=0.1, max_delay_s=1.0)
        last: Optional[Exception] = None
        while True:
            req = urllib.request.Request(
                self.location, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                faults.fire("client.task_create", node_id=self.node.node_id,
                            task_id=self.task_id)
                with trace.span(trace.HTTP, f"POST task {self.task_id}",
                                node=self.node.node_id):
                    with urllib.request.urlopen(req, timeout=30.0) as resp:
                        self.info = codec.loads(resp.read())
                backoff.success()
                return self.info
            except urllib.error.HTTPError as e:
                # 4xx = the worker REJECTED the request (bad body / conflicting
                # task content) — deterministic, so surface its diagnostic body
                # instead of retrying it as if it were a network blip
                detail = e.read().decode("utf-8", "replace")[:500]
                if 400 <= e.code < 500:
                    raise RuntimeError(
                        f"worker {self.node.node_id} rejected task "
                        f"{self.task_id} ({e.code}): {detail}") from None
                if e.code == 503 and "shutting down" in detail:
                    # a DRAINING worker refuses placement by POLICY, not by
                    # transient overload: retrying against it would burn the
                    # whole backoff budget before the re-place. Escalate now
                    # so _create_task excludes the node and re-places on a
                    # healthy one immediately.
                    raise retry.ClusterExecutionError(
                        f"worker {self.node.node_id} is draining "
                        f"(503 shutting down) for task {self.task_id}",
                        node_id=self.node.node_id, retryable=True)
                last = RuntimeError(f"HTTP {e.code}: {detail}")
            except (urllib.error.URLError, OSError) as e:
                last = e
            if backoff.failure():
                raise retry.ClusterExecutionError(
                    f"cannot create task {self.task_id} on "
                    f"{self.node.node_id} after {backoff.failure_count} "
                    f"tries: {last}", node_id=self.node.node_id,
                    retryable=True)
            backoff.wait()

    def poll_info(self) -> Optional[TaskInfo]:
        req = urllib.request.Request(self.location, method="GET")
        try:
            faults.fire("client.task_poll", node_id=self.node.node_id,
                        task_id=self.task_id)
            with trace.span(trace.HTTP, f"GET task {self.task_id}",
                            node=self.node.node_id):
                with urllib.request.urlopen(req, timeout=10.0) as resp:
                    self.info = codec.loads(resp.read())
            if self.info is not None and self.info.state in DONE_STATES \
                    and self.done_mono is None:
                self.done_mono = time.monotonic()
            return self.info
        except (urllib.error.URLError, OSError):
            return None  # judged by the failure detector, not one lost poll

    def update_sources(self, update: "SourceUpdateRequest") -> bool:
        """POST /sources: rewire one of this task's exchange inputs to a
        replacement producer. False = the worker rejected the rewire (data
        already consumed from the old location — caller must escalate)."""
        req = urllib.request.Request(
            self.location + "/sources", data=codec.dumps(update),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=10.0).read()
            return True
        except (urllib.error.URLError, OSError):
            return False

    def cancel(self, abort: bool = True) -> None:
        try:
            req = urllib.request.Request(
                self.location + ("?abort=true" if abort else ""),
                method="DELETE")
            urllib.request.urlopen(req, timeout=5.0).read()
        except (urllib.error.URLError, http.client.HTTPException, OSError):
            # cancel is best-effort: the task may already be done or its
            # node dead — teardown proceeds either way, and the worker's
            # own task GC reaps anything a lost DELETE leaves behind
            pass


class NodeScheduler:
    """SimpleNodeSelector.java:45 (narrowed): every active node runs one task
    of each distributed fragment; single-task fragments rotate by fragment id
    so consecutive SINGLE stages spread. Retry-aware placement: instead of
    excluding-or-round-robin, selection WEIGHS the failure detector's decayed
    failure ratio (discovery.HeartbeatFailureDetector) — a node with a flaky
    recent history stops receiving single-task fragments before it is sick
    enough to be expelled from active_nodes()."""

    def __init__(self, nodes: List[NodeInfo]):
        assert nodes, "no active worker nodes"
        self.nodes = nodes

    @staticmethod
    def _bucket(node: NodeInfo) -> int:
        # quarter-buckets so ordinary heartbeat jitter cannot reshuffle
        # placement between monitor ticks
        return int(min(max(node.failure_ratio, 0.0), 1.0) * 4)

    def ranked(self) -> List[NodeInfo]:
        """Nodes best-first by bucketed decayed failure ratio; the sort is
        stable, so announce order breaks ties (the pre-detector behavior)."""
        return sorted(self.nodes, key=self._bucket)

    def select(self, fragment: Fragment) -> List[NodeInfo]:
        if fragment.partitioning == SINGLE_PART:
            ranked = self.ranked()
            best = self._bucket(ranked[0])
            healthy = [n for n in ranked if self._bucket(n) == best]
            return [healthy[fragment.id % len(healthy)]]
        return list(self.nodes)


@dataclasses.dataclass
class StageExecution:
    fragment: Fragment
    tasks: List[RemoteTask]


class SqlQueryScheduler:
    """Create all stages' tasks, monitor to completion, expose root location.

    Stages are created bottom-up (producers first) so consumers' first pulls
    mostly find their sources; data still STREAMS between stages — no stage
    waits for another to finish before starting (all-at-once policy,
    AllAtOnceExecutionPolicy.java)."""

    def __init__(self, query_id: str, subplan: SubPlan,
                 nodes: List[NodeInfo], session: Session,
                 retry_policy: str = retry.NONE,
                 excluded_nodes: Optional[Set[str]] = None):
        self.query_id = query_id
        self.subplan = subplan
        self.session = session
        self.selector = NodeScheduler(nodes)
        self.retry_policy = retry_policy
        # shared with the coordinator's query-retry loop: nodes that failed
        # here are excluded from the NEXT attempt's placement too
        self.excluded_nodes: Set[str] = (
            excluded_nodes if excluded_nodes is not None else set())
        self.stages: Dict[int, StageExecution] = {}
        self._consumer_tasks = self._consumer_task_counts()
        # observability (surfaced via QueryResult.stats + /v1/metrics)
        self.task_attempts = 0
        self.task_retries = 0
        self.task_speculations = 0
        self.backoff_s = 0.0
        # the coordinator's in-process root consumer (StreamingRemoteSource):
        # registered so root-task recovery can rewire its cursor directly —
        # there is no worker-side /sources endpoint for the coordinator
        self._root_consumer = None
        # straggler speculation: (fragment id, task index) -> (task_id of the
        # original at launch, speculative RemoteTask). Spec tasks are NOT in
        # self.stages, so check_failures never treats their failure as fatal.
        self._live_spec: Dict[Tuple[int, int], Tuple[str, RemoteTask]] = {}
        self._spec_done: Set[str] = set()  # base ids speculated once already
        # serializes stage.tasks mutations between the pull loop's monitor
        # (check_failures / maybe_speculate) and a concurrent planned drain
        # (drain_node, called from the coordinator/autoscaler thread)
        self._monitor_lock = threading.RLock()

    def _consumer_task_counts(self) -> Dict[int, int]:
        """fragment id -> number of tasks of its consuming fragment."""
        counts: Dict[int, int] = {}
        for frag in self.subplan.fragments:
            n_tasks = 1 if frag.partitioning == SINGLE_PART \
                else len(self.selector.nodes)
            for fid in _remote_source_ids(frag.root):
                counts[fid] = n_tasks
        counts[self.subplan.root_fragment.id] = 1  # the coordinator pulls root
        return counts

    def _new_backoff(self) -> Backoff:
        def prop(name, default):
            # 0.0 is a valid budget: it collapses the time window so the
            # budget exhausts after Backoff's min_tries (3) attempts
            value = self.session.get(name)
            return float(default if value is None else value)

        return Backoff(
            max_failure_interval_s=prop("remote_task_error_budget_s", 10.0),
            initial_delay_s=prop("retry_initial_delay_s", 0.1),
            max_delay_s=prop("retry_max_delay_s", 2.0))

    def schedule(self) -> None:
        task_counts = {
            f.id: (1 if f.partitioning == SINGLE_PART
                   else len(self.selector.nodes))
            for f in self.subplan.fragments}
        for frag in self.subplan.fragments:  # bottom-up order from fragmenter
            nodes = self.selector.select(frag)
            input_locations = {
                fid: [t.location for t in self.stages[fid].tasks]
                for fid in _remote_source_ids(frag.root)}
            tasks: List[RemoteTask] = []
            try:
                for i, node in enumerate(nodes):
                    tasks.append(self._create_task(frag, i, node, task_counts,
                                                   input_locations))
            except BaseException:
                # a half-created stage is not in self.stages, so abort() and
                # the coordinator's cleanup would never see these tasks —
                # cancel them here or they leak on the workers per attempt
                for task in tasks:
                    task.cancel(abort=True)
                raise
            self.stages[frag.id] = StageExecution(frag, tasks)

    def _build_request(self, task_id: str, frag: Fragment, index: int,
                      task_counts: Dict[int, int],
                      input_locations: Dict[int, List[str]]
                      ) -> TaskUpdateRequest:
        return TaskUpdateRequest(
            task_id=task_id,
            query_id=self.query_id,
            subplan=self.subplan,
            fragment_id=frag.id,
            worker_index=index,
            task_counts=task_counts,
            input_locations=input_locations,
            session=self.session,
            output_buffers=self._consumer_tasks[frag.id])

    def _create_task(self, frag: Fragment, index: int, node: NodeInfo,
                     task_counts: Dict[int, int],
                     input_locations: Dict[int, List[str]]) -> RemoteTask:
        """Create one task; under TASK policy a node whose create budget is
        exhausted is excluded and the task is re-placed on the next healthy
        node under a new attempt id."""
        base_id = f"{self.query_id}.{frag.id}.{index}"
        attempt = 0
        tried: Set[str] = set()
        while True:
            if self.retry_policy == retry.TASK \
                    and node.node_id in self.excluded_nodes:
                # a node already proven bad this query would burn a full
                # create budget per fragment before re-placing; skip it up
                # front (if every node is excluded, try anyway — the
                # starvation fallback)
                alternative = self._pick_node(
                    exclude=tried | {node.node_id})
                if alternative is not None:
                    node = alternative
            task_id = base_id if attempt == 0 else f"{base_id}.r{attempt}"
            task = RemoteTask(task_id, node, attempt=attempt)
            self.task_attempts += 1
            backoff = self._new_backoff()
            try:
                task.create(
                    self._build_request(task_id, frag, index, task_counts,
                                        input_locations),
                    backoff=backoff)
                return task
            except retry.ClusterExecutionError:
                tried.add(node.node_id)
                if self.retry_policy != retry.TASK:
                    raise
                self.excluded_nodes.add(node.node_id)
                replacement = self._pick_node(exclude=tried)
                if replacement is None:
                    raise
                METRICS.count("cluster.task_retries")
                from ..utils import events
                events.emit("task.retry", severity=events.WARN,
                            query_id=self.query_id, task_id=task_id,
                            retry_kind="re-placement", failed_node=node.node_id,
                            new_node=replacement.node_id,
                            attempt=attempt + 1)
                self.task_retries += 1
                node = replacement
                attempt += 1
            finally:
                self.backoff_s += backoff.total_backoff_s

    def _pick_node(self, exclude: Set[str]) -> Optional[NodeInfo]:
        candidates = [node for node in self.selector.nodes
                      if node.node_id not in exclude
                      and node.node_id not in self.excluded_nodes
                      and not node.draining]
        if not candidates:
            return None
        # weigh the decayed failure ratio: re-place onto the node with the
        # cleanest recent history (stable min keeps announce order on ties)
        return min(candidates, key=NodeScheduler._bucket)

    # ------------------------------------------------------------ monitoring

    def root_task(self) -> RemoteTask:
        return self.stages[self.subplan.root_fragment.id].tasks[0]

    def register_root_consumer(self, source) -> None:
        """The coordinator's pull thread hands over its StreamingRemoteSource
        so root-task recovery can rewire the in-process consumer's chunk
        cursor (workers rewire via POST /sources; the coordinator has no
        such endpoint — it IS the consumer)."""
        self._root_consumer = source

    def all_tasks(self) -> List[RemoteTask]:
        return [t for s in self.stages.values() for t in s.tasks]

    def check_failures(self,
                       active_nodes: Optional[List[NodeInfo]] = None,
                       recover: bool = True) -> None:
        """Poll task infos; raise on any FAILED task or dead node. Under TASK
        policy, first try in-place recovery (leaf AND interior, mid-stream:
        upstream spools + consumer cursors make the replay sound); what
        recovery cannot heal — a retired replay window, an exhausted attempt
        budget, a rejected rewire — raises a typed error the coordinator's
        query-retry loop classifies. Pass
        ``recover=False`` on diagnosis-only calls (an attempt already known
        lost): recovery there would build a replacement task just to throw
        it away, and a successful recovery would swallow the typed error
        whose node id the retry loop needs for placement exclusion."""
        active_ids = ({n.node_id for n in active_nodes}
                      if active_nodes is not None else None)
        pending: List[retry.ClusterExecutionError] = []
        with self._monitor_lock:
            self._check_failures_locked(active_ids, active_nodes, recover,
                                        pending)
        if pending:
            # a dead NODE is the root cause; a FAILED task on a healthy node
            # is often just a consumer of the dead node's stream — raise the
            # node death first so retry placement excludes the right node
            for failure in pending:
                if isinstance(failure, NodeDiedError):
                    raise failure
            raise pending[0]

    def _check_failures_locked(self, active_ids, active_nodes, recover,
                               pending) -> None:
        for stage in self.stages.values():
            for idx, task in enumerate(stage.tasks):
                info = task.poll_info()
                failure: Optional[retry.ClusterExecutionError] = None
                if info is not None and info.state == FAILED:
                    err = info.error or {}
                    failure = TaskFailedError(
                        f"task {task.task_id} failed on {task.node.node_id}: "
                        f"{err.get('message')}\n{err.get('stack', '')[-800:]}",
                        node_id=task.node.node_id,
                        retryable=retry.error_dict_retryable(err))
                elif active_ids is not None \
                        and task.node.node_id not in active_ids \
                        and (info is None or info.state not in DONE_STATES):
                    failure = NodeDiedError(
                        f"worker {task.node.node_id} died with task "
                        f"{task.task_id} in state "
                        f"{info.state if info else 'UNREACHABLE'}",
                        node_id=task.node.node_id)
                if failure is None:
                    continue
                if recover and self.retry_policy == retry.TASK \
                        and failure.retryable and active_nodes \
                        and self._recover_task(stage, idx, active_nodes,
                                               failure):
                    continue
                from ..utils import events
                events.emit(
                    "node.died" if isinstance(failure, NodeDiedError)
                    else "task.failed",
                    severity=events.ERROR, query_id=self.query_id,
                    task_id=task.task_id, node=task.node.node_id,
                    message=str(failure)[:300])
                pending.append(failure)

    # ---------------------------------------------------------------- drain

    def drain_node(self, node_id: str,
                   active_nodes: List[NodeInfo]) -> Tuple[int, int]:
        """Planned drain: proactively hand every live task on `node_id` to a
        replacement through the same mid-stream replay path failure recovery
        uses — exactly-once splice, consumers keep their chunk cursors, no
        410 escalation (the drained worker's spools are pinned and intact).
        Deliberately NOT gated on retry_policy: a drain is an operator
        action, and "zero queries lost" must hold for NONE-policy tenants
        too. Tasks recovery cannot move (attempt budget exhausted, root
        consumer not yet registered) are left to finish naturally on the
        draining node — it keeps serving until they do.
        Returns (tasks handed off, live tasks left to finish in place)."""
        moved = 0
        left = 0
        with self._monitor_lock:
            candidates = [n for n in active_nodes
                          if n.node_id != node_id
                          and not getattr(n, "draining", False)]
            for stage in self.stages.values():
                for idx in range(len(stage.tasks)):
                    task = stage.tasks[idx]
                    if task.node.node_id != node_id:
                        continue
                    info = task.poll_info() or task.info
                    if info is not None and info.state in DONE_STATES:
                        continue
                    if candidates and self._recover_task(
                            stage, idx, candidates, failure=None,
                            retry_kind="drain"):
                        moved += 1
                    else:
                        left += 1
        return moved, left

    def _recover_task(self, stage: StageExecution, idx: int,
                      active_nodes: List[NodeInfo],
                      failure: Optional[retry.ClusterExecutionError] = None,
                      retry_kind: str = "in-place-recovery") -> bool:
        """In-place recovery of one failed task — leaf OR interior, mid-stream
        included. The replacement re-derives its output deterministically
        (leaf fragments re-scan the connector; interior fragments re-pull
        their inputs from sequence 0 against the producers' spools), and
        every consumer keeps its chunk cursor across the rewire, skipping
        frames it already delivered. Unsound cases stay loud: a failure whose
        cause is a retired replay window (HTTP 410) cannot be healed by
        re-running the SAME stream, and a rejected rewire aborts the
        replacement — both escalate to the coordinator's query retry."""
        frag = stage.fragment
        old = stage.tasks[idx]
        message = str(failure).lower() if failure is not None else ""
        if "replay window lost" in message or "cannot replay" in message:
            # the task died because an UPSTREAM spool retired its window;
            # a replacement would re-pull the same 410
            return False
        if frag is self.subplan.root_fragment \
                and self._root_consumer is None:
            return False  # nobody registered to rewire the coordinator's pull
        budget = self.session.get("task_retry_attempts")
        if old.attempt >= int(2 if budget is None else budget):
            # a task that keeps dying would otherwise be recovered forever
            # (recovery resets nothing the failure reads); escalate to the
            # BOUNDED query-level retry instead
            return False
        # draining nodes never receive replacements: moving a task onto a
        # node that is itself leaving would just re-run this recovery
        healthy = [n for n in active_nodes
                   if n.node_id != old.node.node_id
                   and not getattr(n, "draining", False)]
        candidates = [n for n in healthy
                      if n.node_id not in self.excluded_nodes] or healthy
        if not candidates:
            return False
        node = min(candidates, key=NodeScheduler._bucket)
        attempt = old.attempt + 1
        base_id = f"{self.query_id}.{frag.id}.{old.request.worker_index}"
        new_task = self._launch_duplicate(
            frag, old, f"{base_id}.r{attempt}", node, attempt=attempt)
        if new_task is None:
            return False
        if not self._rewire_consumers(frag, old, new_task, active_nodes):
            new_task.cancel(abort=True)
            return False
        old.cancel(abort=True)
        stage.tasks[idx] = new_task
        METRICS.count("cluster.task_retries")
        from ..utils import events
        events.emit("task.retry", severity=events.WARN,
                    query_id=self.query_id, task_id=new_task.task_id,
                    retry_kind=retry_kind, failed_task=old.task_id,
                    failed_node=old.node.node_id, new_node=node.node_id,
                    attempt=attempt)
        self.task_retries += 1
        return True

    def _launch_duplicate(self, frag: Fragment, old: RemoteTask,
                          task_id: str, node: NodeInfo,
                          attempt: int) -> Optional[RemoteTask]:
        """Create a copy of ``old`` under ``task_id`` on ``node``, with its
        remote-source inputs refreshed to the CURRENT producer locations
        (an earlier recovery in this same sweep may have moved them)."""
        input_locations = {
            fid: [t.location for t in self.stages[fid].tasks]
            for fid in _remote_source_ids(frag.root)}
        task = RemoteTask(task_id, node, attempt=attempt)
        self.task_attempts += 1
        backoff = self._new_backoff()
        try:
            task.create(
                dataclasses.replace(old.request, task_id=task_id,
                                    input_locations=input_locations),
                backoff=backoff)
        except (retry.ClusterExecutionError, RuntimeError):
            return None
        finally:
            self.backoff_s += backoff.total_backoff_s
        return task

    def _rewire_consumers(self, frag: Fragment, old: RemoteTask,
                          new_task: RemoteTask,
                          active_nodes: List[NodeInfo]) -> bool:
        """Point every live consumer of ``old`` at ``new_task``, cursors
        preserved. Consumers that are themselves dead or FAILED are skipped —
        stages iterate bottom-up, so this same check_failures sweep recovers
        them AFTER their producers, and _launch_duplicate hands the
        replacement the already-updated producer locations. The root
        fragment's single consumer is the coordinator's in-process source,
        rewired directly."""
        if frag is self.subplan.root_fragment:
            return bool(self._root_consumer) and \
                self._root_consumer.reset_location(old.location,
                                                   new_task.location)
        active_ids = {n.node_id for n in active_nodes}
        for consumer_stage in self.stages.values():
            if frag.id not in _remote_source_ids(consumer_stage.fragment.root):
                continue
            update = SourceUpdateRequest(
                fragment_id=frag.id, old_location=old.location,
                new_location=new_task.location)
            for consumer in consumer_stage.tasks:
                if consumer.node.node_id not in active_ids or (
                        consumer.info is not None
                        and consumer.info.state == FAILED):
                    continue  # recovered later this sweep, with new locations
                if not consumer.update_sources(update):
                    return False
        return True

    # ---------------------------------------------------------- speculation

    def maybe_speculate(self, active_nodes: List[NodeInfo]) -> None:
        """Straggler speculation (speculative_execution knob): a RUNNING task
        whose wall exceeds both a floor and a multiple of its finished
        siblings' median gets a duplicate on the cleanest other node; the
        first to FINISH wins and the loser is aborted. Losing original ==
        winning replay: the spool + cursor machinery rewires consumers
        exactly as in-place recovery does. Every decision is journaled
        ``task.speculated``."""
        if not self.session.get("speculative_execution") \
                or self.retry_policy != retry.TASK:
            return
        with self._monitor_lock:
            self._maybe_speculate_locked(active_nodes)

    def _maybe_speculate_locked(self, active_nodes: List[NodeInfo]) -> None:
        self._resolve_speculations(active_nodes)
        min_wall = float(self.session.get("speculation_min_wall_s") or 5.0)
        multiplier = float(self.session.get("speculation_multiplier") or 2.0)
        for stage in self.stages.values():
            frag = stage.fragment
            for idx, task in enumerate(stage.tasks):
                key = (frag.id, idx)
                base = f"{self.query_id}.{frag.id}.{idx}"
                if key in self._live_spec or base in self._spec_done:
                    continue
                info = task.info
                if info is None or info.state in DONE_STATES:
                    continue
                finished = [t.wall_s() for t in stage.tasks
                            if t.done_mono is not None
                            and t.info is not None
                            and t.info.state == FINISHED]
                if not finished:
                    continue  # no sibling baseline: nothing says "straggler"
                threshold = max(min_wall,
                                multiplier * statistics.median(finished))
                if task.wall_s() <= threshold:
                    continue
                candidates = [n for n in active_nodes
                              if n.node_id != task.node.node_id
                              and n.node_id not in self.excluded_nodes
                              and not getattr(n, "draining", False)]
                if not candidates:
                    continue
                node = min(candidates, key=NodeScheduler._bucket)
                spec = self._launch_duplicate(
                    frag, task, f"{base}.s1", node,
                    attempt=task.attempt + 1)
                self._spec_done.add(base)
                if spec is None:
                    continue
                self._live_spec[key] = (task.task_id, spec)
                self.task_speculations += 1
                METRICS.count("cluster.task_speculations")

    def _resolve_speculations(self, active_nodes: List[NodeInfo]) -> None:
        from ..utils import events
        for key, (orig_id, spec) in list(self._live_spec.items()):
            frag_id, idx = key
            stage = self.stages[frag_id]
            original = stage.tasks[idx]
            spec_info = spec.poll_info()
            winner = None
            if original.task_id != orig_id:
                # recovery replaced the original underneath us: the spec's
                # inputs/consumers may be stale — drop it
                winner = "original"
            elif spec_info is not None and spec_info.state == FAILED:
                winner = "original"  # spec failures never fail the query
            elif original.info is not None \
                    and original.info.state in DONE_STATES:
                winner = "original"
            elif spec_info is not None and spec_info.state == FINISHED:
                if self._rewire_consumers(stage.fragment, original, spec,
                                          active_nodes):
                    stage.tasks[idx] = spec
                    winner = "speculative"
                else:
                    winner = "original"  # unsound rewire: keep waiting it out
            if winner is None:
                continue
            del self._live_spec[key]
            loser = original if winner == "speculative" else spec
            loser.cancel(abort=True)
            events.emit("task.speculated", severity=events.INFO,
                        query_id=self.query_id, task_id=orig_id,
                        speculative_task_id=spec.task_id, winner=winner,
                        original_node=original.node.node_id,
                        speculative_node=spec.node.node_id)

    def is_finished(self) -> bool:
        info = self.root_task().info
        return info is not None and info.state == FINISHED

    def abort(self) -> None:
        for task in self.all_tasks():
            task.cancel(abort=True)
        for _, spec in self._live_spec.values():
            spec.cancel(abort=True)
        self._live_spec.clear()


def _remote_source_ids(node) -> List[int]:
    out: List[int] = []

    def walk(n):
        if isinstance(n, RemoteSourceNode):
            out.append(n.fragment_id)
            return
        for c in n.children():
            walk(c)
    walk(node)
    return out
