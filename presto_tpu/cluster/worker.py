"""Worker server: the task-execution HTTP endpoint of a cluster node.

Analogue of the worker role of server/PrestoServer.java + server/TaskResource
(/root/reference/presto-main/.../server/TaskResource.java:84,122,245):

  POST   /v1/task/{taskId}                         create/update (JSON
                                                   TaskUpdateRequest body)
  GET    /v1/task/{taskId}                         TaskInfo (JSON)
  DELETE /v1/task/{taskId}[?abort=true]            cancel/abort
  GET    /v1/task/{taskId}/results/{buf}/{token}   pull one page frame
         (binary body; X-Next-Token / X-Complete headers; ?wait= long-poll)
  DELETE /v1/task/{taskId}/results/{buf}           release the client buffer
  GET    /v1/status                                heartbeat + node info
                                                   (+ per-task drain progress)
  GET    /v1/info/state                            drain-progress poll: state
                                                   + active tasks + spool
  PUT    /v1/info/state                            "DRAINING" (or the legacy
                                                   "SHUTTING_DOWN") enters the
                                                   drain machine
                                                   (GracefulShutdownHandler.java:43)

Lifecycle: ACTIVE → DRAINING → DRAINED → SHUT_DOWN (see _TRANSITIONS).

Control-plane bodies are structured JSON (cluster/codec.py allow-list codec —
the reference uses JSON/SMILE on the same boundary,
server/InternalCommunicationConfig.java:92-98; pickle would be remote code
execution for anything that can reach the port). Both ends run this binary, the
reference's JSON/SMILE codec pair plays the equivalent role across its JVMs.
Workers announce themselves to the coordinator (discovery.Announcer)."""
from __future__ import annotations

import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..metadata import CatalogManager, MetadataManager
from . import codec, faults
from .buffers import ReplayWindowLost
from .task import (DONE_STATES, SourceUpdateRequest, TaskUpdateRequest,
                   WorkerTaskManager)

ACTIVE = "ACTIVE"
DRAINING = "DRAINING"
DRAINED = "DRAINED"
SHUT_DOWN = "SHUT_DOWN"
# legacy protocol alias (GracefulShutdownHandler.java wire vocabulary): a
# PUT of "SHUTTING_DOWN" enters the drain machine at DRAINING
SHUTTING_DOWN = "SHUTTING_DOWN"

# the drain state machine: ACTIVE → DRAINING → DRAINED → SHUT_DOWN.
# DRAINING refuses new tasks but keeps serving live streams; DRAINED means
# every task reached a DONE state (finished, or its consumers were handed to
# replacements) and the node deregistered from discovery; SHUT_DOWN is the
# terminal hard stop. Anything else is an illegal transition.
_TRANSITIONS = {
    ACTIVE: {DRAINING, SHUT_DOWN},
    DRAINING: {DRAINED, SHUT_DOWN},
    DRAINED: {SHUT_DOWN},
    SHUT_DOWN: set(),
}


def default_catalogs() -> CatalogManager:
    """Every node builds the same static catalog set from its own process
    (the reference loads etc/catalog/*.properties per node)."""
    from ..connectors.blackhole import BlackholeConnector
    from ..connectors.tpcds import TpcdsConnector
    from ..connectors.tpch.connector import TpchConnector

    catalogs = CatalogManager()
    catalogs.register("tpch", TpchConnector("tpch"))
    catalogs.register("tpcds", TpcdsConnector("tpcds"))
    catalogs.register("blackhole", BlackholeConnector("blackhole"))
    return catalogs


class _WorkerHandler(BaseHTTPRequestHandler):
    worker: "WorkerServer" = None  # bound per server instance
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003
        pass

    def _send(self, body: bytes, status: int = 200, headers=()) -> None:
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _send_codec(self, obj, status: int = 200) -> None:
        self._send(codec.dumps(obj), status,
                   [("Content-Type", "application/json")])

    def _inject(self, point: str, **ctx) -> bool:
        """Fault-injection hook (cluster/faults.py). True = the request was
        consumed by an injected fault and the caller must return."""
        try:
            faults.fire(point, node_id=self.worker.node_id, path=self.path,
                        **ctx)
        except faults.InjectedHTTPError as e:
            self._send(e.body.encode(), e.code)
            return True
        except faults.InjectedFault:
            # slam the connection: no status line, no body — the client sees
            # the peer reset a real worker crash would produce
            self.close_connection = True
            return True
        return False

    # ------------------------------------------------------------ endpoints

    def do_POST(self) -> None:  # noqa: N802
        m = re.fullmatch(r"/v1/task/([^/]+)/sources", self.path)
        if m:
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            task = self.worker.tasks.get(m.group(1))
            if task is None:
                return self._send(b"no such task", 404)
            try:
                update: SourceUpdateRequest = codec.loads(body)
                if not isinstance(update, SourceUpdateRequest):
                    # not an assert: those vanish under python -O and the
                    # AttributeError would then escape as a dropped
                    # connection the peer misreads as a transient fault
                    raise TypeError(
                        f"expected SourceUpdateRequest, got "
                        f"{type(update).__name__}")
            except Exception as e:
                return self._send(f"bad sources body: {e}".encode(), 400)
            if not task.update_sources(update):
                return self._send(
                    b"rewire rejected: stream already consumed", 409)
            return self._send(b"", 200)
        m = re.fullmatch(r"/v1/task/([^/]+)", self.path)
        if not m:
            return self._send(b"not found", 404)
        if self._inject("worker.task_create", task_id=m.group(1)):
            return
        if self.worker.state != ACTIVE:
            # draining/drained workers refuse placement; the scheduler
            # treats this 503 as "exclude + re-place NOW", not a transient
            return self._send(b"shutting down", 503)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        try:
            request: TaskUpdateRequest = codec.loads(body)
        except Exception as e:  # non-JSON / unregistered class: reject
            return self._send(f"bad task body: {e}".encode(), 400)
        if not isinstance(request, TaskUpdateRequest):
            return self._send(b"bad task body: not a TaskUpdateRequest", 400)
        try:
            info = self.worker.tasks.create_or_update(request)
        except ValueError as e:
            return self._send(str(e).encode(), 409)
        self._send_codec(info)

    def do_GET(self) -> None:  # noqa: N802
        path, _, query = self.path.partition("?")
        m = re.fullmatch(r"/v1/task/([^/]+)/results/(\d+)/(\d+)", path)
        if m:
            if self._inject("worker.results", task_id=m.group(1)):
                return
            task = self.worker.tasks.get(m.group(1))
            if task is None:
                return self._send(b"no such task", 404)
            wait = float(urllib.parse.parse_qs(query).get("wait", ["1.0"])[0])
            buffer_id = int(m.group(2))
            if buffer_id >= task.output.n_buffers:
                return self._send(b"no such buffer", 404)
            try:
                frame, nxt, complete = task.output.get(
                    buffer_id, int(m.group(3)), wait_s=min(wait, 30.0))
            except ReplayWindowLost as e:
                # the requested chunk was retired from the replay spool
                # (overflow / nondeterministic sink / released buffer):
                # waiting would never produce it. 410 is a HARD error on the
                # consumer — mid-stream recovery is unsound here and must
                # escalate loudly to a query-level retry
                return self._send(str(e).encode(), 410)
            except Exception as e:
                # failed/poisoned buffer -> 500: consumers treat 5xx as
                # transient-within-budget, which is what keeps them alive
                # through the task-recovery rewire window; the body carries
                # the diagnostic PageBufferClient reports if the budget
                # exhausts, and the coordinator's monitor loop surfaces the
                # underlying task failure within one 0.5s tick anyway
                return self._send(str(e).encode(), 500)
            return self._send(
                frame or b"", 200,
                [("Content-Type", "application/octet-stream"),
                 ("X-Next-Token", str(nxt)),
                 ("X-Complete", "true" if complete else "false"),
                 ("X-Task-Instance-Id", task.instance_id)])
        m = re.fullmatch(r"/v1/task/([^/]+)", path)
        if m:
            if self._inject("worker.task_info", task_id=m.group(1)):
                return
            task = self.worker.tasks.get(m.group(1))
            if task is None:
                return self._send(b"no such task", 404)
            return self._send_codec(task.info())
        if path.rstrip("/") == "/v1/status":
            import json
            # a status poll doubles as a drain tick: DRAINING → DRAINED the
            # moment the last task reaches a DONE state, so pollers observe
            # the transition deterministically (no monitor-thread race)
            self.worker.maybe_complete_drain()
            active = 0
            query_mem = {}
            live_queries = set()
            spooled = 0
            drain_tasks = {}
            if self.worker.state != ACTIVE:
                # per-task drain progress: everything still pinning the node
                # — live tasks, plus DONE tasks whose streams consumers are
                # still pulling
                for tid, t in self.worker.tasks.tasks.items():
                    served = t.output.output_drained()
                    if t.state in DONE_STATES and served:
                        continue
                    drain_tasks[tid] = {
                        "state": t.state,
                        "spooledBytes": t.output.spooled_bytes(),
                        "retainedBytes": t.output.retained_bytes(),
                        "replayable": t.output.replayable_all(),
                        "outputDrained": served,
                    }
            for t in self.worker.tasks.tasks.values():
                if t.state in DONE_STATES:
                    continue
                active += 1
                qid = t.request.query_id
                live_queries.add(qid)
                # unacked output frames; spooled (acked, replayable) bytes
                # are already reserved in the shared pool under the query id
                query_mem[qid] = query_mem.get(qid, 0) + \
                    t.output.retained_bytes()
                spooled += t.output.spooled_bytes()
            # unified footprint: operator state + scan prefetch reserved in
            # the worker's shared pool (cluster/task._query_memory) — the
            # OOM killer must see the WHOLE per-query byte count, not just
            # output buffers. Done queries' residue is excluded.
            from ..memory import shared_general_pool
            pool = shared_general_pool()
            by_query = pool.by_query()
            revocable = pool.revocable_by_query()
            spill = pool.spill_by_query()
            # GC walks the UNION of the pool's ledgers: a dead query may
            # leave residue in only the spill (or revocable) ledger
            for qid in set(by_query) | set(revocable) | set(spill):
                if qid in live_queries:
                    if qid in by_query:
                        query_mem[qid] = query_mem.get(qid, 0) \
                            + int(by_query[qid])
                else:
                    # no live task of this query remains on the worker: any
                    # leftover reservation is a failed-teardown leak — clear
                    # it here (the memory manager polls status every second,
                    # so this doubles as the worker's pool GC)
                    pool.clear_query(qid)
            return self._send(json.dumps({
                "nodeId": self.worker.node_id,
                "state": self.worker.state,
                "activeTasks": active,
                # per-query reserved bytes — the ClusterMemoryManager's feed
                # (memory/RemoteNodeMemory.java analogue)
                "queryMemory": query_mem,
                # the revocable slice of queryMemory: what a revoke round
                # could move down the ladder (device->host->disk) instead of
                # killing — the manager's revoke-before-kill evidence
                "queryRevocable": {q: int(b) for q, b in revocable.items()
                                   if q in live_queries},
                # per-query on-disk spill bytes (exec/spill.py runs): the
                # disk rung, charged outside queryMemory so spilling
                # relieves reported pressure but stays observable
                "querySpill": {q: int(b) for q, b in spill.items()
                               if q in live_queries},
                # acked-frame replay spool across live tasks (also counted
                # inside queryMemory via the shared pool)
                "spooledBytes": spooled,
                # per-task drain progress (empty map when ACTIVE): what an
                # operator watches while the node works toward DRAINED
                "drain": drain_tasks,
                "uptime": round(time.monotonic() - self.worker.start_mono, 1),
            }).encode(), 200, [("Content-Type", "application/json")])
        if path.rstrip("/") == "/v1/info/state":
            # drain-progress poll (the PUT's read side): state + what still
            # pins the node, without the /v1/status memory side channels
            import json
            self.worker.maybe_complete_drain()
            active = 0
            draining = 0
            spooled = 0
            tasks = {}
            for tid, t in self.worker.tasks.tasks.items():
                done = t.state in DONE_STATES
                served = t.output.output_drained()
                if done and served:
                    continue
                if not done:
                    active += 1
                draining += 1
                spooled += t.output.spooled_bytes()
                tasks[tid] = {
                    "state": t.state,
                    "spooledBytes": t.output.spooled_bytes(),
                    "replayable": t.output.replayable_all(),
                    "outputDrained": served,
                }
            return self._send(json.dumps({
                "state": self.worker.state,
                "activeTasks": active,
                # tasks that would pin a drain: live ones plus DONE tasks
                # whose streams consumers are still pulling
                "drainingTasks": draining,
                "spooledBytes": spooled,
                "tasks": tasks,
            }).encode(), 200, [("Content-Type", "application/json")])
        if path.rstrip("/").startswith("/v1/metrics"):
            # same surface as the coordinator: flat JSON, ?raw=1 (the
            # mergeable bucket snapshot GET /v1/cluster/metrics consumes),
            # ?format=prometheus for direct scraping of each worker
            from ..utils.metrics import metrics_http_body

            prefix = path.rstrip("/")[len("/v1/metrics"):].lstrip("/")
            body, ctype = metrics_http_body(query, prefix=prefix)
            return self._send(body, 200, [("Content-Type", ctype)])
        if path.rstrip("/") == "/v1/events":
            from ..utils.events import events_http_body

            body, status = events_http_body(query)
            return self._send(body, status,
                              [("Content-Type", "application/json")])
        self._send(b"not found", 404)

    def do_HEAD(self) -> None:  # noqa: N802 — failure-detector ping
        if self.path.rstrip("/") == "/v1/status":
            if self._inject("worker.status"):
                return
            return self._send(b"", 200)
        self._send(b"", 404)

    def do_DELETE(self) -> None:  # noqa: N802
        m = re.fullmatch(r"/v1/task/([^/]+)/results/(\d+)", self.path)
        if m:
            task = self.worker.tasks.get(m.group(1))
            if task is not None:
                task.output.abort(int(m.group(2)))
            return self._send(b"", 204)
        path, _, query = self.path.partition("?")
        m = re.fullmatch(r"/v1/task/([^/]+)", path)
        if m:
            abort = "abort=true" in query
            self.worker.tasks.cancel(m.group(1), abort=abort)
            return self._send(b"", 204)
        self._send(b"not found", 404)

    def do_PUT(self) -> None:  # noqa: N802 — graceful shutdown / drain
        if self.path.rstrip("/") == "/v1/info/state":
            length = int(self.headers.get("Content-Length", 0))
            state = self.rfile.read(length).decode().strip().strip('"')
            if state in (DRAINING, SHUTTING_DOWN):
                # SHUTTING_DOWN is the legacy wire alias: both enter the
                # drain machine (idle workers reach DRAINED immediately)
                try:
                    reached = self.worker.begin_drain()
                except ValueError as e:
                    return self._send(str(e).encode(), 409)
                return self._send(f'"{reached}"'.encode(), 200,
                                  [("Content-Type", "application/json")])
            if state in (ACTIVE, DRAINED, SHUT_DOWN):
                # real states, but not externally settable: DRAINED is
                # earned by finishing tasks, SHUT_DOWN by stop()
                return self._send(
                    f"cannot request transition to {state}".encode(), 409)
            return self._send(b"bad state", 400)
        self._send(b"not found", 404)


class WorkerServer:
    """One worker node: HTTP server + task manager + announcer."""

    def __init__(self, port: int = 0,
                 catalogs: Optional[CatalogManager] = None,
                 coordinator_uri: Optional[str] = None,
                 node_id: Optional[str] = None,
                 host: str = "127.0.0.1",
                 announce_host: Optional[str] = None):
        """`host` is the bind address; `announce_host` is what peers dial
        (defaults to `host`) — a worker binding 0.0.0.0 must announce a
        routable address, not the wildcard."""
        faults.install_from_env()  # PRESTO_TPU_FAULTS chaos knob (no-op unset)
        catalogs = catalogs or default_catalogs()
        self.metadata = MetadataManager(catalogs)
        self.tasks = WorkerTaskManager(self.metadata)
        self.state = ACTIVE
        self._state_lock = threading.RLock()
        self._drain_stop = threading.Event()
        self.start_time = time.time()      # wall timestamp (diagnostics)
        self.start_mono = time.monotonic()  # uptime duration base
        handler = type("BoundWorkerHandler", (_WorkerHandler,), {"worker": self})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        announce = announce_host or host
        if announce == "0.0.0.0":
            # gethostbyname(hostname) often maps to 127.0.1.1 via /etc/hosts;
            # a routed UDP socket's source address is the reachable interface
            import socket
            probe = coordinator_uri or "http://8.8.8.8"
            target = urllib.parse.urlsplit(probe).hostname or "8.8.8.8"
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect((target, 80))  # no packets sent; just routes
                announce = s.getsockname()[0]
            except OSError:
                announce = socket.gethostbyname(socket.gethostname())
            finally:
                s.close()
        self.uri = f"http://{announce}:{self.port}"
        self.node_id = node_id or f"worker-{self.port}"
        self._announcer = None
        if coordinator_uri:
            from .discovery import Announcer
            self._announcer = Announcer(coordinator_uri, self.node_id, self.uri)

    def start(self) -> "WorkerServer":
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever,
            name=f"worker-{self.port}", daemon=True)
        self._serve_thread.start()
        if self._announcer:
            self._announcer.start()
        return self

    # ------------------------------------------------- drain state machine

    def transition(self, new_state: str) -> bool:
        """Move the node through ACTIVE → DRAINING → DRAINED → SHUT_DOWN.
        Same-state is an idempotent no-op (False); anything not in the
        transition map raises — an illegal transition is a caller bug, not
        a race to paper over."""
        with self._state_lock:
            if new_state == self.state:
                return False
            if new_state not in _TRANSITIONS.get(self.state, set()):
                raise ValueError(
                    f"illegal worker state transition "
                    f"{self.state} -> {new_state}")
            self.state = new_state
            return True

    def begin_drain(self, reason: str = "") -> str:
        """Enter DRAINING: refuse new tasks (503), pin every live task's
        output spool so its replay window stays complete for the consumer
        handoff, and watch for the last task to reach a DONE state. Returns
        the state reached NOW — an idle worker completes its drain
        synchronously and returns DRAINED. Idempotent while draining;
        raises from DRAINED/SHUT_DOWN (nothing left to drain)."""
        with self._state_lock:
            if self.state == DRAINING:
                return self.state
            self.transition(DRAINING)
        from ..utils import events
        events.emit("worker.draining", severity=events.WARN,
                    node=self.node_id, reason=reason,
                    active_tasks=self.active_task_count())
        for t in list(self.tasks.tasks.values()):
            # pin every spool (done tasks may still be serving): an acked
            # frame retired during the handoff window would turn a planned
            # drain into a 410 escalation
            t.output.pin_spool()
        if not self.maybe_complete_drain():
            self._drain_thread = threading.Thread(
                target=self._drain_loop, name=f"drain-{self.node_id}",
                daemon=True)
            self._drain_thread.start()
        return self.state

    def maybe_complete_drain(self) -> bool:
        """DRAINING → DRAINED when nothing pins the node: every task is in a
        DONE state (finished, or aborted after its consumers were handed to
        a replacement) AND its output streams are fully delivered — a
        FINISHED task still serving spooled chunks to live consumers keeps
        the node DRAINING until they catch up or are rewired elsewhere.
        Called by the drain monitor AND by the status/state endpoints so
        pollers never race the monitor thread."""
        with self._state_lock:
            if self.state != DRAINING or self.draining_task_count() > 0:
                return False
            self.transition(DRAINED)
        # the node is out of work: deregister EXPLICITLY so the scheduler
        # stops seeing it now, not a heartbeat-decay window later
        if self._announcer:
            self._announcer.stop()
            self._announcer.deregister()
        from ..utils import events
        events.emit("worker.drained", severity=events.INFO,
                    node=self.node_id)
        return True

    def _drain_loop(self) -> None:
        while not self._drain_stop.wait(0.1):
            if self.state != DRAINING or self.maybe_complete_drain():
                return

    def begin_shutdown(self) -> None:
        """Legacy entry point (the old one-flag shutdown): now an alias that
        enters the drain machine. The process exits when active tasks finish
        (GracefulShutdownHandler semantics) — and, unlike the old flag, the
        coordinator is TOLD when the node is out of work (deregister at
        DRAINED) instead of discovering it by heartbeat decay."""
        self.begin_drain(reason="begin_shutdown")

    def active_task_count(self) -> int:
        return sum(1 for t in self.tasks.tasks.values()
                   if t.state not in DONE_STATES)

    def draining_task_count(self) -> int:
        """Tasks that still pin a DRAINING node: live, or done but with
        consumers mid-pull on their output streams."""
        return sum(1 for t in self.tasks.tasks.values()
                   if t.state not in DONE_STATES
                   or not t.output.output_drained())

    def stop(self) -> None:
        with self._state_lock:
            self.state = SHUT_DOWN  # hard stop: bypasses transition checks
        self._drain_stop.set()
        if self._announcer:
            self._announcer.stop()
        for t in list(self.tasks.tasks.values()):
            t.cancel(abort=True)
        self.httpd.shutdown()
        self.httpd.server_close()
        serve = getattr(self, "_serve_thread", None)
        if serve is not None:
            serve.join(timeout=5.0)
        drain = getattr(self, "_drain_thread", None)
        if drain is not None:
            drain.join(timeout=5.0)


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(prog="presto-tpu-worker")
    ap.add_argument("--port", type=int, default=8081)
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (0.0.0.0 to serve other hosts)")
    ap.add_argument("--announce-host", default=None,
                    help="address peers should dial (defaults to --host, or "
                         "this host's name when binding 0.0.0.0)")
    ap.add_argument("--node-id", default=None)
    ap.add_argument("--coordinator", default=None,
                    help="coordinator URI to announce to")
    ap.add_argument("--etc", default=None,
                    help="config directory with catalog/*.properties — every "
                         "node must load the same catalog set")
    ap.add_argument("--event-log", default=None, metavar="PATH",
                    help="append this worker's structured event journal "
                         "(task lifecycle, spills) as JSONL to PATH")
    args = ap.parse_args(argv)
    if args.event_log:
        from ..utils.events import JOURNAL
        JOURNAL.set_log_path(args.event_log)
    catalogs = None
    if args.etc:
        from ..server.config import load_catalogs, load_plugins_for_etc

        load_plugins_for_etc(args.etc)
        catalogs = load_catalogs(args.etc)
    server = WorkerServer(port=args.port, coordinator_uri=args.coordinator,
                          host=args.host, announce_host=args.announce_host,
                          node_id=args.node_id, catalogs=catalogs)
    if server._announcer:
        server._announcer.start()
    print(f"presto-tpu worker {server.node_id} listening on "  # prestocheck: ignore[print-hygiene] - CLI startup banner
          f":{server.port}")
    server.httpd.serve_forever()


if __name__ == "__main__":
    main()
