"""Cluster memory manager: cross-worker memory accounting + OOM killing.

Analogue of memory/ClusterMemoryManager.java:92 (coordinator polls every
worker's memory state through its status endpoint) and the
TotalReservationLowMemoryKiller policy: when the cluster's total reserved
bytes stay over the limit for `grace_polls` consecutive polls, the query
with the LARGEST total reservation across workers is killed — freeing the
most memory with one victim, exactly the reference policy's choice.

Killing is the LAST rung of the memory ladder, not the first response:
when workers report revocable bytes (their operators can still move state
device->host->disk), the manager first journals ``memory.revoke`` and
requests a revoke round, then waits exactly one more poll — the bounded
beat that lets spilling land — and only if the cluster is STILL over the
limit does it select a victim. ``query.oom_killed`` then records whether
revocation was attempted and how many revocable bytes remained, so a
post-mortem can tell "nothing left to spill" from "killed too eagerly".

Workers report {query_id: bytes} via /v1/status (see worker.py); the kill
action is injected so the coordinator wires its own task cancellation and
tests wire a recorder.
"""
from __future__ import annotations

import json
import threading
import urllib.request
from typing import Callable, Dict, List, Optional


class ClusterMemoryManager:
    def __init__(self, nodes, kill_query: Callable[[str], None],
                 limit_bytes: int = 32 << 30,
                 poll_period_s: float = 1.0,
                 grace_polls: int = 2,
                 fetch_status: Optional[Callable[[str], Dict]] = None,
                 request_revoke: Optional[Callable[[], None]] = None):
        """`nodes` provides active_nodes() (DiscoveryNodeManager); a custom
        `fetch_status(uri)` replaces the HTTP GET in tests.
        `request_revoke` (best-effort, optional) nudges workers to run a
        revoke round NOW instead of waiting for their own pressure checks;
        the revoke-before-kill beat happens regardless — operators revoke
        on their next add_input under pressure either way."""
        self.nodes = nodes
        self.kill_query = kill_query
        self.limit_bytes = limit_bytes
        self.poll_period_s = poll_period_s
        self.grace_polls = grace_polls
        self._fetch = fetch_status or self._http_status
        self.request_revoke = request_revoke
        self._over_count = 0
        self._revoke_requested = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="cluster-memory", daemon=True)
        self.last_total = 0
        self.last_by_query: Dict[str, int] = {}
        self.last_revocable: Dict[str, int] = {}
        # per-node activity from the same poll — the autoscaler's pressure
        # feed (it must never add its own status-poll storm on top of this
        # monitor loop's)
        self.last_active_tasks: Dict[str, int] = {}
        self.last_spooled: Dict[str, int] = {}
        self.killed: List[str] = []

    def saturation(self) -> float:
        """Cluster memory pressure 0.0..: last polled total reserved bytes
        over the limit (>=1.0 means the OOM ladder is in play)."""
        return self.last_total / self.limit_bytes if self.limit_bytes else 0.0

    # ------------------------------------------------------------------ api

    def start(self) -> "ClusterMemoryManager":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def poll_once(self) -> Optional[str]:
        """One poll + policy step; returns the killed query id, if any."""
        from ..utils import events
        by_query: Dict[str, int] = {}
        revocable: Dict[str, int] = {}
        per_node: Dict[str, Dict[str, int]] = {}
        active_tasks: Dict[str, int] = {}
        spooled: Dict[str, int] = {}
        total = 0
        for node in self.nodes.active_nodes():
            try:
                status = self._fetch(node.uri)
            except Exception:  # noqa: BLE001 - dead nodes are the detector's job
                continue
            name = getattr(node, "node_id", None) or getattr(node, "uri", "?")
            active_tasks[name] = int(status.get("activeTasks") or 0)
            spooled[name] = int(status.get("spooledBytes") or 0)
            node_mem = {qid: int(b)
                        for qid, b in (status.get("queryMemory") or {}).items()}
            if node_mem:
                # `name` tolerates minimal node stand-ins (tests inject bare
                # uri-only objects); the uri always identifies the worker
                per_node[name] = node_mem
            for qid, b in node_mem.items():
                by_query[qid] = by_query.get(qid, 0) + b
                total += b
            for qid, b in (status.get("queryRevocable") or {}).items():
                revocable[qid] = revocable.get(qid, 0) + int(b)
        self.last_total = total
        self.last_by_query = by_query
        self.last_revocable = revocable
        self.last_active_tasks = active_tasks
        self.last_spooled = spooled
        if total <= self.limit_bytes or not by_query:
            self._over_count = 0
            self._revoke_requested = False
            return None
        self._over_count += 1
        if self._over_count < self.grace_polls:
            return None  # transient spike: give revocation/spill a chance
        revocable_total = sum(revocable.values())
        if revocable_total > 0 and not self._revoke_requested:
            # kill is the LAST rung: the workers still hold revocable state,
            # so request a revoke round (device->host->disk) and wait
            # exactly one more poll for the spill to land before deciding
            self._revoke_requested = True
            events.emit("memory.revoke", severity=events.WARN,
                        requested_bytes=revocable_total, total_bytes=total,
                        limit_bytes=self.limit_bytes, per_node=per_node)
            if self.request_revoke is not None:
                try:
                    self.request_revoke()
                except Exception:  # noqa: BLE001 - best-effort nudge only
                    pass
            return None
        victim = max(by_query.items(), key=lambda kv: kv[1])[0]
        revoke_attempted = self._revoke_requested
        self._over_count = 0
        self._revoke_requested = False
        self.killed.append(victim)
        # journal the DECISION with the evidence that justified it: the
        # per-worker per-query byte snapshot at kill time is exactly what a
        # post-mortem needs and is gone one poll later
        events.emit("query.oom_killed", severity=events.ERROR,
                    query_id=victim,
                    victim_bytes=by_query[victim], total_bytes=total,
                    limit_bytes=self.limit_bytes, per_node=per_node,
                    revoke_attempted=revoke_attempted,
                    revocable_bytes=revocable_total)
        try:
            self.kill_query(victim)
        except Exception:  # noqa: BLE001 - kill is best-effort; retried next poll
            pass
        return victim

    # ------------------------------------------------------------- internal

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_period_s):
            self.poll_once()

    @staticmethod
    def _http_status(uri: str) -> Dict:
        # raise-through by design: poll_once classifies the failure (a dead
        # node is the failure detector's job, this poll just skips it)
        with urllib.request.urlopen(f"{uri}/v1/status", timeout=2.0) as resp:  # prestocheck: ignore[retry-discipline]
            return json.loads(resp.read())
