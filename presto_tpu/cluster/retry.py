"""Shared retry machinery for the cluster tier: backoff + failure taxonomy.

Analogue of server/remotetask/Backoff.java (/root/reference/presto-main): one
jittered-exponential-delay class with a transient-failure budget, shared by
every retry loop on the coordinator<->worker boundary (remote task create,
exchange page pulls, worker announcements, the consumer tail-poll) instead of
the divergent ad-hoc loops each of those sites grew independently.

Also home of the RetryPolicy vocabulary (SystemSessionProperties'
retry_policy) and the retryable-failure classification the coordinator uses
to decide whether a query attempt may be transparently re-run:

  NONE   — today's behavior: any task failure or node death fails the query.
  QUERY  — the coordinator re-plans and re-executes the whole query on a
           retryable failure, excluding failed nodes from placement.
  TASK   — QUERY, plus in-place recovery of failed tasks (leaf AND
           interior, mid-stream included): upstream buffers spool acked
           chunks (cluster/buffers.py), so a replacement task re-pulls its
           inputs from sequence 0 and each consumer re-issues GET from its
           chunk cursor against the replacement (cluster/exchange_client).
           The unsound remainder — a replay window retired from a bounded
           spool (HTTP 410), a nondeterministic multi-driver sink, or a
           consumer that cannot be rewired — escalates loudly to a
           query-level retry, matching the reference's split between
           pipelined and fault-tolerant (spooled) execution.
"""
from __future__ import annotations

import random
import time
import urllib.error
from typing import Callable, Optional

# RetryPolicy vocabulary (session property "retry_policy")
NONE = "NONE"
QUERY = "QUERY"
TASK = "TASK"
RETRY_POLICIES = (NONE, QUERY, TASK)


def retry_policy(session) -> str:
    policy = str(session.get("retry_policy") or NONE).upper()
    if policy not in RETRY_POLICIES:
        raise ValueError(
            f"invalid retry_policy {policy!r} (one of {RETRY_POLICIES})")
    return policy


class Backoff:
    """Jittered exponential backoff with a transient-failure budget.

    ``failure()`` records one failure and returns True when the budget is
    exhausted (at least ``min_tries`` failures AND ``max_failure_interval_s``
    elapsed since the first unhealed failure — Backoff.java:101's contract).
    ``success()`` heals the streak. ``wait()`` sleeps the current jittered
    delay and accounts it in ``total_backoff_s``.

    Clock, sleeper and RNG are injectable so tests drive every retry path
    deterministically (no sleeps-and-hope)."""

    def __init__(self, max_failure_interval_s: float = 60.0,
                 initial_delay_s: float = 0.05,
                 max_delay_s: float = 2.0,
                 min_tries: int = 3,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        assert min_tries >= 1
        self.max_failure_interval_s = max_failure_interval_s
        self.initial_delay_s = initial_delay_s
        self.max_delay_s = max_delay_s
        self.min_tries = min_tries
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self._sleep = sleep
        self.failure_count = 0
        self.first_failure_at: Optional[float] = None
        self.last_failure_at: Optional[float] = None
        self.total_backoff_s = 0.0

    def failure(self) -> bool:
        """Record a failure; True when the transient budget is exhausted."""
        now = self._clock()
        self.failure_count += 1
        if self.first_failure_at is None:
            self.first_failure_at = now
        self.last_failure_at = now
        return (self.failure_count >= self.min_tries
                and now - self.first_failure_at >= self.max_failure_interval_s)

    def success(self) -> None:
        self.failure_count = 0
        self.first_failure_at = None

    def time_since_first_failure_s(self) -> float:
        if self.first_failure_at is None:
            return 0.0
        return self._clock() - self.first_failure_at

    def backoff_delay_s(self) -> float:
        """Current delay: initial * 2^(failures-1), capped, with 50% jitter."""
        if self.failure_count == 0:
            return 0.0
        exponent = min(self.failure_count - 1, 16)  # cap 2**k well below inf
        delay = min(self.max_delay_s, self.initial_delay_s * (2 ** exponent))
        return delay * (0.5 + 0.5 * self._rng.random())

    def wait(self) -> float:
        delay = self.backoff_delay_s()
        if delay > 0:
            self._sleep(delay)
            self.total_backoff_s += delay
        return delay


# --------------------------------------------------------------- failure taxonomy

class ClusterExecutionError(RuntimeError):
    """A cluster-tier failure with enough structure for the retry loop:
    which node (for placement exclusion) and whether re-running could help."""

    def __init__(self, message: str, node_id: Optional[str] = None,
                 retryable: bool = False):
        super().__init__(message)
        self.node_id = node_id
        self.retryable = retryable


class NodeDiedError(ClusterExecutionError):
    """A worker stopped announcing / answering with live tasks on it."""

    def __init__(self, message: str, node_id: Optional[str] = None):
        super().__init__(message, node_id=node_id, retryable=True)


class TaskFailedError(ClusterExecutionError):
    """A task reported FAILED; retryable iff the remote error looks like a
    transport/environment fault rather than a deterministic query error."""


# error types (TaskInfo.error["type"]) that indicate the environment, not the
# query: retrying elsewhere can heal these, a SQL error it cannot
_RETRYABLE_ERROR_TYPES = {
    "ConnectionResetError", "ConnectionRefusedError", "ConnectionAbortedError",
    "BrokenPipeError", "TimeoutError", "OSError", "URLError",
    "InjectedFault", "InjectedDisconnect",
}

_RETRYABLE_MESSAGE_MARKERS = (
    "unreachable", "was recreated", "connection reset", "connection refused",
    "remote end closed", "timed out", "injected fault", "worker killed",
    "output buffer failed", "task output failed",
    # spool replay unsound (410): only a full query re-run can help
    "replay window lost", "cannot replay",
)


def error_dict_retryable(error: Optional[dict]) -> bool:
    """Classify a remote TaskInfo.error dict."""
    if not error:
        return False
    if error.get("type") in _RETRYABLE_ERROR_TYPES:
        return True
    message = str(error.get("message") or "").lower()
    return any(marker in message for marker in _RETRYABLE_MESSAGE_MARKERS)


def is_retryable(exc: BaseException) -> bool:
    """May a new query attempt on (possibly different) nodes succeed?"""
    if isinstance(exc, ClusterExecutionError):
        return exc.retryable
    if isinstance(exc, (urllib.error.URLError, ConnectionError,
                        TimeoutError, OSError)):
        return True
    message = str(exc).lower()
    return any(marker in message for marker in _RETRYABLE_MESSAGE_MARKERS)
