"""Page wire format for the cross-host (DCN) data plane.

Analogue of execution/buffer/PagesSerde.java:39,55 + PagesSerdeFactory.java:38
(/root/reference/presto-main): the reference ships LZ4-compressed block-encoded
pages over HTTP; here a batch of pages becomes ONE columnar frame — dead
(masked-off) rows are dropped producer-side, each column's dense little-endian
bytes are concatenated and zlib-compressed per frame (zlib level 1 plays the
LZ4 "cheap and fast" role; it is what the environment provides).

Frame layout:
    magic 'PSER1'  | u32 header_len | header json | column payload...
Header: {"rows": N, "cols": [{"dtype": str, "nbytes": int, "nulls": bool}],
         "codec": "zlib1" | "raw"}
Payload: for each column, data bytes then (if nulls) a packed null bitmap.
Compressed when that wins, raw otherwise (PagesSerde's same tradeoff).

Types/dictionaries do NOT ride the wire: both ends plan the same fragment and
already agree on the schema (the reference ships block encodings instead; our
schema is a plan-time property, see cluster/task.py).
"""
from __future__ import annotations

import json
import struct
import zlib
from typing import List, Optional, Sequence

import numpy as np

from ..block import Block, Dictionary, Page
from ..types import Type

MAGIC = b"PSER1"


def pages_to_columns(pages: Sequence[Page], types: Sequence[Type]
                     ) -> tuple:
    """Concat pages, drop masked rows -> (datas, nulls, nrows). Host side."""
    ncols = len(types)
    if not pages:
        return ([np.zeros(0, dtype=np.dtype(t.np_dtype)) for t in types],
                [None] * ncols, 0)
    mask = np.concatenate([np.asarray(p.mask) for p in pages])
    keep = np.flatnonzero(mask)
    datas: List[np.ndarray] = []
    nulls: List[Optional[np.ndarray]] = []
    for c in range(ncols):
        dt = np.dtype(types[c].np_dtype)
        col = np.concatenate([np.asarray(p.blocks[c].data) for p in pages])
        datas.append(np.ascontiguousarray(col.astype(dt, copy=False)[keep]))
        if any(p.blocks[c].nulls is not None for p in pages):
            nm = np.concatenate(
                [np.asarray(p.blocks[c].nulls) if p.blocks[c].nulls is not None
                 else np.zeros(p.capacity, dtype=bool) for p in pages])
            nm = nm[keep]
            nulls.append(nm if nm.any() else None)
        else:
            nulls.append(None)
    return datas, nulls, len(keep)


def serialize_columns(datas: Sequence[np.ndarray],
                      nulls: Sequence[Optional[np.ndarray]],
                      nrows: int, compress: bool = True) -> bytes:
    cols_meta = []
    payload = bytearray()
    for data, nm in zip(datas, nulls):
        raw = data.tobytes()
        cols_meta.append({"dtype": data.dtype.str, "nbytes": len(raw),
                          "nulls": nm is not None})
        payload += raw
        if nm is not None:
            payload += np.packbits(nm).tobytes()
    body = bytes(payload)
    codec = "raw"
    if compress and len(body) > 512:
        z = zlib.compress(body, 1)
        if len(z) < len(body):
            body, codec = z, "zlib1"
    header = json.dumps({"rows": nrows, "cols": cols_meta,
                         "codec": codec}).encode()
    frame = MAGIC + struct.pack("<I", len(header)) + header + body
    from ..utils.metrics import METRICS
    METRICS.count("exchange.frames")
    METRICS.count("exchange.bytes", len(frame))
    METRICS.count("exchange.rows", nrows)
    return frame


def serialize_pages(pages: Sequence[Page], types: Sequence[Type],
                    compress: bool = True) -> bytes:
    datas, nulls, nrows = pages_to_columns(pages, types)
    return serialize_columns(datas, nulls, nrows, compress)


def deserialize_columns(frame: bytes) -> tuple:
    """-> (datas, nulls, nrows); inverse of serialize_columns."""
    assert frame[:5] == MAGIC, "bad page frame magic"
    (hlen,) = struct.unpack_from("<I", frame, 5)
    header = json.loads(frame[9:9 + hlen].decode())
    body = frame[9 + hlen:]
    if header["codec"] == "zlib1":
        body = zlib.decompress(body)
    nrows = header["rows"]
    datas, nulls = [], []
    off = 0
    for cm in header["cols"]:
        dt = np.dtype(cm["dtype"])
        datas.append(np.frombuffer(body, dtype=dt, count=cm["nbytes"] // dt.itemsize,
                                   offset=off).copy())
        off += cm["nbytes"]
        if cm["nulls"]:
            nbytes = (nrows + 7) // 8
            bits = np.frombuffer(body, dtype=np.uint8, count=nbytes, offset=off)
            nulls.append(np.unpackbits(bits)[:nrows].astype(bool))
            off += nbytes
        else:
            nulls.append(None)
    return datas, nulls, nrows


def _pad(arr: np.ndarray, length: int) -> np.ndarray:
    if len(arr) >= length:
        return arr
    return np.concatenate([arr, np.zeros(length - len(arr), dtype=arr.dtype)])


def columns_to_pages(datas: Sequence[np.ndarray],
                     nulls: Sequence[Optional[np.ndarray]], nrows: int,
                     types: Sequence[Type],
                     dicts: Sequence[Optional[Dictionary]],
                     page_capacity: int) -> List[Page]:
    """Re-page received columns at standard capacities so downstream operators
    reuse kernels compiled for scan pages (same policy as the mesh exchange,
    parallel/runner.py run_exchange)."""
    if nrows == 0:
        return []
    cap = min(page_capacity, 1 << (nrows - 1).bit_length())
    out: List[Page] = []
    for lo in range(0, nrows, cap):
        hi = min(lo + cap, nrows)
        blocks = []
        for c, t in enumerate(types):
            nm = nulls[c]
            nm_slice = _pad(nm[lo:hi], cap) if nm is not None else None
            if nm_slice is not None and not nm_slice.any():
                nm_slice = None
            blocks.append(Block(t, _pad(datas[c][lo:hi], cap), nm_slice,
                                dicts[c] if dicts else None))
        out.append(Page(tuple(blocks),
                        _pad(np.ones(hi - lo, dtype=bool), cap)))
    return out


def deserialize_pages(frame: bytes, types: Sequence[Type],
                      dicts: Sequence[Optional[Dictionary]],
                      page_capacity: int) -> List[Page]:
    datas, nulls, nrows = deserialize_columns(frame)
    return columns_to_pages(datas, nulls, nrows, types, dicts, page_capacity)
