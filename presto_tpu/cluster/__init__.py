"""Multi-process cluster tier: coordinator + worker servers over HTTP (DCN).

This package is the engine's analogue of the reference's distributed runtime
(layers 5/6/8/9 of SURVEY.md §1): discovery + heartbeat failure detection,
node/stage scheduling, remote tasks, worker task management, partitioned
output buffers with token-acked page pull, and the page wire format.

Division of labor with the SPMD tier (presto_tpu/parallel/): inside one host's
TPU slice, fragments execute as shard_map collectives over ICI; ACROSS hosts,
this package ships serialized page frames over HTTP — the reference's
HTTP+LZ4 data plane (operator/ExchangeClient.java) mapped onto the DCN tier,
where XLA collectives are not available."""
__all__ = ["ClusterQueryRunner", "WorkerServer", "Backoff", "FaultInjector",
           "WorkerPoolAutoscaler"]


def __getattr__(name):  # lazy: `python -m presto_tpu.cluster.worker` must not
    if name == "ClusterQueryRunner":          # re-import its own module
        from .coordinator import ClusterQueryRunner
        return ClusterQueryRunner
    if name == "WorkerServer":
        from .worker import WorkerServer
        return WorkerServer
    if name == "Backoff":
        from .retry import Backoff
        return Backoff
    if name == "FaultInjector":
        from .faults import FaultInjector
        return FaultInjector
    if name == "WorkerPoolAutoscaler":
        from .autoscaler import WorkerPoolAutoscaler
        return WorkerPoolAutoscaler
    raise AttributeError(name)
