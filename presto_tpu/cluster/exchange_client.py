"""Consumer half of the cross-host shuffle: HTTP page pull with token acks.

Analogue of operator/ExchangeClient.java:145 + HttpPageBufferClient.java:88,301
(/root/reference/presto-main): for each upstream task location, GET
{location}/results/{buffer_id}/{token} long-polls one frame at a time; the next
request's token acknowledges everything before it. Transient HTTP errors back
off and retry (server/remotetask/Backoff.java); a hard error or an upstream
task failure fails the consumer."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..block import Dictionary, Page
from ..spi.connector import ConnectorPageSource
from ..types import Type
from .serde import deserialize_pages

# transient-failure budget before a location is declared dead
_MAX_ERROR_S = 60.0


def http_json(method: str, url: str, body: Optional[bytes] = None,
              timeout_s: float = 30.0) -> dict:
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/octet-stream")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        data = resp.read()
    return json.loads(data) if data else {}


class PageBufferClient:
    """One upstream location's pull loop state."""

    def __init__(self, location: str, buffer_id: int):
        self.location = location.rstrip("/")
        self.buffer_id = buffer_id
        self.token = 0
        self.complete = False
        self._error_since: Optional[float] = None
        self._instance_id: Optional[str] = None

    def poll(self, timeout_s: float = 10.0) -> Optional[bytes]:
        """One GET; returns a frame or None (no data yet / now complete)."""
        url = (f"{self.location}/results/{self.buffer_id}/{self.token}"
               f"?wait={timeout_s:.1f}")
        req = urllib.request.Request(url, method="GET")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s + 15.0) as resp:
                nxt = int(resp.headers.get("X-Next-Token", self.token))
                complete = resp.headers.get("X-Complete") == "true"
                instance = resp.headers.get("X-Task-Instance-Id")
                frame = resp.read()
            if instance:
                if self._instance_id is None:
                    self._instance_id = instance
                elif self._instance_id != instance:
                    # the producer task was RECREATED: its tokens restarted at
                    # 0, so our token would silently skip/duplicate frames —
                    # fail the query loudly (reference: PRESTO_TASK_INSTANCE_ID
                    # mismatch aborts the page client)
                    raise RuntimeError(
                        f"exchange source {self.location} was recreated "
                        f"(instance {self._instance_id} -> {instance}); "
                        f"stream tokens are no longer valid")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # producer task not created yet (all-at-once scheduling may
                # reach the consumer first) — transient within the budget
                return self._transient(e)
            raise RuntimeError(
                f"exchange source {self.location} failed: {e} "
                f"{e.read()[:500].decode(errors='replace')}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            return self._transient(e)
        self._error_since = None
        self.token = nxt
        self.complete = complete
        return frame if frame else None

    def _transient(self, e: Exception) -> None:
        now = time.monotonic()
        if self._error_since is None:
            self._error_since = now
        if now - self._error_since > _MAX_ERROR_S:
            raise RuntimeError(
                f"exchange source {self.location} unreachable: {e}") from e
        time.sleep(0.2)
        return None

    def finished_ack(self) -> None:
        """Final ack freeing the server-side buffer (abort endpoint)."""
        try:
            url = f"{self.location}/results/{self.buffer_id}"
            req = urllib.request.Request(url, method="DELETE")
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:
            pass  # buffer cleanup is best-effort; task teardown also frees it


class StreamingRemoteSource(ConnectorPageSource):
    """Page source over N upstream task locations — the worker-side endpoint of
    a fragment's RemoteSourceNode (ExchangeOperator.java:35 analogue). Iterating
    round-robins the locations, yielding pages as frames arrive; exhausts when
    every location reports complete."""

    def __init__(self, locations: Sequence[str], buffer_id: int,
                 types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int,
                 cancelled: Optional[threading.Event] = None):
        self.clients = [PageBufferClient(loc, buffer_id) for loc in locations]
        self.types = list(types)
        self.dicts = list(dicts)
        self.page_capacity = page_capacity
        self.cancelled = cancelled

    def __iter__(self) -> Iterator[Page]:
        pending = list(self.clients)
        while pending:
            if self.cancelled is not None and self.cancelled.is_set():
                raise RuntimeError("task cancelled while reading exchange")
            progressed = False
            for c in list(pending):
                # short poll while multiple sources are live so one slow
                # producer cannot starve the others; the tail drains long-polled
                frame = c.poll(timeout_s=0.2 if len(pending) > 1 else 10.0)
                if frame:
                    progressed = True
                    for page in deserialize_pages(frame, self.types, self.dicts,
                                                  self.page_capacity):
                        yield page
                if c.complete:
                    c.finished_ack()
                    pending.remove(c)
            if not progressed and pending:
                time.sleep(0.01)

    def close(self) -> None:
        for c in self.clients:
            if not c.complete:
                c.finished_ack()


class MergingRemoteSource(ConnectorPageSource):
    """N-way merge over per-producer LOCALLY-SORTED streams — the HTTP-tier
    distributed sort (operator/MergeOperator.java + MergeSortedPages): each
    upstream task sorted its own rows (plan_subplan inserts the local
    SortNode under MERGE outputs), so the consumer only heap-merges K
    ordered streams instead of re-sorting the full row set.

    `orderings`: [(channel, descending, nulls_first)]; varchar channels
    compare by dictionary rank (Dictionary.sort_keys), exactly like the
    engine's sort operators."""

    def __init__(self, locations: Sequence[str], buffer_id: int,
                 types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int,
                 orderings: Sequence[tuple],
                 cancelled: Optional[threading.Event] = None):
        self.locations = list(locations)
        self.buffer_id = buffer_id
        self.types = list(types)
        self.dicts = list(dicts)
        self.page_capacity = page_capacity
        self.orderings = list(orderings)
        self.cancelled = cancelled
        self._inner: List[StreamingRemoteSource] = []

    def _row_iter(self, location: str):
        """-> (sort key, row values tuple, row nulls tuple) per live row."""
        from ..exec.grouped import _Cmp, _Neg, _Null

        _NULLV = _Null()
        ranks = {}
        for ch, _d, _nf in self.orderings:
            d = self.dicts[ch]
            if d is not None and hasattr(d, "sort_keys"):
                ranks[ch] = np.asarray(d.sort_keys())
        src = StreamingRemoteSource([location], self.buffer_id, self.types,
                                    self.dicts, self.page_capacity,
                                    cancelled=self.cancelled)
        self._inner.append(src)
        for page in src:
            mask = np.asarray(page.mask)
            datas = [np.asarray(b.data) for b in page.blocks]
            nulls = [None if b.nulls is None else np.asarray(b.nulls)
                     for b in page.blocks]
            for i in np.flatnonzero(mask):
                key = []
                for ch, desc, nf in self.orderings:
                    isnull = nulls[ch] is not None and nulls[ch][i]
                    if isnull:
                        key.append((0 if nf else 1, _NULLV))
                    else:
                        v = datas[ch][i]
                        if ch in ranks:
                            v = ranks[ch][int(v)]
                        key.append((1 if nf else 0,
                                    _Neg(v) if desc else _Cmp(v)))
                yield (tuple(key),
                       tuple(d[i] for d in datas),
                       tuple(False if n is None else bool(n[i])
                             for n in nulls))

    def __iter__(self) -> Iterator[Page]:
        import heapq

        from ..block import Block, Page as _Page

        merged = heapq.merge(*(self._row_iter(loc) for loc in self.locations),
                             key=lambda t: t[0])
        ncols = len(self.types)
        buf_vals: List[list] = [[] for _ in range(ncols)]
        buf_nulls: List[list] = [[] for _ in range(ncols)]
        n = 0

        def flush():
            blocks = []
            for c in range(ncols):
                data = np.asarray(buf_vals[c],
                                  dtype=self.types[c].np_dtype)
                nm = np.asarray(buf_nulls[c], dtype=bool)
                blocks.append(Block(self.types[c], data,
                                    nm if nm.any() else None,
                                    self.dicts[c]))
            return _Page(tuple(blocks), np.ones(n, dtype=bool))

        for _key, vals, nls in merged:
            for c in range(ncols):
                buf_vals[c].append(vals[c])
                buf_nulls[c].append(nls[c])
            n += 1
            if n >= self.page_capacity:
                yield flush()
                buf_vals = [[] for _ in range(ncols)]
                buf_nulls = [[] for _ in range(ncols)]
                n = 0
        if n:
            yield flush()

    def close(self) -> None:
        # release producer-side buffers promptly on cancellation: an
        # unclosed stream would leave producers parked in OutputBuffer
        # backpressure until its timeout
        for src in self._inner:
            src.close()
