"""Consumer half of the cross-host shuffle: HTTP page pull with token acks.

Analogue of operator/ExchangeClient.java:145 + HttpPageBufferClient.java:88,301
(/root/reference/presto-main): for each upstream task location, GET
{location}/results/{buffer_id}/{token} long-polls one frame at a time; the next
request's token acknowledges everything before it. Transient HTTP errors back
off and retry (server/remotetask/Backoff.java); a hard error or an upstream
task failure fails the consumer."""
from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Sequence

from ..block import Dictionary, Page
from ..spi.connector import ConnectorPageSource
from ..types import Type
from .serde import deserialize_pages

# transient-failure budget before a location is declared dead
_MAX_ERROR_S = 60.0


def http_json(method: str, url: str, body: Optional[bytes] = None,
              timeout_s: float = 30.0) -> dict:
    req = urllib.request.Request(url, data=body, method=method)
    if body is not None:
        req.add_header("Content-Type", "application/octet-stream")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        data = resp.read()
    return json.loads(data) if data else {}


class PageBufferClient:
    """One upstream location's pull loop state."""

    def __init__(self, location: str, buffer_id: int):
        self.location = location.rstrip("/")
        self.buffer_id = buffer_id
        self.token = 0
        self.complete = False
        self._error_since: Optional[float] = None
        self._instance_id: Optional[str] = None

    def poll(self, timeout_s: float = 10.0) -> Optional[bytes]:
        """One GET; returns a frame or None (no data yet / now complete)."""
        url = (f"{self.location}/results/{self.buffer_id}/{self.token}"
               f"?wait={timeout_s:.1f}")
        req = urllib.request.Request(url, method="GET")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s + 15.0) as resp:
                nxt = int(resp.headers.get("X-Next-Token", self.token))
                complete = resp.headers.get("X-Complete") == "true"
                instance = resp.headers.get("X-Task-Instance-Id")
                frame = resp.read()
            if instance:
                if self._instance_id is None:
                    self._instance_id = instance
                elif self._instance_id != instance:
                    # the producer task was RECREATED: its tokens restarted at
                    # 0, so our token would silently skip/duplicate frames —
                    # fail the query loudly (reference: PRESTO_TASK_INSTANCE_ID
                    # mismatch aborts the page client)
                    raise RuntimeError(
                        f"exchange source {self.location} was recreated "
                        f"(instance {self._instance_id} -> {instance}); "
                        f"stream tokens are no longer valid")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # producer task not created yet (all-at-once scheduling may
                # reach the consumer first) — transient within the budget
                return self._transient(e)
            raise RuntimeError(
                f"exchange source {self.location} failed: {e} "
                f"{e.read()[:500].decode(errors='replace')}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            return self._transient(e)
        self._error_since = None
        self.token = nxt
        self.complete = complete
        return frame if frame else None

    def _transient(self, e: Exception) -> None:
        now = time.monotonic()
        if self._error_since is None:
            self._error_since = now
        if now - self._error_since > _MAX_ERROR_S:
            raise RuntimeError(
                f"exchange source {self.location} unreachable: {e}") from e
        time.sleep(0.2)
        return None

    def finished_ack(self) -> None:
        """Final ack freeing the server-side buffer (abort endpoint)."""
        try:
            url = f"{self.location}/results/{self.buffer_id}"
            req = urllib.request.Request(url, method="DELETE")
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:
            pass  # buffer cleanup is best-effort; task teardown also frees it


class StreamingRemoteSource(ConnectorPageSource):
    """Page source over N upstream task locations — the worker-side endpoint of
    a fragment's RemoteSourceNode (ExchangeOperator.java:35 analogue). Iterating
    round-robins the locations, yielding pages as frames arrive; exhausts when
    every location reports complete."""

    def __init__(self, locations: Sequence[str], buffer_id: int,
                 types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int,
                 cancelled: Optional[threading.Event] = None):
        self.clients = [PageBufferClient(loc, buffer_id) for loc in locations]
        self.types = list(types)
        self.dicts = list(dicts)
        self.page_capacity = page_capacity
        self.cancelled = cancelled

    def __iter__(self) -> Iterator[Page]:
        pending = list(self.clients)
        while pending:
            if self.cancelled is not None and self.cancelled.is_set():
                raise RuntimeError("task cancelled while reading exchange")
            progressed = False
            for c in list(pending):
                # short poll while multiple sources are live so one slow
                # producer cannot starve the others; the tail drains long-polled
                frame = c.poll(timeout_s=0.2 if len(pending) > 1 else 10.0)
                if frame:
                    progressed = True
                    for page in deserialize_pages(frame, self.types, self.dicts,
                                                  self.page_capacity):
                        yield page
                if c.complete:
                    c.finished_ack()
                    pending.remove(c)
            if not progressed and pending:
                time.sleep(0.01)

    def close(self) -> None:
        for c in self.clients:
            if not c.complete:
                c.finished_ack()
