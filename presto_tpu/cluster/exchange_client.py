"""Consumer half of the cross-host shuffle: HTTP page pull with token acks.

Analogue of operator/ExchangeClient.java:145 + HttpPageBufferClient.java:88,301
(/root/reference/presto-main): for each upstream task location, GET
{location}/results/{buffer_id}/{token} long-polls one frame at a time; the next
request's token acknowledges everything before it. Transient HTTP errors back
off and retry under the shared cluster/retry.Backoff budget
(server/remotetask/Backoff.java); a hard error or an upstream task failure
fails the consumer.

Fault tolerance: every client tracks a per-consumer CHUNK CURSOR (`token`,
the next sequence number it needs). When the scheduler recovers a failed
producer (POST /v1/task/{id}/sources -> SqlTask.update_sources ->
reset_location here) the client keeps its cursor and re-issues GET from it
against the replacement — the replacement re-produces the same deterministic
frame sequence (single sink driver; a nondeterministic sink marks its buffer
non-replayable server-side), its spool absorbs the prefix the consumer
already has, and a monotonic sequence check asserts exactly-once delivery.
A replayed token that was already retired from the producer's bounded spool
answers HTTP 410 (`replay window lost`) — a hard error that escalates
loudly to a query-level retry instead of silently skipping data."""
from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..block import Dictionary, Page
from ..spi.connector import ConnectorPageSource
from ..types import Type
from ..utils import trace
from . import faults
from .retry import Backoff
from .serde import deserialize_pages

# default transient-failure budget before a location is declared dead
# (the exchange_error_budget_s session default in metadata.py matches; use
# this constant as the fallback wherever that property might be None)
_MAX_ERROR_S = 60.0


class PageBufferClient:
    """One upstream location's pull loop state."""

    def __init__(self, location: str, buffer_id: int,
                 error_budget_s: float = _MAX_ERROR_S):
        self.location = location.rstrip("/")
        self.buffer_id = buffer_id
        self.token = 0
        self.complete = False
        self.done = False  # complete AND final ack sent
        self._backoff = Backoff(max_failure_interval_s=error_budget_s,
                                initial_delay_s=0.05, max_delay_s=1.0)
        self._instance_id: Optional[str] = None
        # guards token/complete/location/epoch against the rewire path: a
        # reset bumps the epoch, and a poll that was in flight against the
        # OLD location commits nothing (its frame is dropped) — without
        # this, a rewire accepted mid-poll could double-consume frame 0
        self._lock = threading.Lock()
        self._epoch = 0

    def poll(self, timeout_s: float = 10.0) -> Optional[bytes]:
        """One GET; returns a frame or None (no data yet / now complete)."""
        with self._lock:
            epoch = self._epoch
            location = self.location
            url = (f"{location}/results/{self.buffer_id}/{self.token}"
                   f"?wait={timeout_s:.1f}")
        req = urllib.request.Request(url, method="GET")
        t0 = time.perf_counter_ns()
        try:
            # token rides along so chaos callbacks can key on consumption
            # state (e.g. "fail this consumer once it has committed 2 chunks")
            faults.fire("client.results", location=location, token=self.token)
            with urllib.request.urlopen(req, timeout=timeout_s + 15.0) as resp:
                nxt = int(resp.headers.get("X-Next-Token", self.token))
                complete = resp.headers.get("X-Complete") == "true"
                instance = resp.headers.get("X-Task-Instance-Id")
                frame = resp.read()
            if trace.active() is not None:
                trace.record(trace.HTTP, "pull results", t0,
                             time.perf_counter_ns() - t0,
                             {"location": location,
                              "bytes": len(frame) if frame else 0})
        except urllib.error.HTTPError as e:
            if e.code == 410:
                # the producer retired this chunk from its replay spool
                # (overflow or nondeterministic sink): waiting cannot help
                # and skipping would lose rows — hard-fail; the message
                # marker classifies it QUERY-retryable upstream
                detail = e.read()[:300].decode(errors="replace")
                raise RuntimeError(
                    f"exchange source {location} cannot replay: "
                    f"{detail or 'replay window lost'}") from e
            if e.code == 404 or e.code >= 500:
                # 404: producer task not created yet (all-at-once scheduling
                # may reach the consumer first); 5xx: a server-side blip or
                # a failed buffer mid-recovery — both transient within the
                # budget (HttpPageBufferClient treats any non-OK response as
                # a retryable failure). Keep the body: if the budget
                # exhausts, the LAST server diagnostic must survive into
                # the error instead of a bare 'unreachable'
                detail = e.read()[:300].decode(errors="replace")
                return self._transient(RuntimeError(
                    f"HTTP {e.code}: {detail}" if detail else str(e)))
            raise RuntimeError(
                f"exchange source {location} failed: {e} "
                f"{e.read()[:500].decode(errors='replace')}") from e
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            return self._transient(e)
        with self._lock:
            if self._epoch != epoch:
                return None  # rewired mid-flight: drop the stale frame
            if instance:
                if self._instance_id is None:
                    self._instance_id = instance
                elif self._instance_id != instance:
                    # the producer task was RECREATED behind our back: its
                    # tokens restarted at 0, so our token would silently
                    # skip/duplicate frames — fail the query loudly
                    # (reference: PRESTO_TASK_INSTANCE_ID mismatch aborts
                    # the page client). A scheduler-driven rewire instead
                    # goes through reset_location, which bumps the epoch
                    # and clears the pinned instance id first.
                    raise RuntimeError(
                        f"exchange source {location} was recreated "
                        f"(instance {self._instance_id} -> {instance}); "
                        f"stream tokens are no longer valid")
            self._backoff.success()
            if frame and nxt != self.token + 1:
                # exactly-once guard: a served frame must advance the cursor
                # by exactly one sequence number — anything else means the
                # producer skipped or re-delivered a chunk
                raise RuntimeError(
                    f"exchange source {location} sequence violation: "
                    f"cursor {self.token} answered with next token {nxt}")
            self.token = nxt
            self.complete = complete
        return frame if frame else None

    def _transient(self, e: Exception) -> None:
        if self._backoff.failure():
            raise RuntimeError(
                f"exchange source {self.location} unreachable after "
                f"{self._backoff.failure_count} tries over "
                f"{self._backoff.time_since_first_failure_s():.1f}s: {e}"
            ) from e
        self._backoff.wait()
        return None

    def can_reset(self) -> bool:
        # the chunk cursor makes a mid-stream rewire sound: the client
        # re-issues GET from `token` and the replacement's spool replays or
        # absorbs the already-consumed prefix (410 if it cannot)
        return True

    def reset_location(self, new_location: str) -> bool:
        """Point this client at a replacement producer, KEEPING the chunk
        cursor: the next poll re-issues GET from `token` and sequence
        numbers assert exactly-once delivery across the rewire. Bumps the
        epoch so an in-flight poll against the old location cannot commit;
        clears the pinned instance id (the replacement is a new instance by
        design). A finished (`done`) client just releases the replacement's
        buffer so the new task never wedges on backpressure."""
        was_done = False
        with self._lock:
            self.location = new_location.rstrip("/")
            self._instance_id = None
            self._epoch += 1
            self._backoff.success()
            was_done = self.done
        if was_done:
            self.finished_ack()
        return True

    def finished_ack(self) -> None:
        """Final ack freeing the server-side buffer (abort endpoint)."""
        try:
            url = f"{self.location}/results/{self.buffer_id}"
            req = urllib.request.Request(url, method="DELETE")
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:
            pass  # buffer cleanup is best-effort; task teardown also frees it
        self.done = True


class StreamingRemoteSource(ConnectorPageSource):
    """Page source over N upstream task locations — the worker-side endpoint of
    a fragment's RemoteSourceNode (ExchangeOperator.java:35 analogue). Iterating
    round-robins the locations, yielding pages as frames arrive; exhausts when
    every location reports complete."""

    # reads long-poll upstream tasks: must never step on the shared pool
    external_wait = True

    def __init__(self, locations: Sequence[str], buffer_id: int,
                 types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int,
                 cancelled: Optional[threading.Event] = None,
                 error_budget_s: float = _MAX_ERROR_S):
        self._lock = threading.Lock()
        self.clients = [PageBufferClient(loc, buffer_id,
                                         error_budget_s=error_budget_s)
                        for loc in locations]
        self.types = list(types)
        self.dicts = list(dicts)
        self.page_capacity = page_capacity
        self.cancelled = cancelled

    def can_reset_location(self, old_location: str) -> bool:
        """Would a rewire of `old_location` be sound right now? (the check
        half of SqlTask.update_sources' check-then-apply)"""
        old = old_location.rstrip("/")
        with self._lock:
            for client in self.clients:
                if client.location == old:
                    return client.can_reset()
        return False

    def reset_location(self, old_location: str, new_location: str) -> bool:
        """Rewire the client pulling `old_location` to a replacement
        producer, cursor preserved (mid-stream rewires are sound under the
        chunk protocol); False only when no client matches that location."""
        old = old_location.rstrip("/")
        with self._lock:
            for client in self.clients:
                if client.location == old:
                    return client.reset_location(new_location)
        return False

    def __iter__(self) -> Iterator[Page]:
        # bounded idle wait replacing the old 10ms busy-spin: a stalled
        # producer backs the consumer off exponentially (capped), any
        # progress heals the streak
        idle = Backoff(max_failure_interval_s=float("inf"),
                       initial_delay_s=0.005, max_delay_s=0.1, min_tries=1)
        while True:
            with self._lock:
                live = [c for c in self.clients if not c.done]
            if not live:
                return
            if self.cancelled is not None and self.cancelled.is_set():
                raise RuntimeError("task cancelled while reading exchange")
            progressed = False
            for c in live:
                # short poll while multiple sources are live so one slow
                # producer cannot starve the others; the tail drains long-polled
                frame = c.poll(timeout_s=0.2 if len(live) > 1 else 10.0)
                if frame:
                    progressed = True
                    for page in deserialize_pages(frame, self.types, self.dicts,
                                                  self.page_capacity):
                        yield page
                if c.complete:
                    c.finished_ack()
            if progressed:
                idle.success()
            else:
                idle.failure()
                idle.wait()

    def close(self) -> None:
        # a CANCELLED consumer must NOT send final acks: the DELETE would
        # release the producer-side buffer (and its replay spool) that this
        # task's replacement — same buffer id, fresh cursor — still needs.
        # On the abort path the producers are torn down too, which frees
        # their buffers without any ack.
        if self.cancelled is not None and self.cancelled.is_set():
            return
        with self._lock:
            clients = list(self.clients)
        for c in clients:
            if not c.complete:
                c.finished_ack()


class MergingRemoteSource(ConnectorPageSource):
    """N-way merge over per-producer LOCALLY-SORTED streams — the HTTP-tier
    distributed sort (operator/MergeOperator.java + MergeSortedPages): each
    upstream task sorted its own rows (plan_subplan inserts the local
    SortNode under MERGE outputs), so the consumer only heap-merges K
    ordered streams instead of re-sorting the full row set.

    `orderings`: [(channel, descending, nulls_first)]; varchar channels
    compare by dictionary rank (Dictionary.sort_keys), exactly like the
    engine's sort operators."""

    # reads long-poll upstream tasks: must never step on the shared pool
    external_wait = True

    def __init__(self, locations: Sequence[str], buffer_id: int,
                 types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int,
                 orderings: Sequence[tuple],
                 cancelled: Optional[threading.Event] = None,
                 error_budget_s: float = _MAX_ERROR_S):
        self.locations = list(locations)
        self.buffer_id = buffer_id
        self.types = list(types)
        self.dicts = list(dicts)
        self.page_capacity = page_capacity
        self.orderings = list(orderings)
        self.cancelled = cancelled
        self.error_budget_s = error_budget_s
        self._lock = threading.Lock()
        self._started = False
        self._inner: List[StreamingRemoteSource] = []

    def can_reset_location(self, old_location: str) -> bool:
        old = old_location.rstrip("/")
        with self._lock:
            if self._started:
                inner = list(self._inner)
            else:
                return any(loc.rstrip("/") == old for loc in self.locations)
        return any(src.can_reset_location(old) for src in inner)

    def reset_location(self, old_location: str, new_location: str) -> bool:
        """Rewire one producer stream to a replacement. Before the merge
        starts this just swaps the location; after, it delegates to the
        per-stream inner source, whose chunk cursor makes the mid-stream
        rewire sound — the heap has consumed exactly the frames below that
        cursor, and already-deserialized rows stay buffered in the merge."""
        old = old_location.rstrip("/")
        with self._lock:
            for i, loc in enumerate(self.locations):
                if loc.rstrip("/") == old:
                    self.locations[i] = new_location
            if self._started:
                inner = list(self._inner)
            else:
                return any(loc.rstrip("/") == new_location.rstrip("/")
                           for loc in self.locations)
        return any(src.reset_location(old, new_location) for src in inner)

    def _row_iter(self, src: "StreamingRemoteSource"):
        """-> (sort key, row values tuple, row nulls tuple) per live row."""
        from ..exec.grouped import _Cmp, _Neg, _Null

        _NULLV = _Null()
        ranks = {}
        for ch, _d, _nf in self.orderings:
            d = self.dicts[ch]
            if d is not None and hasattr(d, "sort_keys"):
                ranks[ch] = np.asarray(d.sort_keys())
        for page in src:
            mask = np.asarray(page.mask)
            datas = [np.asarray(b.data) for b in page.blocks]
            nulls = [None if b.nulls is None else np.asarray(b.nulls)
                     for b in page.blocks]
            for i in np.flatnonzero(mask):
                key = []
                for ch, desc, nf in self.orderings:
                    isnull = nulls[ch] is not None and nulls[ch][i]
                    if isnull:
                        key.append((0 if nf else 1, _NULLV))
                    else:
                        v = datas[ch][i]
                        if ch in ranks:
                            v = ranks[ch][int(v)]
                        key.append((1 if nf else 0,
                                    _Neg(v) if desc else _Cmp(v)))
                yield (tuple(key),
                       tuple(d[i] for d in datas),
                       tuple(False if n is None else bool(n[i])
                             for n in nulls))

    def __iter__(self) -> Iterator[Page]:
        import heapq

        from ..block import Block, Page as _Page

        with self._lock:
            # materialize one inner source per producer BEFORE marking
            # started: a rewire arriving from here on always finds a live
            # per-stream cursor to delegate to (no lazy-creation race)
            for loc in self.locations:
                self._inner.append(StreamingRemoteSource(
                    [loc], self.buffer_id, self.types, self.dicts,
                    self.page_capacity, cancelled=self.cancelled,
                    error_budget_s=self.error_budget_s))
            self._started = True
            inner = list(self._inner)
        merged = heapq.merge(*(self._row_iter(src) for src in inner),
                             key=lambda t: t[0])
        ncols = len(self.types)
        buf_vals: List[list] = [[] for _ in range(ncols)]
        buf_nulls: List[list] = [[] for _ in range(ncols)]
        n = 0

        def flush():
            blocks = []
            for c in range(ncols):
                data = np.asarray(buf_vals[c],
                                  dtype=self.types[c].np_dtype)
                nm = np.asarray(buf_nulls[c], dtype=bool)
                blocks.append(Block(self.types[c], data,
                                    nm if nm.any() else None,
                                    self.dicts[c]))
            return _Page(tuple(blocks), np.ones(n, dtype=bool))

        for _key, vals, nls in merged:
            for c in range(ncols):
                buf_vals[c].append(vals[c])
                buf_nulls[c].append(nls[c])
            n += 1
            if n >= self.page_capacity:
                yield flush()
                buf_vals = [[] for _ in range(ncols)]
                buf_nulls = [[] for _ in range(ncols)]
                n = 0
        if n:
            yield flush()

    def close(self) -> None:
        # release producer-side buffers promptly on cancellation: an
        # unclosed stream would leave producers parked in OutputBuffer
        # backpressure until its timeout. Best-effort per stream — one
        # unreachable worker must not strand the remaining producers
        for src in self._inner:
            try:
                src.close()
            except Exception:
                pass  # close of the remaining streams is best-effort
