"""Structured JSON codec for the cluster control plane.

Replaces pickle on every coordinator<->worker HTTP body. The reference
deliberately uses JSON/SMILE codecs on this boundary
(server/InternalCommunicationConfig.java:92-98, jackson codecs for
TaskUpdateRequest/TaskInfo/PlanFragment); pickle here was
remote-code-execution-by-design for anything that can reach a worker port.

Design: every wire object is a frozen/plain dataclass (plan nodes,
expressions, types, handles, session, task DTOs). One reflective codec walks
dataclass fields; decoding instantiates ONLY classes in the explicit
ALLOWED registry — an unknown tag is an error, never an import or a call.

Wire forms:
  dataclass      -> {"$c": "ClassName", "f": {field: value, ...}}
  tuple          -> {"$t": [items]}           (tuple/list distinction matters:
                                               plan dataclasses hash tuples)
  dict           -> {"$d": [[k, v], ...]}     (keys may be ints)
  Decimal        -> {"$dec": "1.23"}
  datetime.date  -> {"$date": "1995-06-17"}
  bytes          -> {"$b": base64}
  numpy scalar   -> plain int/float
  int/float/str/bool/None/list pass through natively.
"""
from __future__ import annotations

import base64
import dataclasses
import datetime
import decimal
import json
from typing import Any, Dict, List, Type as PyType

import numpy as np


def _allowed_classes() -> Dict[str, type]:
    from .. import types as t
    from ..metadata import Session
    from ..ops import expressions as e
    from ..spi.connector import (ColumnHandle, SchemaTableName, TableHandle)
    from ..sql.planner import plan as p
    from ..sql.planner.fragmenter import Fragment, SubPlan

    classes: List[type] = [
        # task DTOs (registered lazily to dodge the circular import with task.py)
        # types
        t.BigintType, t.IntegerType, t.SmallintType, t.DoubleType, t.RealType,
        t.BooleanType, t.DateType, t.TimestampType, t.DecimalType,
        t.VarcharType, t.CharType, t.UnknownType,
        # expressions
        e.InputRef, e.Constant, e.SymbolRef, e.Call, e.SpecialForm,
        # handles / session
        ColumnHandle, SchemaTableName, TableHandle, Session,
        # plan
        p.Symbol, p.AggregationCall, p.Ordering, p.WindowCall,
        p.TableScanNode, p.FilterNode, p.ProjectNode, p.AggregationNode,
        p.JoinNode, p.SemiJoinNode, p.SortNode, p.WindowNode, p.TopNNode,
        p.LimitNode, p.ValuesNode, p.ExchangeNode, p.RemoteSourceNode,
        p.OutputNode, p.EnforceSingleRowNode, p.UnionNode,
        Fragment, SubPlan,
    ]
    extra = [c for c in (getattr(p, n, None)
                         for n in ("DistinctLimitNode", "MarkDistinctNode",
                                   "AssignUniqueIdNode", "GroupIdNode",
                                   "UnnestNode", "SampleNode",
                                   "TableWriterNode", "TableFinishNode",
                                   "DeleteNode", "ExplainAnalyzeNode",
                                   "RowNumberNode", "TopNRowNumberNode"))
             if c is not None]
    return {c.__name__: c for c in classes + extra}


_REGISTRY: Dict[str, type] = {}
_BOOTSTRAPPED = False


def register(cls: type) -> type:
    """Add a dataclass to the wire allow-list (used by task.py's DTOs)."""
    _REGISTRY[cls.__name__] = cls  # prestocheck: ignore[unbounded-cache] - one entry per DTO class, fixed at import
    return cls


def _registry() -> Dict[str, type]:
    global _BOOTSTRAPPED
    if not _BOOTSTRAPPED:
        _REGISTRY.update(_allowed_classes())
        _BOOTSTRAPPED = True
    return _REGISTRY


def encode(obj: Any) -> Any:
    """Python object -> JSON-compatible structure."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, np.bool_):
        return bool(obj)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, decimal.Decimal):
        return {"$dec": str(obj)}
    if isinstance(obj, datetime.date):
        return {"$date": obj.isoformat()}
    if isinstance(obj, bytes):
        return {"$b": base64.b64encode(obj).decode("ascii")}
    if isinstance(obj, tuple):
        return {"$t": [encode(v) for v in obj]}
    if isinstance(obj, list):
        return [encode(v) for v in obj]
    if isinstance(obj, dict):
        return {"$d": [[encode(k), encode(v)] for k, v in obj.items()]}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = type(obj).__name__
        if name not in _registry():
            raise TypeError(f"{name} is not wire-registered")
        fields = {f.name: encode(getattr(obj, f.name))
                  for f in dataclasses.fields(obj)}
        return {"$c": name, "f": fields}
    raise TypeError(f"cannot encode {type(obj).__name__} on the control plane")


def decode(obj: Any) -> Any:
    """JSON structure -> Python object (allow-listed classes only)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return [decode(v) for v in obj]
    if isinstance(obj, dict):
        if "$c" in obj:
            cls = _registry().get(obj["$c"])
            if cls is None:
                raise ValueError(f"unknown wire class {obj['$c']!r}")
            fields = {k: decode(v) for k, v in obj.get("f", {}).items()}
            return cls(**fields)
        if "$t" in obj:
            return tuple(decode(v) for v in obj["$t"])
        if "$d" in obj:
            return {decode(k): decode(v) for k, v in obj["$d"]}
        if "$dec" in obj:
            return decimal.Decimal(obj["$dec"])
        if "$date" in obj:
            return datetime.date.fromisoformat(obj["$date"])
        if "$b" in obj:
            return base64.b64decode(obj["$b"])
        raise ValueError(f"unrecognized wire object keys: {list(obj)[:4]}")
    raise ValueError(f"cannot decode {type(obj).__name__}")


def dumps(obj: Any) -> bytes:
    return json.dumps(encode(obj), separators=(",", ":")).encode("utf-8")


def loads(data: bytes) -> Any:
    return decode(json.loads(data.decode("utf-8")))
