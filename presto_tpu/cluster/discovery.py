"""Discovery + failure detection: who is in the cluster, and who is healthy.

Analogues (/root/reference/presto-main):
  - metadata/DiscoveryNodeManager.java:70,116 — the coordinator's view of live
    nodes, refreshed from announcements
  - failureDetector/HeartbeatFailureDetector.java:77,326-360 — coordinator
    pings every node's /v1/status; an exponentially-decayed failure ratio
    above the threshold (:92) gates the node out of scheduling
  - the worker side of airlift discovery — periodic service announcements

Workers POST /v1/announcement to the coordinator every second; the coordinator
expires nodes it has not heard from and, independently, probes them."""
from __future__ import annotations

import dataclasses
import threading
import time
import urllib.request
from typing import Dict, List, Optional

_ANNOUNCE_PERIOD_S = 1.0
_EXPIRE_S = 10.0

# HeartbeatFailureDetector defaults (scaled down: seconds, not 30s heartbeats)
_PING_PERIOD_S = 1.0
_DECAY_ALPHA = 0.2           # exponential-decay weight per observation
_FAILURE_RATIO_THRESHOLD = 0.9


@dataclasses.dataclass
class NodeInfo:
    node_id: str
    uri: str
    last_announce: float
    failure_ratio: float = 0.0
    # planned drain (cluster lifecycle): the node is ALIVE — consumers keep
    # pulling its spooled streams — but must receive no new placements.
    # Distinct from failure_ratio gating: a draining node is healthy.
    draining: bool = False


class DiscoveryNodeManager:
    """Coordinator-side registry of announced worker nodes."""

    def __init__(self):
        self._nodes: Dict[str, NodeInfo] = {}
        self._lock = threading.Lock()

    def announce(self, node_id: str, uri: str) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                self._nodes[node_id] = NodeInfo(node_id, uri, time.monotonic())
            else:
                # a re-announce refreshes liveness but never clears a drain:
                # only remove() (DRAINED teardown) resets it, so a rejoining
                # upgraded worker comes back schedulable under a fresh entry
                node.uri = uri
                node.last_announce = time.monotonic()

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)

    def get(self, node_id: str) -> Optional[NodeInfo]:
        with self._lock:
            return self._nodes.get(node_id)

    def set_draining(self, node_id: str, draining: bool = True) -> bool:
        """Mark a node as draining (unschedulable but alive). False = the
        node is unknown."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return False
            node.draining = bool(draining)
            return True

    def all_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return list(self._nodes.values())

    def active_nodes(self) -> List[NodeInfo]:
        """Announced recently AND not gated by the failure detector.
        DRAINING nodes are included: they are alive and still serve their
        spooled exchange streams — treating them as dead would misread a
        planned drain as a node death mid-query. Placement must use
        schedulable_nodes()."""
        now = time.monotonic()
        with self._lock:
            return [n for n in self._nodes.values()
                    if now - n.last_announce < _EXPIRE_S
                    and n.failure_ratio < _FAILURE_RATIO_THRESHOLD]

    def schedulable_nodes(self) -> List[NodeInfo]:
        """Active AND not draining: the placement view of the cluster."""
        return [n for n in self.active_nodes() if not n.draining]


class HeartbeatFailureDetector:
    """Pings every announced node's /v1/status; maintains the decayed failure
    ratio on its NodeInfo (HeartbeatFailureDetector.java:326-360)."""

    def __init__(self, nodes: DiscoveryNodeManager,
                 period_s: float = _PING_PERIOD_S):
        self.nodes = nodes
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="failure-detector", daemon=True)

    def start(self) -> "HeartbeatFailureDetector":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            for node in self.nodes.all_nodes():
                failed = 0.0
                try:
                    req = urllib.request.Request(f"{node.uri}/v1/status",
                                                 method="HEAD")
                    urllib.request.urlopen(req, timeout=2.0).read()
                except Exception:
                    failed = 1.0
                # exponential decay toward the latest observation
                node.failure_ratio = (
                    (1 - _DECAY_ALPHA) * node.failure_ratio
                    + _DECAY_ALPHA * failed)


class Announcer:
    """Worker-side: periodically announce this node to the coordinator.

    Failure tracking rides the shared cluster/retry.Backoff (the announce
    CADENCE stays period-driven — the announce loop never sleeps extra, a
    worker must reappear the moment the coordinator does)."""

    def __init__(self, coordinator_uri: str, node_id: str, uri: str):
        from .retry import Backoff

        self.coordinator_uri = coordinator_uri.rstrip("/")
        self.node_id = node_id
        self.uri = uri
        # infinite budget: announcing retries forever, the Backoff only
        # counts the failure streak for the persistent-failure warnings
        self._backoff = Backoff(max_failure_interval_s=float("inf"),
                                min_tries=1)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"announcer-{node_id}",
                                        daemon=True)

    @property
    def _announce_failures(self) -> int:
        """Current failure streak — single source: the shared Backoff."""
        return self._backoff.failure_count

    def start(self) -> "Announcer":
        self._announce_once()   # synchronous first announce: the node is
        self._thread.start()    # schedulable as soon as start() returns
        return self

    def stop(self) -> None:
        self._stop.set()

    def _announce_once(self) -> None:
        import json
        body = json.dumps({"nodeId": self.node_id, "uri": self.uri}).encode()
        req = urllib.request.Request(
            f"{self.coordinator_uri}/v1/announcement", data=body,
            method="POST", headers={"Content-Type": "application/json"})
        try:
            from . import faults
            faults.fire("client.announce", node_id=self.node_id)
            urllib.request.urlopen(req, timeout=5.0).read()
            self._backoff.success()
        except Exception as e:
            # coordinator may not be up yet (retried next period) — but a
            # PERSISTENT failure must be loud: a 401 here means the
            # coordinator requires authentication the worker cannot supply
            # and the node would silently never join the cluster
            self._backoff.failure()
            n = self._backoff.failure_count
            if n in (3, 20) or n % 100 == 0:
                import sys
                print(f"presto_tpu worker {self.node_id}: announcement to "
                      f"{self.coordinator_uri} failing ({n}x): {e!r}",
                      file=sys.stderr, flush=True)

    def deregister(self) -> bool:
        """Explicitly remove this node from the coordinator's registry
        (DELETE /v1/announcement/{nodeId}) — the DRAINED handoff. Without
        this, a stopped announcer leaves the node ACTIVE in discovery until
        heartbeat decay gates it out, a full detector window in which the
        scheduler keeps placing tasks at a gone worker. Best-effort: the
        coordinator may already be down, and expiry still cleans up."""
        req = urllib.request.Request(
            f"{self.coordinator_uri}/v1/announcement/{self.node_id}",
            method="DELETE")
        try:
            urllib.request.urlopen(req, timeout=5.0).read()
            return True
        except Exception:  # noqa: BLE001 - expiry is the fallback path
            return False

    def _loop(self) -> None:
        while not self._stop.wait(_ANNOUNCE_PERIOD_S):
            self._announce_once()
