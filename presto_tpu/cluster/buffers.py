"""Task output buffers: the producer half of the cross-host shuffle.

Analogue of execution/buffer/ (PartitionedOutputBuffer / BroadcastOutputBuffer
/ ClientBuffer, /root/reference/presto-main): each task owns one OutputBuffer
with a ClientBuffer per consumer; consumers pull serialized page frames with a
monotonically increasing token — requesting token T acknowledges (frees) every
frame below T, re-requesting T is idempotent (ClientBuffer's token protocol,
server/TaskResource.java:245-318).

Backpressure: the buffer bounds retained bytes; enqueue blocks the producing
driver thread until a consumer drains (the reference blocks the task's output
future the same way)."""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

PARTITIONED = "PARTITIONED"
BROADCAST = "BROADCAST"
GATHER = "GATHER"          # single consumer buffer (TaskOutputOperator case)


class ClientBuffer:
    """One consumer's frame queue with token acks."""

    def __init__(self, lock: threading.Condition):
        self._cv = lock
        self._frames: List[Tuple[int, bytes]] = []  # (token, frame)
        self._next_token = 0
        self._no_more = False
        self._aborted = False

    # producer side (caller holds the cv lock via OutputBuffer)
    def enqueue_locked(self, frame: bytes) -> int:
        if self._aborted:
            return 0  # consumer is gone: drop, never accumulate unacked bytes
        token = self._next_token
        self._frames.append((token, frame))
        self._next_token += 1
        return len(frame)

    def set_no_more_locked(self) -> None:
        self._no_more = True

    def abort_locked(self) -> int:
        freed = sum(len(f) for _, f in self._frames)
        self._frames.clear()
        self._aborted = True
        self._no_more = True
        return freed

    # consumer side
    def ack_locked(self, token: int) -> int:
        """Drop frames below `token`; returns bytes freed."""
        freed = 0
        while self._frames and self._frames[0][0] < token:
            freed += len(self._frames[0][1])
            self._frames.pop(0)
        return freed

    def get_locked(self, token: int) -> Tuple[Optional[bytes], int, bool]:
        """-> (frame|None, next_token, complete). Caller holds lock."""
        for tok, frame in self._frames:
            if tok == token:
                return frame, token + 1, False
        complete = (self._no_more and
                    (not self._frames or self._frames[-1][0] < token))
        return None, token, complete


class OutputBuffer:
    """Per-task output: `n_buffers` client buffers of serialized frames."""

    def __init__(self, kind: str, n_buffers: int,
                 max_bytes: int = 64 << 20):
        assert kind in (PARTITIONED, BROADCAST, GATHER)
        self.kind = kind
        self.n_buffers = n_buffers if kind != GATHER else 1
        self._cv = threading.Condition()
        self._buffers = [ClientBuffer(self._cv) for _ in range(self.n_buffers)]
        self._bytes = 0
        self._max_bytes = max_bytes
        self._no_more = False
        self._failed: Optional[str] = None

    # ------------------------------------------------------------- producer

    def _wait_for_space_locked(self, need: int, timeout_s: float) -> None:
        """Bounded producer wait while the buffer is over its byte bound
        (backpressure; the reference's OutputBuffers block the same way).
        Caller holds the cv lock."""
        deadline = None
        while self._bytes + need > self._max_bytes and self._bytes:
            if self._failed:
                raise RuntimeError(f"output buffer failed: {self._failed}")
            if deadline is None:
                deadline = time.monotonic() + timeout_s
            if not self._cv.wait(timeout=1.0) and time.monotonic() > deadline:
                raise TimeoutError("output buffer backpressure timeout")
        if self._failed:
            raise RuntimeError(f"output buffer failed: {self._failed}")

    def enqueue(self, buffer_id: int, frame: bytes,
                timeout_s: float = 300.0) -> None:
        with self._cv:
            self._wait_for_space_locked(len(frame), timeout_s)
            self._bytes += self._buffers[buffer_id].enqueue_locked(frame)
            self._cv.notify_all()

    def enqueue_broadcast(self, frame: bytes, timeout_s: float = 300.0) -> None:
        """A broadcast producer retains one copy per live consumer, so
        outrunning consumers would grow memory without bound (the reference's
        BroadcastOutputBuffer blocks the producer at the memory bound too)."""
        with self._cv:
            live = sum(1 for b in self._buffers if not b._aborted)
            need = len(frame) * max(live, 1)
            self._wait_for_space_locked(need, timeout_s)
            for b in self._buffers:
                self._bytes += b.enqueue_locked(frame)
            self._cv.notify_all()

    def set_no_more_pages(self) -> None:
        with self._cv:
            self._no_more = True
            for b in self._buffers:
                b.set_no_more_locked()
            self._cv.notify_all()

    def fail(self, message: str) -> None:
        """Poison the buffer: producers and consumers unblock with an error."""
        with self._cv:
            self._failed = message
            self._cv.notify_all()

    # ------------------------------------------------------------- consumer

    def get(self, buffer_id: int, token: int, wait_s: float = 1.0
            ) -> Tuple[Optional[bytes], int, bool]:
        """Long-poll for frame `token` of `buffer_id`; acks frames below it.
        -> (frame|None, next_token, complete)."""
        import time as _t

        deadline = _t.monotonic() + wait_s
        with self._cv:
            if self._failed:
                raise RuntimeError(f"task output failed: {self._failed}")
            self._bytes -= self._buffers[buffer_id].ack_locked(token)
            self._cv.notify_all()
            while True:
                frame, nxt, complete = self._buffers[buffer_id].get_locked(token)
                if frame is not None or complete:
                    return frame, nxt, complete
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    return None, token, False
                self._cv.wait(timeout=remaining)
                if self._failed:
                    raise RuntimeError(f"task output failed: {self._failed}")

    def abort(self, buffer_id: int) -> None:
        with self._cv:
            self._bytes -= self._buffers[buffer_id].abort_locked()
            self._cv.notify_all()

    def destroy(self) -> None:
        with self._cv:
            for b in self._buffers:
                self._bytes -= b.abort_locked()
            self._no_more = True
            self._cv.notify_all()

    def retained_bytes(self) -> int:
        with self._cv:
            return self._bytes
