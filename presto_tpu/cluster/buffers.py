"""Task output buffers: the producer half of the cross-host shuffle.

Analogue of execution/buffer/ (PartitionedOutputBuffer / BroadcastOutputBuffer
/ ClientBuffer, /root/reference/presto-main): each task owns one OutputBuffer
with a ClientBuffer per consumer; consumers pull serialized page frames with a
monotonically increasing token — requesting token T acknowledges frames below
T, re-requesting T is idempotent (ClientBuffer's token protocol,
server/TaskResource.java:245-318).

Chunk spooling (replayable mid-stream retry): an acked frame is no longer
freed — it retires into a bounded per-task SPOOL, still keyed by its sequence
token. A consumer that lost its producer (or was itself recreated) re-issues
GET from its chunk cursor and the spool replays the exact frame sequence;
a recreated consumer re-pulls from token 0 the same way. The spool is bounded
by `spool_max_bytes` (the `exchange_spool_bytes` session knob): overflow
retires the oldest-acked frames first and marks that client stream
non-replayable — a later GET below the surviving floor raises
:class:`ReplayWindowLost` (HTTP 410 on the worker), which escalates loudly to
a query-level retry instead of silently truncating the stream. Spooled bytes
are accounted in the unified memory pool via the `reserve` callback so
admission and the OOM killer see them.

Backpressure: the buffer bounds retained *unacked* bytes; enqueue blocks the
producing driver thread until a consumer drains (the reference blocks the
task's output future the same way). Spooled bytes never exert backpressure —
they are bounded by eviction, not by blocking the producer.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Tuple

PARTITIONED = "PARTITIONED"
BROADCAST = "BROADCAST"
GATHER = "GATHER"          # single consumer buffer (TaskOutputOperator case)


class ReplayWindowLost(RuntimeError):
    """GET below the spool floor: the frames needed to replay this stream
    were retired (spool overflow / nondeterministic sink / released buffer).
    Mid-stream task recovery is unsound here — the caller must escalate to a
    query-level retry, never skip ahead."""


class ClientBuffer:
    """One consumer's frame queue with token acks and an acked-frame spool.

    Frames are retained contiguously from `_floor`: the acked prefix
    (tokens < `_ack`) is the spool, the suffix is unacked. The spool trims
    oldest-first (raising `_floor`) when the owning OutputBuffer is over its
    spool bound."""

    def __init__(self, lock: threading.Condition):
        self._cv = lock
        self._frames: List[Tuple[int, bytes]] = []  # (token, frame), sorted
        self._floor = 0       # token of _frames[0]; below it = retired
        self._ack = 0         # tokens < _ack are acked (spooled)
        self._next_token = 0
        self._no_more = False
        self._aborted = False
        self.replay_lost = False   # some acked frame was retired for good

    # producer side (caller holds the cv lock via OutputBuffer)
    def enqueue_locked(self, frame: bytes) -> Tuple[int, int]:
        """-> (unacked bytes added, spool bytes added). A frame below an
        already-advanced ack boundary (a replacement task re-producing the
        prefix a rewired consumer has acked) lands directly in the spool
        account — it must never exert backpressure or the replay wedges."""
        if self._aborted:
            return 0, 0  # consumer is gone: drop, never accumulate bytes
        token = self._next_token
        self._frames.append((token, frame))
        self._next_token += 1
        if token < self._ack:
            return 0, len(frame)
        return len(frame), 0

    def set_no_more_locked(self) -> None:
        self._no_more = True

    def abort_locked(self) -> Tuple[int, int]:
        """-> (unacked bytes freed, spooled bytes freed)."""
        freed = spool_freed = 0
        for tok, f in self._frames:
            if tok < self._ack:
                spool_freed += len(f)
            else:
                freed += len(f)
        self._frames.clear()
        self._floor = self._next_token
        self._aborted = True
        self._no_more = True
        self.replay_lost = True
        return freed, spool_freed

    def final_ack_locked(self) -> Tuple[int, int]:
        """Final ack (consumer DELETE). A fully-delivered stream (`no_more`
        set and every frame acked) is NOT released: its frames are already
        in the bounded spool, and a recreated consumer may still need to
        replay them from token 0. A mid-stream final ack (early-exit
        consumer, e.g. LIMIT satisfied) releases for real — the producer
        must unblock and stop retaining. -> (unacked freed, spool freed)."""
        if self._no_more and self._ack >= self._next_token:
            return 0, 0
        return self.abort_locked()

    # consumer side
    def ack_locked(self, token: int) -> Tuple[int, int]:
        """Advance the ack boundary to `token`: newly acked frames move from
        the unacked (backpressure) account to the spool. A replaying consumer
        re-acking below the boundary is a no-op. -> (unacked bytes released,
        spool bytes gained) — equal unless frames were already retired."""
        if token <= self._ack:
            return 0, 0
        moved = 0
        for tok, f in self._frames:
            if tok >= token:
                break
            if tok >= self._ack:
                moved += len(f)
        self._ack = token
        return moved, moved

    def drop_oldest_spooled_locked(self) -> int:
        """Retire the oldest acked frame (spool overflow). -> bytes freed."""
        if not self._frames or self._frames[0][0] >= self._ack:
            return 0
        _, frame = self._frames.pop(0)
        self._floor += 1
        self.replay_lost = True
        return len(frame)

    def drop_spool_locked(self) -> int:
        """Retire the whole acked prefix (nonreplayable sink). -> bytes."""
        freed = 0
        while self._frames and self._frames[0][0] < self._ack:
            freed += len(self._frames.pop(0)[1])
            self._floor += 1
        return freed

    def spooled_bytes_locked(self) -> int:
        return sum(len(f) for tok, f in self._frames if tok < self._ack)

    def get_locked(self, token: int) -> Tuple[Optional[bytes], int, bool]:
        """-> (frame|None, next_token, complete). Caller holds lock.
        Raises ReplayWindowLost when `token` fell below the retained floor —
        the frame existed once and is gone, so waiting would be a lie."""
        if self._aborted:
            raise ReplayWindowLost(
                "replay window lost: buffer was released (final ack or task "
                "teardown) — stream cannot be replayed")
        if token < self._floor:
            raise ReplayWindowLost(
                f"replay window lost: token {token} below spool floor "
                f"{self._floor} (oldest acked frames were retired)")
        idx = token - self._floor
        if 0 <= idx < len(self._frames):
            tok, frame = self._frames[idx]
            assert tok == token, "spool tokens must be contiguous"
            return frame, token + 1, False
        return None, token, self._no_more and token >= self._next_token


class OutputBuffer:
    """Per-task output: `n_buffers` client buffers of serialized frames.

    `spool_max_bytes` bounds the acked-frame spool across all clients
    (0 disables spooling: acked frames retire immediately and every stream
    is non-replayable — the pre-spool protocol, but loud on replay).
    `reserve` is the unified-memory hook: called under the buffer lock with
    spool byte deltas (positive on retire-to-spool, negative on trim/free);
    it must be cheap and must not raise."""

    def __init__(self, kind: str, n_buffers: int,
                 max_bytes: int = 64 << 20,
                 spool_max_bytes: int = 64 << 20,
                 reserve: Optional[Callable[[int], None]] = None):
        assert kind in (PARTITIONED, BROADCAST, GATHER)
        self.kind = kind
        self.n_buffers = n_buffers if kind != GATHER else 1
        self._cv = threading.Condition()
        self._buffers = [ClientBuffer(self._cv) for _ in range(self.n_buffers)]
        self._bytes = 0          # unacked (backpressure account)
        self._spool_bytes = 0    # acked, retained for replay
        self._max_bytes = max_bytes
        self._spool_max = max(int(spool_max_bytes), 0)
        self._reserve = reserve
        self._no_more = False
        self._failed: Optional[str] = None
        self._nonreplayable: Optional[str] = None
        self._spool_pinned = False  # drain: never retire the replay window

    # ------------------------------------------------------------- producer

    def _wait_for_space_locked(self, need: int, timeout_s: float) -> None:
        """Bounded producer wait while the buffer is over its byte bound
        (backpressure; the reference's OutputBuffers block the same way).
        Caller holds the cv lock."""
        deadline = None
        while self._bytes + need > self._max_bytes and self._bytes:
            if self._failed:
                raise RuntimeError(f"output buffer failed: {self._failed}")
            if deadline is None:
                deadline = time.monotonic() + timeout_s
            if not self._cv.wait(timeout=1.0) and time.monotonic() > deadline:
                raise TimeoutError("output buffer backpressure timeout")
        if self._failed:
            raise RuntimeError(f"output buffer failed: {self._failed}")

    def enqueue(self, buffer_id: int, frame: bytes,
                timeout_s: float = 300.0) -> None:
        with self._cv:
            self._wait_for_space_locked(len(frame), timeout_s)
            unacked, spooled = self._buffers[buffer_id].enqueue_locked(frame)
            self._bytes += unacked
            self._account_spool_locked(spooled)
            self._trim_spool_locked()
            self._cv.notify_all()

    def enqueue_broadcast(self, frame: bytes, timeout_s: float = 300.0) -> None:
        """A broadcast producer retains one copy per live consumer, so
        outrunning consumers would grow memory without bound (the reference's
        BroadcastOutputBuffer blocks the producer at the memory bound too)."""
        with self._cv:
            live = sum(1 for b in self._buffers if not b._aborted)
            need = len(frame) * max(live, 1)
            self._wait_for_space_locked(need, timeout_s)
            for b in self._buffers:
                unacked, spooled = b.enqueue_locked(frame)
                self._bytes += unacked
                self._account_spool_locked(spooled)
            self._trim_spool_locked()
            self._cv.notify_all()

    def set_no_more_pages(self) -> None:
        with self._cv:
            self._no_more = True
            for b in self._buffers:
                b.set_no_more_locked()
            self._cv.notify_all()

    def fail(self, message: str) -> None:
        """Poison the buffer: producers and consumers unblock with an error."""
        with self._cv:
            self._failed = message
            self._cv.notify_all()

    def mark_nonreplayable(self, reason: str) -> None:
        """This task's frame sequence is not deterministic (e.g. multiple
        sink drivers interleave nondeterministically): spooling it would
        replay *different* data. Drop the spool and stop retaining."""
        with self._cv:
            if self._nonreplayable:
                return
            self._nonreplayable = reason
            freed = 0
            for b in self._buffers:
                freed += b.drop_spool_locked()
                b.replay_lost = True
            self._account_spool_locked(-freed)
            self._cv.notify_all()

    # --------------------------------------------------------------- spool

    def _account_spool_locked(self, delta: int) -> None:
        if not delta:
            return
        self._spool_bytes += delta
        if self._reserve is not None:
            try:
                self._reserve(delta)
            except Exception:  # noqa: BLE001 - accounting must not poison I/O
                pass

    def pin_spool(self) -> None:
        """Drain support: stop retiring acked frames even when the spool is
        over its bound, so every live stream's replay window stays COMPLETE
        while consumers are handed to a replacement task. The window is
        short (the drain re-places producers within seconds) and the bytes
        stay accounted in the shared pool, so the overshoot is observable."""
        with self._cv:
            self._spool_pinned = True

    def output_drained(self) -> bool:
        """No live consumer depends on FUTURE pulls from this buffer: every
        stream was fully delivered and acked (complete streams keep their
        spool for replay, which nobody will need) or explicitly released.
        The drain machine's per-task gate — a FINISHED task still serving
        chunks pins its node in DRAINING until consumers catch up or are
        handed to replacements."""
        with self._cv:
            return all(b._aborted or (b._no_more and b._ack >= b._next_token)
                       for b in self._buffers)

    def replayable_all(self) -> bool:
        """Every stream of this buffer can still replay from token 0 — the
        per-task drain-progress signal (a handoff is exactly-once only while
        this holds)."""
        with self._cv:
            return not self._nonreplayable and \
                all(not b.replay_lost and b._floor == 0 for b in self._buffers)

    def _trim_spool_locked(self) -> None:
        """Retire oldest-acked frames until the spool fits its bound, biggest
        spooler first (deterministic tie-break by buffer index)."""
        if self._spool_pinned:
            return
        while self._spool_bytes > self._spool_max:
            victim = max(self._buffers, key=lambda b: b.spooled_bytes_locked())
            freed = victim.drop_oldest_spooled_locked()
            if freed == 0:
                break
            self._account_spool_locked(-freed)

    # ------------------------------------------------------------- consumer

    def get(self, buffer_id: int, token: int, wait_s: float = 1.0
            ) -> Tuple[Optional[bytes], int, bool]:
        """Long-poll for frame `token` of `buffer_id`; acks frames below it
        into the spool. -> (frame|None, next_token, complete). Raises
        ReplayWindowLost when `token` was already retired."""
        import time as _t

        deadline = _t.monotonic() + wait_s
        with self._cv:
            if self._failed:
                raise RuntimeError(f"task output failed: {self._failed}")
            unacked, spooled = self._buffers[buffer_id].ack_locked(token)
            self._bytes -= unacked
            if self._nonreplayable:
                self._buffers[buffer_id].drop_spool_locked()
                spooled = 0
            self._account_spool_locked(spooled)
            self._trim_spool_locked()
            self._cv.notify_all()
            while True:
                frame, nxt, complete = \
                    self._buffers[buffer_id].get_locked(token)
                if frame is not None or complete:
                    return frame, nxt, complete
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    return None, token, False
                self._cv.wait(timeout=remaining)
                if self._failed:
                    raise RuntimeError(f"task output failed: {self._failed}")

    def abort(self, buffer_id: int) -> None:
        """Consumer DELETE: retire a fully-delivered stream into the spool
        (still replayable by a recreated consumer) or release a mid-stream
        abort for good — see ClientBuffer.final_ack_locked."""
        with self._cv:
            unacked, spooled = self._buffers[buffer_id].final_ack_locked()
            self._bytes -= unacked
            self._account_spool_locked(-spooled)
            self._cv.notify_all()

    def destroy(self) -> None:
        with self._cv:
            for b in self._buffers:
                unacked, spooled = b.abort_locked()
                self._bytes -= unacked
                self._account_spool_locked(-spooled)
            self._no_more = True
            self._cv.notify_all()

    def retained_bytes(self) -> int:
        """Unacked bytes (the backpressure account; spool excluded — it is
        reported separately and accounted in the shared pool)."""
        with self._cv:
            return self._bytes

    def spooled_bytes(self) -> int:
        with self._cv:
            return self._spool_bytes

    def replayable(self, buffer_id: int) -> bool:
        """Can `buffer_id`'s stream still be replayed from token 0?"""
        with self._cv:
            b = self._buffers[buffer_id]
            return not (b.replay_lost or self._nonreplayable
                        or b._floor > 0)
