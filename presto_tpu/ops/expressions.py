"""Row-expression IR and its XLA compiler.

Analogue of the reference's RowExpression tree + runtime bytecode generation
(presto-main sql/gen/PageFunctionCompiler.java:97,160-193, ExpressionCompiler,
sql/relational/RowExpression). Where the reference emits JVM bytecode per expression
and relies on JIT, we *compose jnp closures* and let XLA fuse the whole
filter+project into one TPU kernel — the compiler pass replaces the bytecode pass.

Null semantics: every compiled node yields (data, nulls) with nulls=None meaning
"provably non-null" (the compiler drops mask arithmetic entirely for the common
TPC case, like the reference's @SqlNullable specialization).

Strings: varchar values are dictionary codes. String predicates are resolved against
the input block's dictionary AT COMPILE TIME (dictionaries are static page metadata),
so e.g. `l_shipmode IN ('MAIL','SHIP')` compiles to an int compare — the reference
gets the same effect dynamically via DictionaryAwarePageProjection.java.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..block import Dictionary, Page
from ..types import (BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, REAL, TIMESTAMP, Type,
                     UNKNOWN, VARCHAR, DecimalType, VarcharType, is_floating,
                     is_integral, is_numeric, is_string)

Array = jnp.ndarray
CompiledValue = Tuple[Array, Optional[Array]]  # (data, null_mask)


# ---------------------------------------------------------------------------
# IR
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RowExpression:
    type: Type


@dataclasses.dataclass(frozen=True)
class InputRef(RowExpression):
    channel: int

    def __str__(self):
        return f"#{self.channel}"


@dataclasses.dataclass(frozen=True)
class Constant(RowExpression):
    value: Any  # python value; strings raw (encoded at compile), decimals unscaled int

    def __str__(self):
        return repr(self.value)


@dataclasses.dataclass(frozen=True)
class SymbolRef(RowExpression):
    """Planner-level column reference by symbol name (sql/planner/Symbol.java).

    Plans carry expressions over symbols; the local execution planner rewrites every
    SymbolRef to a channel InputRef against the child operator's layout (the same
    symbol->channel translation LocalExecutionPlanner.java does via
    SourceLayout/InputChannels)."""
    name: str

    def __str__(self):
        return self.name


@dataclasses.dataclass(frozen=True)
class Call(RowExpression):
    name: str
    args: Tuple[RowExpression, ...]

    def __str__(self):
        return f"{self.name}({', '.join(map(str, self.args))})"


@dataclasses.dataclass(frozen=True)
class SpecialForm(RowExpression):
    """AND / OR / NOT / IF / CASE(WHEN) / IN / BETWEEN / IS_NULL / COALESCE / CAST."""
    form: str
    args: Tuple[RowExpression, ...]

    def __str__(self):
        return f"{self.form}({', '.join(map(str, self.args))})"


def input_ref(channel: int, type_: Type) -> InputRef:
    return InputRef(type_, channel)


def symbol_ref(name: str, type_: Type) -> SymbolRef:
    return SymbolRef(type_, name)


def rewrite_expression(expr: RowExpression, fn) -> RowExpression:
    """Bottom-up rewrite: fn(node) -> replacement or None (keep). Children first."""
    if isinstance(expr, Call):
        new_args = tuple(rewrite_expression(a, fn) for a in expr.args)
        expr = Call(expr.type, expr.name, new_args)
    elif isinstance(expr, SpecialForm):
        new_args = tuple(rewrite_expression(a, fn) for a in expr.args)
        expr = SpecialForm(expr.type, expr.form, new_args)
    out = fn(expr)
    return expr if out is None else out


def symbols_in(expr: RowExpression) -> set:
    """Names of all SymbolRefs in the tree."""
    out = set()

    def visit(e):
        if isinstance(e, SymbolRef):
            out.add(e.name)
        return None
    rewrite_expression(expr, visit)
    return out


def resolve_symbols(expr: RowExpression, channels: Dict[str, int]) -> RowExpression:
    """SymbolRef -> InputRef via a symbol->channel map (local-planning step)."""
    def visit(e):
        if isinstance(e, SymbolRef):
            return InputRef(e.type, channels[e.name])
        return None
    return rewrite_expression(expr, visit)


def constant(value: Any, type_: Type) -> Constant:
    return Constant(type_, value)


def call(name: str, type_: Type, *args: RowExpression) -> Call:
    return Call(type_, name, tuple(args))


def special(form: str, type_: Type, *args: RowExpression) -> SpecialForm:
    return SpecialForm(type_, form, tuple(args))


# ---------------------------------------------------------------------------
# type rules (FunctionManager / built-in operator resolution analogue)
# ---------------------------------------------------------------------------

def arithmetic_result_type(op: str, a: Type, b: Type) -> Type:
    if is_string(a) or is_string(b):
        raise TypeError(f"cannot {op} strings")
    if op == "divide":
        if isinstance(a, DecimalType) or isinstance(b, DecimalType) or \
                is_floating(a) or is_floating(b):
            return DOUBLE
        return BIGINT if (a is BIGINT or b is BIGINT) else INTEGER
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        if op == "multiply":
            return DecimalType(min(18, a.precision + b.precision), a.scale + b.scale)
        return DecimalType(min(18, max(a.precision, b.precision) + 1), max(a.scale, b.scale))
    if isinstance(a, DecimalType):
        if is_floating(b):
            return DOUBLE
        return a
    if isinstance(b, DecimalType):
        if is_floating(a):
            return DOUBLE
        return b
    if is_floating(a) or is_floating(b):
        return DOUBLE
    if a is DATE or b is DATE:
        return DATE  # date +/- interval days
    order = ["smallint", "integer", "bigint"]
    return a if order.index(a.name) >= order.index(b.name) else b


# ---------------------------------------------------------------------------
# compiler
# ---------------------------------------------------------------------------

class InputLayout:
    """Static description of the input page: types + dictionaries per channel."""

    def __init__(self, types: Sequence[Type], dictionaries: Sequence[Optional[Dictionary]]):
        self.types = list(types)
        self.dictionaries = list(dictionaries)

    @staticmethod
    def of_page(page: Page) -> "InputLayout":
        return InputLayout([b.type for b in page.blocks],
                           [b.dictionary for b in page.blocks])

    def dictionary(self, ch: int) -> Optional[Dictionary]:
        return self.dictionaries[ch]


class CompiledExpression:
    """fn(blocks_data: tuple, blocks_nulls: tuple) -> (data, nulls).

    Holds the output dictionary when the expression is a varchar passthrough."""

    def __init__(self, fn, type_: Type, dictionary: Optional[Dictionary] = None):
        self.fn = fn
        self.type = type_
        self.dictionary = dictionary

    def __call__(self, datas, nulls) -> CompiledValue:
        return self.fn(datas, nulls)


def _like_to_predicate(pattern: str, escape: Optional[str] = None) -> Callable[[str], bool]:
    """SQL LIKE -> python predicate (reference: type/LikeFunctions.java via joni regex)."""
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    rx = re.compile("^" + "".join(out) + "$", re.DOTALL)
    return lambda s: rx.match(s) is not None


def _np_const(value, type_: Type):
    return np.asarray(value, dtype=type_.np_dtype)


# pluggable scalar-function compilers, keyed by Call name: each maps
# `(compiler, call_expr) -> (closure, output_dictionary)` — the counterpart
# of sql/analyzer.py's EXTERNAL_FUNCTIONS typer registry (together they are
# the engine's FunctionManager extension point; presto_tpu.functions.*
# modules self-register on import)
EXTERNAL_COMPILERS: dict = {}


def register_compiler(name: str, fn) -> None:
    EXTERNAL_COMPILERS[name.lower()] = fn  # prestocheck: ignore[unbounded-cache] - plugin registry: one entry per registered function, not per request


class ExpressionCompiler:
    """Compiles a RowExpression against a static InputLayout."""

    def __init__(self, layout: InputLayout):
        self.layout = layout

    def compile(self, expr: RowExpression) -> CompiledExpression:
        fn, dict_ = self._compile(expr)
        return CompiledExpression(fn, expr.type, dict_)

    # returns (fn, output_dictionary)
    def _compile(self, expr: RowExpression):
        if isinstance(expr, InputRef):
            ch = expr.channel
            d = self.layout.dictionary(ch)
            return (lambda datas, nulls: (datas[ch], nulls[ch])), d

        if isinstance(expr, Constant):
            return self._compile_constant(expr)

        if isinstance(expr, SpecialForm):
            return self._compile_special(expr)

        if isinstance(expr, Call):
            return self._compile_call(expr)

        raise TypeError(f"unknown expression node {expr!r}")

    def _compile_constant(self, expr: Constant):
        if expr.value is None:
            z = _np_const(0, expr.type if expr.type is not UNKNOWN else BIGINT)
            return (lambda datas, nulls: (jnp.asarray(z), jnp.asarray(True))), None
        if is_string(expr.type):
            # raw string constant: kept python-side; comparisons resolve it against the
            # other side's dictionary. Standalone projection of a string constant gets
            # its own single-entry dictionary.
            d = Dictionary([expr.value])
            zero = np.asarray(0, dtype=np.int32)
            return (lambda datas, nulls: (jnp.asarray(zero), None)), d
        v = expr.value
        if isinstance(expr.type, DecimalType) and not isinstance(v, (int, np.integer)):
            v = round(float(v) * 10 ** expr.type.scale)
        c = _np_const(v, expr.type)
        return (lambda datas, nulls: (jnp.asarray(c), None)), None

    # --- special forms ------------------------------------------------------

    def _compile_special(self, expr: SpecialForm):
        form = expr.form
        if form == "AND" or form == "OR":
            parts = [self._compile(a)[0] for a in expr.args]
            is_and = form == "AND"

            def fn(datas, nulls):
                acc_d, acc_n = parts[0](datas, nulls)
                for p in parts[1:]:
                    d, n = p(datas, nulls)
                    if is_and:
                        new_d = acc_d & d
                    else:
                        new_d = acc_d | d
                    acc_n = _logic_nulls(is_and, acc_d, acc_n, d, n)
                    acc_d = new_d
                return acc_d, acc_n
            return fn, None

        if form == "NOT":
            f = self._compile(expr.args[0])[0]
            return (lambda datas, nulls: ((lambda d, n: (~d, n))(*f(datas, nulls)))), None

        if form == "IS_NULL":
            f = self._compile(expr.args[0])[0]

            def fn(datas, nulls):
                d, n = f(datas, nulls)
                if n is None:
                    return jnp.zeros(jnp.shape(d), dtype=jnp.bool_), None
                return n, None
            return fn, None

        if form == "IF":
            c = self._compile(expr.args[0])[0]
            t, td = self._compile(expr.args[1])
            e, ed = self._compile(expr.args[2])
            out_dict = _merge_dicts(td, ed)

            def fn(datas, nulls):
                cd, cn = c(datas, nulls)
                td_, tn = t(datas, nulls)
                ed_, en = e(datas, nulls)
                cond = cd if cn is None else (cd & ~cn)
                data = jnp.where(cond, td_, ed_)
                n = _where_nulls(cond, tn, en, jnp.shape(data))
                return data, n
            return fn, out_dict

        if form == "COALESCE":
            parts = [self._compile(a) for a in expr.args]
            out_dict = None
            for _, d in parts:
                out_dict = _merge_dicts(out_dict, d)

            def fn(datas, nulls):
                d0, n0 = parts[0][0](datas, nulls)
                data, n = d0, n0
                for p, _ in parts[1:]:
                    if n is None:
                        break
                    pd, pn = p(datas, nulls)
                    data = jnp.where(n, pd, data)
                    n = pn if pn is None else (n & pn)
                return data, n
            return fn, out_dict

        if form == "IN":
            return self._compile_in(expr)

        if form == "BETWEEN":
            v = expr.args[0]
            lo, hi = expr.args[1], expr.args[2]
            ge = self._compile_comparison("greater_than_or_equal", v, lo)
            le = self._compile_comparison("less_than_or_equal", v, hi)

            def fn(datas, nulls):
                g, gn = ge(datas, nulls)
                l, ln = le(datas, nulls)
                return g & l, _combine_nulls(gn, ln)
            return fn, None

        if form == "CAST":
            return self._compile_cast(expr)

        if form == "SWITCH":
            # args: [operand?, (when_cond, when_value)*..., default] flattened as
            # cond1, val1, cond2, val2, ..., default  (searched-case form)
            pairs = expr.args[:-1]
            default = expr.args[-1]
            conds = [self._compile(pairs[i])[0] for i in range(0, len(pairs), 2)]
            vals = [self._compile(pairs[i + 1]) for i in range(0, len(pairs), 2)]
            dflt, ddict = self._compile(default)
            out_dict = ddict
            for _, vd in vals:
                out_dict = _merge_dicts(out_dict, vd)

            def fn(datas, nulls):
                data, n = dflt(datas, nulls)
                # evaluate in reverse so first match wins
                for c, (v, _) in zip(reversed(conds), reversed(vals)):
                    cd, cn = c(datas, nulls)
                    cond = cd if cn is None else (cd & ~cn)
                    vd_, vn = v(datas, nulls)
                    data = jnp.where(cond, vd_, data)
                    n = _where_nulls(cond, vn, n, jnp.shape(data))
                return data, n
            return fn, out_dict

        raise NotImplementedError(f"special form {form}")

    def _compile_in(self, expr: SpecialForm):
        value = expr.args[0]
        items = expr.args[1:]
        if is_string(value.type) and all(isinstance(i, Constant) for i in items):
            d = self._dictionary_of(value)
            codes = sorted(c for c in (d.code_of(i.value) for i in items) if c >= 0) if d else []
            vfn = self._compile(value)[0]
            codes_arr = np.asarray(codes, dtype=np.int32)

            def fn(datas, nulls):
                vd, vn = vfn(datas, nulls)
                if len(codes_arr) == 0:
                    return jnp.zeros(jnp.shape(vd), dtype=jnp.bool_), vn
                acc = (vd == codes_arr[0])
                for c in codes_arr[1:]:
                    acc = acc | (vd == c)
                return acc, vn
            return fn, None
        # generic: OR of equals
        ors = [self._compile_comparison("equal", value, i) for i in items]

        def fn(datas, nulls):
            d, n = ors[0](datas, nulls)
            for o in ors[1:]:
                od, on = o(datas, nulls)
                d = d | od
                n = _combine_nulls(n, on)
            return d, n
        return fn, None

    def _compile_cast(self, expr: SpecialForm):
        src = expr.args[0]
        target = expr.type
        f, d = self._compile(src)
        st = src.type
        if st == target:
            return f, d
        if isinstance(st, DecimalType) and is_floating(target):
            scale = 10.0 ** st.scale
            return (lambda datas, nulls: ((lambda dd, nn: (
                dd.astype(jnp.float64) / scale, nn))(*f(datas, nulls)))), None
        if isinstance(target, DecimalType):
            if isinstance(st, DecimalType):
                shift = target.scale - st.scale
                mul = 10 ** abs(shift)
                if shift >= 0:
                    return (lambda datas, nulls: ((lambda dd, nn: (
                        dd.astype(jnp.int64) * mul, nn))(*f(datas, nulls)))), None
                return (lambda datas, nulls: ((lambda dd, nn: (
                    dd.astype(jnp.int64) // mul, nn))(*f(datas, nulls)))), None
            if is_integral(st):
                mul = 10 ** target.scale
                return (lambda datas, nulls: ((lambda dd, nn: (
                    dd.astype(jnp.int64) * mul, nn))(*f(datas, nulls)))), None
            if is_floating(st):
                mul = 10.0 ** target.scale
                return (lambda datas, nulls: ((lambda dd, nn: (
                    jnp.round(dd * mul).astype(jnp.int64), nn))(*f(datas, nulls)))), None
        dtype = jnp.dtype(target.np_dtype)
        return (lambda datas, nulls: ((lambda dd, nn: (
            dd.astype(dtype), nn))(*f(datas, nulls)))), None

    # --- calls --------------------------------------------------------------

    _CMP = {"equal": "==", "not_equal": "!=", "less_than": "<",
            "less_than_or_equal": "<=", "greater_than": ">",
            "greater_than_or_equal": ">="}
    _ARITH = {"add", "subtract", "multiply", "divide", "modulus", "negate"}

    def _compile_call(self, expr: Call):
        name = expr.name
        if name in self._CMP:
            return self._compile_comparison(name, expr.args[0], expr.args[1]), None
        if name in self._ARITH:
            return self._compile_arithmetic(expr), None
        if name == "like":
            return self._compile_like(expr), None
        if name == "year" or name == "month" or name == "day":
            f = self._compile(expr.args[0])[0]
            part = name

            def fn(datas, nulls):
                d, n = f(datas, nulls)
                y, m, dd = _civil_from_days(d.astype(jnp.int32))
                out = {"year": y, "month": m, "day": dd}[part]
                return out.astype(jnp.int64), n
            return fn, None
        if name == "substr" or name == "substring":
            return self._compile_substr(expr)
        if name == "cardinality":
            # dynamic ARRAY/MAP handle column: per-handle lengths gathered
            # from the host ArrayValues store (a compile-time constant —
            # the kernel cache keys on the store's (token, len) version)
            f, d = self._compile(expr.args[0])
            if d is None or not hasattr(d, "values"):
                raise NotImplementedError(
                    "cardinality() needs an array/map handle column")
            lengths = np.asarray([len(v) for v in d.values],
                                 dtype=np.int64)
            lengths = np.concatenate([lengths, [0]])  # slot for handle -1

            def fn(datas, nulls, _l=lengths):
                data, n = f(datas, nulls)
                codes = data.astype(jnp.int32)
                out = jnp.take(jnp.asarray(_l),
                               jnp.clip(codes, 0, len(_l) - 1))
                neg = codes < 0
                n = neg if n is None else (n | neg)
                return out, n
            return fn, None
        if name == "abs":
            f = self._compile(expr.args[0])[0]
            return (lambda datas, nulls: ((lambda d, n: (jnp.abs(d), n))(*f(datas, nulls)))), None
        if name in ("sqrt", "ln", "log10", "exp", "floor", "ceil", "ceiling", "round"):
            f = self._compile(expr.args[0])[0]
            jfn = {"sqrt": jnp.sqrt, "ln": jnp.log, "log10": jnp.log10, "exp": jnp.exp,
                   "floor": jnp.floor, "ceil": jnp.ceil, "ceiling": jnp.ceil,
                   "round": jnp.round}[name]
            at = expr.args[0].type
            if isinstance(at, DecimalType) and name != "sqrt" and \
                    name not in ("ln", "log10", "exp"):
                # decimal substrate is scaled int64: round/floor/ceil operate
                # on whole units of 10^scale, exactly (no float round-trip).
                # round is half-AWAY-from-zero (Presto semantics)
                mul = 10 ** at.scale

                def fn(datas, nulls, _name=name, _m=mul):
                    d, n = f(datas, nulls)
                    a = jnp.abs(d)
                    if _name == "round":
                        q = jnp.sign(d) * ((a + _m // 2) // _m)
                    elif _name == "floor":
                        q = jnp.where(d >= 0, a // _m, -((a + _m - 1) // _m))
                    else:  # ceil
                        q = jnp.where(d >= 0, (a + _m - 1) // _m, -(a // _m))
                    return (q * _m).astype(jnp.int64), n
                return fn, None
            if name == "round":
                # Presto round(double): half away from zero, not half-to-even
                return (lambda datas, nulls: ((lambda d, n: (
                    jnp.sign(d) * jnp.floor(jnp.abs(d) + 0.5), n))(
                        *f(datas, nulls)))), None
            return (lambda datas, nulls: ((lambda d, n: (jfn(d), n))(*f(datas, nulls)))), None
        if name == "hash_code":  # engine-internal
            f = self._compile(expr.args[0])[0]
            return (lambda datas, nulls: ((lambda d, n: (
                _hash64(d.astype(jnp.int64)), n))(*f(datas, nulls)))), None
        if name in ("log2", "cbrt", "truncate"):
            f = self._compile(expr.args[0])[0]
            jfn = {"log2": jnp.log2, "cbrt": jnp.cbrt,
                   "truncate": jnp.trunc}[name]
            at = expr.args[0].type
            if name == "truncate" and isinstance(at, DecimalType):
                mul = 10 ** at.scale

                def fn(datas, nulls, _m=mul):
                    d, n = f(datas, nulls)
                    # toward-zero on the scaled int substrate
                    q = jnp.sign(d) * (jnp.abs(d) // _m) * _m
                    return q.astype(jnp.int64), n
                return fn, None
            return (lambda datas, nulls: (
                (lambda d, n: (jfn(d), n))(*f(datas, nulls)))), None
        if name == "round2":  # round(x, digits) with literal digits
            f = self._compile(expr.args[0])[0]
            dig = expr.args[1]
            if not isinstance(dig, Constant):
                raise NotImplementedError("round() digits must be a literal")
            at = expr.args[0].type
            digits = int(dig.value)
            scale = at.scale if isinstance(at, DecimalType) else 0
            if is_integral(at) or isinstance(at, DecimalType):
                # exact on the (scaled-)integer substrate, half away from zero
                shift = scale - digits
                if shift <= 0:
                    return f, None  # already finer than requested digits
                m = 10 ** shift

                def fn(datas, nulls, _m=m):
                    d, n = f(datas, nulls)
                    q = jnp.sign(d) * ((jnp.abs(d) + _m // 2) // _m) * _m
                    return q.astype(jnp.int64), n
                return fn, None
            mul = 10.0 ** digits
            return (lambda datas, nulls: ((lambda d, n: (
                jnp.sign(d) * jnp.floor(jnp.abs(d) * mul + 0.5) / mul, n))(
                    *f(datas, nulls)))), None
        if name == "power":
            fa = self._compile(expr.args[0])[0]
            fb = self._compile(expr.args[1])[0]

            def fn(datas, nulls):
                a, na = fa(datas, nulls)
                b, nb = fb(datas, nulls)
                n = na if nb is None else (nb if na is None else (na | nb))
                return jnp.power(a, b), n
            return fn, None
        if name == "sign":
            f = self._compile(expr.args[0])[0]
            if is_floating(expr.args[0].type):
                # Presto: sign(double) -> double (NaN propagates)
                return (lambda datas, nulls: ((lambda d, n: (
                    jnp.sign(d), n))(*f(datas, nulls)))), None
            return (lambda datas, nulls: ((lambda d, n: (
                jnp.sign(d).astype(jnp.int64), n))(*f(datas, nulls)))), None
        if name in ("greatest", "least"):
            fns = [self._compile(a)[0] for a in expr.args]
            pick = jnp.maximum if name == "greatest" else jnp.minimum

            def fn(datas, nulls):
                d, n = fns[0](datas, nulls)
                for g in fns[1:]:
                    d2, n2 = g(datas, nulls)
                    d = pick(d, d2)
                    # SQL: greatest/least is NULL if ANY argument is NULL
                    n = n2 if n is None else (n if n2 is None else (n | n2))
                return d, n
            return fn, None
        if name in ("quarter", "week", "day_of_week", "dow", "day_of_year",
                    "doy"):
            f = self._compile(expr.args[0])[0]
            part = name

            def fn(datas, nulls):
                d, n = f(datas, nulls)
                days = d.astype(jnp.int32)
                if part == "quarter":
                    _, m, _ = _civil_from_days(days)
                    out = (m - 1) // 3 + 1
                elif part in ("day_of_week", "dow"):
                    out = (days.astype(jnp.int64) + 3) % 7 + 1  # 1=Monday
                elif part in ("day_of_year", "doy"):
                    y, _, _ = _civil_from_days(days)
                    jan1 = _days_from_civil_vec(y, 1, 1)
                    out = days.astype(jnp.int64) - jan1 + 1
                else:  # ISO 8601 week-of-year
                    y, _, _ = _civil_from_days(days)
                    jan1 = _days_from_civil_vec(y, 1, 1)
                    doy = days.astype(jnp.int64) - jan1 + 1
                    dow = (days.astype(jnp.int64) + 3) % 7 + 1  # 1=Monday
                    w = (doy - dow + 10) // 7

                    def weeks_in(yy):
                        p = (yy + yy // 4 - yy // 100 + yy // 400) % 7
                        pm = ((yy - 1) + (yy - 1) // 4 - (yy - 1) // 100 +
                              (yy - 1) // 400) % 7
                        return 52 + ((p == 4) | (pm == 3)).astype(jnp.int64)
                    y64 = y.astype(jnp.int64)
                    out = jnp.where(w < 1, weeks_in(y64 - 1),
                                    jnp.where(w > weeks_in(y64), 1, w))
                return out.astype(jnp.int64), n
            return fn, None
        if name in ("length", "upper", "lower"):
            d = self._dictionary_of(expr.args[0])
            if d is None or not hasattr(d, "values"):
                raise NotImplementedError(
                    f"{name}() needs a materialized dictionary column")
            f = self._compile(expr.args[0])[0]
            if name == "length":
                lens = jnp.asarray([len(v) for v in d.values],
                                   dtype=jnp.int64)
                return (lambda datas, nulls: ((lambda c, n: (
                    lens[jnp.clip(c.astype(jnp.int32), 0, len(d.values) - 1)],
                    n))(*f(datas, nulls)))), None
            # upper/lower: transformed values can COLLIDE ('abc' and 'ABC'
            # both upper to 'ABC'), so codes re-encode through a deduplicated
            # dictionary — code-based equality then matches all colliding rows
            xform = str.upper if name == "upper" else str.lower
            transformed = [xform(v) for v in d.values]
            uniq = sorted(set(transformed))
            pos = {v: i for i, v in enumerate(uniq)}
            remap = jnp.asarray([pos[v] for v in transformed], dtype=jnp.int32)
            new_dict = Dictionary(uniq)

            def fn(datas, nulls, _remap=remap, _hi=len(transformed) - 1):
                c, n = f(datas, nulls)
                return _remap[jnp.clip(c.astype(jnp.int32), 0, _hi)], n
            return fn, new_dict
        compiler = EXTERNAL_COMPILERS.get(name)
        if compiler is not None:
            return compiler(self, expr)
        raise NotImplementedError(f"function {name}")

    def _dictionary_of(self, expr: RowExpression) -> Optional[Dictionary]:
        return self._compile(expr)[1]

    def _compile_comparison(self, op: str, left: RowExpression, right: RowExpression):
        sym = self._CMP[op]
        if is_string(left.type) or is_string(right.type):
            return self._compile_string_comparison(op, left, right)
        lf = self._compile(left)[0]
        rf = self._compile(right)[0]
        lt, rt = left.type, right.type
        lscale = lt.scale if isinstance(lt, DecimalType) else 0
        rscale = rt.scale if isinstance(rt, DecimalType) else 0
        # align decimal scales; mixed decimal/float compares in float space
        mixed_float = (is_floating(lt) and isinstance(rt, DecimalType)) or \
                      (is_floating(rt) and isinstance(lt, DecimalType))

        def fn(datas, nulls):
            ld, ln = lf(datas, nulls)
            rd, rn = rf(datas, nulls)
            if mixed_float:
                if lscale:
                    ld = ld.astype(jnp.float64) / (10 ** lscale)
                if rscale:
                    rd = rd.astype(jnp.float64) / (10 ** rscale)
            else:
                if lscale < rscale:
                    ld = ld.astype(jnp.int64) * (10 ** (rscale - lscale))
                elif rscale < lscale:
                    rd = rd.astype(jnp.int64) * (10 ** (lscale - rscale))
            d = {"==": lambda a, b: a == b, "!=": lambda a, b: a != b,
                 "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
                 ">": lambda a, b: a > b, ">=": lambda a, b: a >= b}[sym](ld, rd)
            return d, _combine_nulls(ln, rn)
        return fn

    def _compile_string_comparison(self, op: str, left: RowExpression, right: RowExpression):
        # literal vs column: resolve literal to a code in the column's dictionary
        if isinstance(right, Constant) and not isinstance(left, Constant):
            d = self._dictionary_of(left)
            lf = self._compile(left)[0]
            if op in ("equal", "not_equal"):
                code = d.code_of(right.value) if d else -1
                neq = op == "not_equal"

                def fn(datas, nulls):
                    ld, ln = lf(datas, nulls)
                    if code < 0:
                        r = jnp.ones(jnp.shape(ld), jnp.bool_) if neq \
                            else jnp.zeros(jnp.shape(ld), jnp.bool_)
                        return r, ln
                    r = (ld != code) if neq else (ld == code)
                    return r, ln
                return fn
            # ordering comparison on strings: use sort-rank of codes
            ranks = d.sort_keys()
            target = right.value

            def key_rank(v):
                import bisect
                vals = sorted(d.values.astype(str))
                return bisect.bisect_left(vals, v)

            tr = key_rank(target)
            strict_map = {"less_than": lambda r: r < tr,
                          "less_than_or_equal": lambda r: r < tr,  # refined below
                          "greater_than": lambda r: r >= tr,
                          "greater_than_or_equal": lambda r: r >= tr}
            # for <=, include equal value if present
            eq_code = d.code_of(target)

            def fn(datas, nulls):
                ld, ln = lf(datas, nulls)
                r = jnp.asarray(ranks)[ld]
                if op == "less_than":
                    res = r < tr
                elif op == "greater_than_or_equal":
                    res = r >= tr
                elif op == "less_than_or_equal":
                    res = (r < tr) | ((ld == eq_code) if eq_code >= 0 else False)
                else:  # greater_than
                    res = (r >= tr) & ((ld != eq_code) if eq_code >= 0 else True)
                return res, ln
            return fn
        if isinstance(left, Constant):
            flip = {"equal": "equal", "not_equal": "not_equal",
                    "less_than": "greater_than", "greater_than": "less_than",
                    "less_than_or_equal": "greater_than_or_equal",
                    "greater_than_or_equal": "less_than_or_equal"}[op]
            return self._compile_string_comparison(flip, right, left)
        # column vs column: only valid when sharing a dictionary
        ld_ = self._dictionary_of(left)
        rd_ = self._dictionary_of(right)
        lf = self._compile(left)[0]
        rf = self._compile(right)[0]
        if ld_ is rd_ and op in ("equal", "not_equal"):
            neq = op == "not_equal"

            def fn(datas, nulls):
                ld, ln = lf(datas, nulls)
                rd, rn = rf(datas, nulls)
                r = (ld != rd) if neq else (ld == rd)
                return r, _combine_nulls(ln, rn)
            return fn
        raise NotImplementedError(
            "cross-dictionary string comparison requires a re-encode (not yet needed)")

    def _compile_arithmetic(self, expr: Call):
        name = expr.name
        if name == "negate":
            f = self._compile(expr.args[0])[0]
            return lambda datas, nulls: ((lambda d, n: (-d, n))(*f(datas, nulls)))
        left, right = expr.args
        lf = self._compile(left)[0]
        rf = self._compile(right)[0]
        lt, rt = left.type, right.type
        out = expr.type
        lscale = lt.scale if isinstance(lt, DecimalType) else 0
        rscale = rt.scale if isinstance(rt, DecimalType) else 0
        oscale = out.scale if isinstance(out, DecimalType) else 0

        def fn(datas, nulls):
            ld, ln = lf(datas, nulls)
            rd, rn = rf(datas, nulls)
            n = _combine_nulls(ln, rn)
            if isinstance(out, DecimalType):
                a = ld.astype(jnp.int64)
                b = rd.astype(jnp.int64)
                if name == "multiply":
                    # scales add: (a*10^-ls)*(b*10^-rs) = ab * 10^-(ls+rs)
                    d = a * b
                    if lscale + rscale != oscale:
                        d = d * (10 ** (oscale - lscale - rscale)) if oscale > lscale + rscale \
                            else d // (10 ** (lscale + rscale - oscale))
                    return d, n
                a = a * (10 ** (oscale - lscale))
                b = b * (10 ** (oscale - rscale))
                if name == "add":
                    return a + b, n
                if name == "subtract":
                    return a - b, n
                if name == "modulus":
                    # SQL mod: sign of the DIVIDEND (truncate toward zero)
                    return jnp.sign(a) * (jnp.abs(a) % jnp.abs(b)), n
                raise AssertionError(name)
            if out is DOUBLE or out is REAL:
                a = ld.astype(jnp.float64) / (10 ** lscale) if lscale else ld.astype(jnp.float64)
                b = rd.astype(jnp.float64) / (10 ** rscale) if rscale else rd.astype(jnp.float64)
                d = {"add": a + b, "subtract": a - b, "multiply": a * b,
                     "divide": a / b,
                     "modulus": jnp.sign(a) * (jnp.abs(a) % jnp.abs(b))}[name]
                return d, n
            # integral
            a, b = ld, rd
            if name == "divide":
                d = a.astype(jnp.int64) // jnp.where(b == 0, 1, b)
                # SQL semantics: truncate toward zero (python // floors)
                d = jnp.where((a % b != 0) & ((a < 0) ^ (b < 0)), d + 1, d)
                return d.astype(out.np_dtype), n
            if name == "modulus":
                d = jnp.sign(a) * (jnp.abs(a) % jnp.abs(jnp.where(b == 0, 1, b)))
            else:
                d = {"add": a + b, "subtract": a - b, "multiply": a * b}[name]
            return jnp.asarray(d, dtype=out.np_dtype), n
        return fn

    def _compile_like(self, expr: Call):
        value, pattern = expr.args[0], expr.args[1]
        escape = expr.args[2].value if len(expr.args) > 2 else None
        assert isinstance(pattern, Constant), "LIKE pattern must be a literal"
        d = self._dictionary_of(value)
        vf = self._compile(value)[0]
        pred = _like_to_predicate(pattern.value, escape)

        # PackedWordsDictionary path: %t1%t2%...% ordered-containment patterns
        # lower to a DP over the packed word fields (exact LIKE semantics)
        from ..connectors.tpch.generator import PackedWordsDictionary
        if isinstance(d, PackedWordsDictionary):
            # escaped patterns would need escape-aware tokenization; fall through
            fn = None if escape is not None else _packed_like(d, pattern.value, vf)
            if fn is not None:
                return fn
            # fall through: cannot evaluate analytically
            raise NotImplementedError(f"LIKE {pattern.value!r} on packed column")
        codes = d.codes_where(pred)

        def fn(datas, nulls):
            vd, vn = vf(datas, nulls)
            if len(codes) == 0:
                return jnp.zeros(jnp.shape(vd), jnp.bool_), vn
            if len(codes) <= 64:
                acc = vd == int(codes[0])
                for c in codes[1:]:
                    acc = acc | (vd == int(c))
                return acc, vn
            # large match sets: sorted-membership via searchsorted
            sc = jnp.asarray(np.sort(codes))
            pos = jnp.searchsorted(sc, vd)
            pos = jnp.clip(pos, 0, len(codes) - 1)
            return sc[pos] == vd, vn
        return fn

    def _compile_substr(self, expr: Call):
        # substring on dictionary columns: rewrite dictionary host-side
        value = expr.args[0]
        start = expr.args[1]
        length = expr.args[2] if len(expr.args) > 2 else None
        d = self._dictionary_of(value)
        if d is None or not isinstance(start, Constant) or \
                (length is not None and not isinstance(length, Constant)):
            raise NotImplementedError("substr requires dictionary input + literal bounds")
        if not hasattr(d, "values"):
            # virtual dictionaries (FormattedDictionary) materialize no values
            # array; a synthesized substring rule maps codes to a small real
            # dictionary with pure device arithmetic (e.g. phone country code)
            rule = getattr(d, "substr_rules", {}).get(
                (int(start.value),
                 int(length.value) if length is not None else None))
            if rule is not None:
                nd_, transform = rule
                vf_ = self._compile(value)[0]

                def vfn(datas, nulls):
                    vd, vn = vf_(datas, nulls)
                    return transform(vd).astype(jnp.int32), vn
                return vfn, nd_
            raise NotImplementedError(
                f"substr over a virtual dictionary ({type(d).__name__}) has no "
                f"synthesized rule for ({start.value}, "
                f"{length.value if length is not None else None})")
        s = int(start.value) - 1
        ln = int(length.value) if length is not None else None
        new_values = [v[s:s + ln] if ln is not None else v[s:] for v in d.values]
        uniq = sorted(set(new_values))
        nd = Dictionary(uniq)
        remap = np.asarray([nd.index()[v] for v in new_values], dtype=np.int32)
        vf = self._compile(value)[0]

        def fn(datas, nulls):
            vd, vn = vf(datas, nulls)
            return jnp.asarray(remap)[vd], vn
        return fn, nd


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _packed_like(d, pattern: str, vf):
    """LIKE over a PackedWordsDictionary column, exactly, without materializing
    strings: patterns of the form %t1%t2%...% (tokens free of '%'/'_'/separator)
    are an ordered-substring-containment test, evaluated as a dynamic program
    over the packed word fields. State s = "first s tokens matched"; a field's
    word can advance s->e when tokens[s:e] appear in order inside that word
    (a single word may satisfy several consecutive tokens). Word-id -> advance
    lookup tables are precomputed host-side; the device side is n_fields gathers.
    Returns None when the pattern is not of this shape (caller falls back)."""
    if "_" in pattern:
        return None
    anchored_start = not pattern.startswith("%")
    anchored_end = not pattern.endswith("%")
    tokens = [t for t in pattern.split("%") if t != ""]
    if not tokens or any(d.sep in t for t in tokens):
        return None
    k = len(tokens)

    def contains_seq(word: str, toks, at_start=False, at_end=False) -> bool:
        """tokens appear in order within the word; optionally the first must
        start at position 0 / the last must end at the word's end."""
        pos = 0
        for i, t in enumerate(toks):
            if i == 0 and at_start:
                if not word.startswith(t):
                    return False
                j = 0
            else:
                j = word.find(t, pos)
                if j < 0:
                    return False
            pos = j + len(t)
        if at_end and toks:
            last = toks[-1]
            # re-find the last token as far right as possible after the prior ones
            prior_end = 0
            for t in toks[:-1]:
                prior_end = word.find(t, prior_end) + len(t)
            return word.endswith(last) and word.rfind(last) >= prior_end
        return True

    # advance tables: word moves the DP from "s tokens matched" to "e matched".
    # Anchored variants pin the first/last token to the word's boundary; an
    # anchored start additionally restricts matching to field 0.
    tables = {}
    for s in range(k):
        for e in range(s + 1, k + 1):
            a_s = anchored_start and s == 0
            a_e = anchored_end and e == k
            tables[(s, e, a_s, a_e)] = np.asarray(
                [contains_seq(w, tokens[s:e], a_s, a_e) for w in d.words],
                dtype=bool)
    bits, nf, nw = d.BITS, d.n_fields, len(d.words)

    def fn(datas, nulls):
        vd, vn = vf(datas, nulls)
        c = vd.astype(jnp.int64)
        shape = jnp.shape(c)
        states = [jnp.ones(shape, jnp.bool_)] + \
                 [jnp.zeros(shape, jnp.bool_) for _ in range(k)]
        for f in range(nf):
            wid = jnp.clip((c >> (bits * f)) & ((1 << bits) - 1), 0, nw - 1)
            new = list(states)
            for e in range(1, k + 1):
                for s in range(e):
                    a_s = anchored_start and s == 0 and f == 0
                    a_e = anchored_end and e == k
                    if anchored_start and s == 0 and f > 0:
                        continue  # match must begin in field 0
                    if a_e and f != nf - 1:
                        continue  # match must end in the last field
                    hit = jnp.asarray(tables[(s, e, a_s, a_e)])[wid]
                    new[e] = new[e] | (states[s] & hit)
            states = new
            if anchored_start and f == 0:
                states[0] = jnp.zeros(shape, jnp.bool_)
        return states[k], vn
    return fn


def _merge_dicts(a: Optional[Dictionary], b: Optional[Dictionary]) -> Optional[Dictionary]:
    """Output dictionary of a branch merge (IF/SWITCH/COALESCE). Branches that are
    NULL or non-string carry no dictionary; distinct dictionaries would need a
    re-encode pass (not needed by the TPC workloads yet)."""
    if a is None:
        return b
    if b is None:
        return a
    if a is b:
        return a
    raise NotImplementedError(
        "CASE/COALESCE across two distinct dictionaries requires re-encoding")


def _combine_nulls(a: Optional[Array], b: Optional[Array]) -> Optional[Array]:
    if a is None:
        return b
    if b is None:
        return a
    return a | b


def _logic_nulls(is_and: bool, ad, an, bd, bn) -> Optional[Array]:
    """3-valued AND/OR null mask."""
    if an is None and bn is None:
        return None
    ann = an if an is not None else jnp.zeros(jnp.shape(ad), jnp.bool_)
    bnn = bn if bn is not None else jnp.zeros(jnp.shape(bd), jnp.bool_)
    if is_and:
        # null AND true = null; null AND false = false
        return (ann & (bnn | bd)) | (bnn & (ann | ad))
    # null OR false = null; null OR true = true
    return (ann & (bnn | ~bd)) | (bnn & (ann | ~ad))


def _where_nulls(cond, tn, en, shape) -> Optional[Array]:
    if tn is None and en is None:
        return None
    tnn = tn if tn is not None else jnp.zeros(shape, jnp.bool_)
    enn = en if en is not None else jnp.zeros(shape, jnp.bool_)
    return jnp.where(cond, tnn, enn)


def _hash64(x: Array) -> Array:
    """splitmix64 on device (engine hash for repartition/group-by)."""
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return (x ^ (x >> 31)).astype(jnp.int64)


def _civil_from_days(days: Array):
    """days since 1970-01-01 -> (year, month, day). Howard Hinnant's algorithm,
    branch-free — replaces the reference's Joda-time date functions with pure VPU ops
    (operator/scalar/DateTimeFunctions.java)."""
    z = days.astype(jnp.int32) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil_vec(y: Array, m: int, d: int) -> Array:
    """Vectorized inverse of _civil_from_days for a fixed month/day."""
    y = y.astype(jnp.int64) - (1 if m <= 2 else 0)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def days_from_civil(y: int, m: int, d: int) -> int:
    """Host-side inverse for date literals."""
    y -= m <= 2
    era = (y if y >= 0 else y - 399) // 400
    yoe = y - era * 400
    doy = (153 * (m + (-3 if m > 2 else 9)) + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468
