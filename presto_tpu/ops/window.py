"""Window functions on TPU: sort + segmented scans.

Analogue of operator/WindowOperator.java (pagesIndex sort + per-partition
function evaluation) and operator/window/* function implementations.

TPU re-design: the reference walks partitions row-by-row through per-function
accumulators. Here the whole input is ONE sorted-layout problem:

  1. lexsort rows by (partition keys..., order keys...)    — bitonic sorter
  2. partition starts + peer-group starts = adjacent diffs — vector compare
  3. every function is a closed-form gather/scan over that layout:
       row_number   position - partition_start + 1
       rank         peer_start - partition_start + 1
       dense_rank   segmented cumsum of new-peer flags
       agg ROWS     segmented inclusive scan (cumsum / cummin / cummax)
       agg RANGE    the scan value at each row's LAST PEER (peers share frames)
       agg no-order whole-partition total broadcast back
       lag/lead/first_value/last_value   clamped positional gathers
  4. inverse-permute results back to input row order (window functions do not
     reorder rows)

Segmented min/max scans use the segmented-scan monoid over (reset, value)
pairs via lax.associative_scan — O(log n) depth, parallel on the VPU.
"""
from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..block import Block, Dictionary, Page
from ..types import Type
from .operator import Operator, OperatorContext, OperatorFactory, timed
from .sorting import lexsort_fast


def _seg_scan(op: str, values: jnp.ndarray, new_seg: jnp.ndarray) -> jnp.ndarray:
    """Inclusive segmented scan accumulating within segments (reset where
    new_seg is True)."""
    if op == "sum":
        total = jnp.cumsum(values)
        seg_id = jnp.cumsum(new_seg.astype(jnp.int32)) - 1
        starts = jnp.flatnonzero(new_seg, size=values.shape[0], fill_value=0)
        base_at_start = jnp.where(starts > 0,
                                  total[jnp.maximum(starts - 1, 0)],
                                  jnp.zeros((), dtype=total.dtype))
        return total - base_at_start[seg_id]

    def combine(a, b):
        fa, va = a
        fb, vb = b
        merged = jnp.minimum(va, vb) if op == "min" else jnp.maximum(va, vb)
        return fa | fb, jnp.where(fb, vb, merged)
    _, out = jax.lax.associative_scan(combine, (new_seg, values))
    return out


@functools.partial(jax.jit, static_argnames=("calls", "n_keys", "n_ord"))
def _window_kernel(keys, args_and_nulls, mask, calls, n_keys, n_ord):
    """Evaluate every window call of one spec over one sorted layout.

    calls: static tuple of (name, n_args, frame_mode, scale_div). Returns one
    (values, null_mask_or_None) per call, in ORIGINAL row order."""
    n = mask.shape[0]
    sort_cols = tuple(reversed(keys)) + (~mask,)  # dead rows sort last
    order = lexsort_fast(sort_cols)
    inv = jnp.zeros(n, dtype=jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    sm = mask[order]
    skeys = [k[order] for k in keys]

    first = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    new_part = first | (sm != jnp.roll(sm, 1))
    for k in skeys[:n_keys]:
        new_part = new_part | (k != jnp.roll(k, 1))
    new_peer = new_part
    for k in skeys[n_keys:n_keys + n_ord]:
        new_peer = new_peer | (k != jnp.roll(k, 1))

    pos = jnp.arange(n, dtype=jnp.int64)
    part_start = _seg_scan("max", jnp.where(new_part, pos, 0), new_part)
    peer_start = _seg_scan("max", jnp.where(new_peer, pos, 0), new_peer)
    # peer_last[i] = last position of i's peer group: reversed segmented scan
    rev = slice(None, None, -1)
    peer_end_rev = jnp.roll(new_peer, -1).at[-1].set(True)[rev]
    peer_last = _seg_scan("max", jnp.where(peer_end_rev, n - 1 - pos, 0),
                          peer_end_rev)[rev]
    part_end_rev = jnp.roll(new_part, -1).at[-1].set(True)[rev]
    part_last = _seg_scan("max", jnp.where(part_end_rev, n - 1 - pos, 0),
                          part_end_rev)[rev]
    part_id = jnp.cumsum(new_part.astype(jnp.int64)) - 1

    outs = []
    ai = 0
    for (name, n_args, frame_mode, scale_div, offset) in calls:
        cargs = args_and_nulls[ai: ai + 2 * n_args]
        ai += 2 * n_args
        if name == "row_number":
            outs.append(((pos - part_start + 1)[inv], None))
            continue
        if name == "rank":
            outs.append(((peer_start - part_start + 1)[inv], None))
            continue
        if name == "dense_rank":
            vals = _seg_scan("sum", new_peer.astype(jnp.int64), new_part)
            outs.append((vals[inv], None))
            continue
        if name == "ntile":
            # first (s mod b) buckets get ceil(s/b) rows (spec 6.10 NTILE)
            s = part_last - part_start + 1
            r = pos - part_start
            b = jnp.int64(offset)
            q, rem = s // b, s % b
            big = rem * (q + 1)
            vals = jnp.where(
                q == 0, r + 1,
                jnp.where(r < big, r // jnp.maximum(q + 1, 1) + 1,
                          rem + (r - big) // jnp.maximum(q, 1) + 1))
            outs.append((vals[inv], None))
            continue
        if name == "percent_rank":
            s = part_last - part_start + 1
            vals = (peer_start - part_start).astype(jnp.float64) / \
                jnp.maximum(s - 1, 1).astype(jnp.float64)
            outs.append((vals[inv], None))
            continue
        if name == "cume_dist":
            s = part_last - part_start + 1
            vals = (peer_last - part_start + 1).astype(jnp.float64) / \
                s.astype(jnp.float64)
            outs.append((vals[inv], None))
            continue
        if name == "nth_value":
            v = cargs[0][order]
            vn = cargs[1][order] if cargs[1] is not None else None
            target = part_start + jnp.int64(offset - 1)
            frame_end = peer_last if frame_mode == "range" else pos
            oob = target > frame_end  # beyond frame (incl. beyond partition)
            clipped = jnp.clip(target, 0, n - 1)
            vals = v[clipped]
            nul = oob if vn is None else (vn[clipped] | oob)
            outs.append((vals[inv], nul[inv]))
            continue
        if name in ("lag", "lead", "first_value", "last_value"):
            v = cargs[0][order]
            vn = cargs[1][order] if cargs[1] is not None else None
            if name == "first_value":
                src = part_start
                oob = jnp.zeros(n, dtype=jnp.bool_)
            elif name == "last_value":
                # RANGE frame ends at the last peer; ROWS at the current row
                src = peer_last if frame_mode == "range" else pos
                oob = jnp.zeros(n, dtype=jnp.bool_)
            else:
                shift = jnp.int64(offset if name == "lag" else -offset)
                src = pos - shift
                clipped = jnp.clip(src, 0, n - 1)
                oob = (src < 0) | (src > n - 1) | \
                    (part_id[clipped] != part_id)
                src = clipped
            vals = v[src]
            nul = oob if vn is None else (vn[src] | oob)
            outs.append((vals[inv], nul[inv]))
            continue
        # aggregates: count/sum/min/max/avg
        if n_args == 0:  # count(*)
            live = sm
            contrib = sm.astype(jnp.int64)
        else:
            v = cargs[0][order]
            vn = cargs[1][order] if cargs[1] is not None else None
            live = sm if vn is None else (sm & ~vn)
            contrib = v
        live_i = live.astype(jnp.int64)
        if name in ("count", "sum", "avg"):
            c = contrib.astype(jnp.int64) if name == "count" else contrib
            c = jnp.where(live, c, jnp.zeros((), dtype=c.dtype))
            if n_ord == 0:
                pid32 = part_id.astype(jnp.int32)
                run = jax.ops.segment_sum(c, pid32, num_segments=n)[part_id]
                nrun = jax.ops.segment_sum(live_i, pid32,
                                           num_segments=n)[part_id]
            else:
                run = _seg_scan("sum", c, new_part)
                nrun = _seg_scan("sum", live_i, new_part)
                if frame_mode == "range":
                    run, nrun = run[peer_last], nrun[peer_last]
            if name == "count":
                outs.append((nrun[inv] if n_args else run[inv], None))
            elif name == "avg":
                vals = run.astype(jnp.float64) / \
                    (jnp.maximum(nrun, 1) * scale_div)
                outs.append((vals[inv], (nrun == 0)[inv]))
            else:
                outs.append((run[inv], (nrun == 0)[inv]))
        else:  # min / max
            ident = _identity_for(name, contrib.dtype)
            c = jnp.where(live, contrib, ident)
            if n_ord == 0:
                pid32 = part_id.astype(jnp.int32)
                seg = jax.ops.segment_min if name == "min" \
                    else jax.ops.segment_max
                run = seg(c, pid32, num_segments=n)[part_id]
                nrun = jax.ops.segment_sum(live_i, pid32,
                                           num_segments=n)[part_id]
            else:
                run = _seg_scan(name, c, new_part)
                nrun = _seg_scan("sum", live_i, new_part)
                if frame_mode == "range":
                    run, nrun = run[peer_last], nrun[peer_last]
            outs.append((run[inv], (nrun == 0)[inv]))
    return tuple(outs)


def _identity_for(name: str, dtype) -> jnp.ndarray:
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if name == "min" else info.min, dtype=dtype)
    return jnp.asarray(jnp.inf if name == "min" else -jnp.inf, dtype=dtype)


@jax.jit
def _order_encode_float(v):
    """Order-preserving int64 encode of float64 (IEEE bit trick)."""
    bits = jax.lax.bitcast_convert_type(v.astype(jnp.float64), jnp.int64)
    return jnp.where(bits < 0, jnp.int64(np.int64(-1)) ^ bits | jnp.int64(
        np.int64(1) << 63), bits)


class WindowOperator(Operator):
    """Buffering operator: collect ALL input (windows are global), evaluate at
    finish with one kernel, emit one combined page in input row order."""

    def __init__(self, context: OperatorContext, f: "WindowOperatorFactory"):
        super().__init__(context)
        self.f = f
        self._pages: List[Page] = []       # device-resident
        self._host_pages: List[Page] = []  # revoked to host RAM
        self._results: Optional[List[Page]] = None

    @property
    def output_types(self) -> List[Type]:
        return self.f.output_types

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        self._pages.append(page)
        self.context.update_revocable(self.revocable_bytes(),
                                      self.start_memory_revoke)

    # buffered input participates in the revoke protocol like the other
    # accumulating operators: offload to host, re-uploaded at compute
    def revocable_bytes(self) -> int:
        total = 0
        for p in self._pages:
            rows = p.capacity
            total += rows
            for b in p.blocks:
                total += rows * np.dtype(b.data.dtype).itemsize
                if b.nulls is not None:
                    total += rows
        return total

    def start_memory_revoke(self) -> None:
        self._host_pages.extend(jax.device_get(p) for p in self._pages)
        self._pages = []
        self.context.revocable_memory.set_bytes(0)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        self._pages = self._host_pages + self._pages
        self._host_pages = []
        self._results = self._compute()
        self._pages = []
        self.context.revocable_memory.set_bytes(0)

    def _concat(self) -> Page:
        if len(self._pages) == 1:
            return self._pages[0]
        ncols = len(self._pages[0].blocks)
        blocks = []
        for c in range(ncols):
            b0 = self._pages[0].blocks[c]
            data = jnp.concatenate([p.blocks[c].data for p in self._pages])
            if any(p.blocks[c].nulls is not None for p in self._pages):
                nulls = jnp.concatenate([p.blocks[c].null_mask()
                                         for p in self._pages])
            else:
                nulls = None
            blocks.append(Block(b0.type, data, nulls, b0.dictionary))
        mask = jnp.concatenate([p.mask for p in self._pages])
        return Page(tuple(blocks), mask)

    def _compute(self) -> List[Page]:
        if not self._pages:
            return []
        page = self._concat()
        f = self.f
        keys = []
        for ch in f.partition_channels:
            keys.append(self._sort_key(page.blocks[ch], False, False))
        for o in f.orderings:
            keys.append(self._sort_key(page.blocks[o.channel], o.descending,
                                       o.nulls_first))
        args_and_nulls = []
        # min/max over a dict-encoded varchar must order by dictionary RANK,
        # not code; compute in rank space and map the result back to codes
        unrank: List[Optional[jnp.ndarray]] = []
        for (name, arg_chs, _fm, _sd, _off) in f.call_channels:
            post = None
            for i, ch in enumerate(arg_chs):
                b = page.blocks[ch]
                data = b.data
                if i == 0 and name in ("min", "max") and \
                        b.dictionary is not None and hasattr(b.dictionary,
                                                             "values"):
                    ranks = jnp.asarray(b.dictionary.sort_keys())
                    data = ranks[b.data]
                    post = jnp.argsort(ranks)  # rank -> code
                args_and_nulls.append(data)
                args_and_nulls.append(b.nulls)
            unrank.append(post)
        outs = _window_kernel(tuple(keys), tuple(args_and_nulls), page.mask,
                              tuple(f.call_channels_static()),
                              len(f.partition_channels), len(f.orderings))
        blocks = list(page.blocks)
        for (vals, nulls), (t_, d_), post in zip(outs, f.call_meta, unrank):
            if post is not None:
                safe = jnp.clip(vals, 0, post.shape[0] - 1)
                vals = post[safe.astype(jnp.int32)]
            blocks.append(Block(t_, vals.astype(t_.np_dtype), nulls, d_))
        out = Page(tuple(blocks), page.mask)
        self.context.record_output(out, out.capacity)
        return [out]

    @staticmethod
    def _sort_key(block: Block, descending: bool, nulls_first: bool):
        """Order-preserving int64 encode of a column incl. null placement
        (dictionary varchar orders by rank, floats by the IEEE bit trick)."""
        d = block.dictionary
        if d is not None and hasattr(d, "values"):
            v = jnp.asarray(d.sort_keys())[block.data].astype(jnp.int64)
        elif d is not None and not getattr(d, "monotonic", False):
            raise NotImplementedError(
                f"window ordering over non-monotonic virtual dictionary {d!r}")
        elif jnp.issubdtype(jnp.asarray(block.data).dtype, jnp.floating):
            v = _order_encode_float(block.data)
        else:
            v = block.data.astype(jnp.int64)
        if descending:
            v = -v
        if block.nulls is not None:
            big = jnp.int64(np.iinfo(np.int64).max - 1)
            v = jnp.where(block.nulls, -big if nulls_first else big, v)
        return v

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._results:
            return self._results.pop(0)
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._results


class WindowOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, partition_channels: List[int],
                 orderings: List,
                 call_channels: List[Tuple[str, List[int], str, int, int]],
                 call_meta: List[Tuple[Type, Optional[Dictionary]]],
                 input_types: List[Type]):
        super().__init__(operator_id, "Window")
        self.partition_channels = partition_channels
        self.orderings = orderings      # [SortOrder(channel, desc, nulls_first)]
        # [(fn name, arg channels, frame mode, decimal scale divisor, offset)]
        self.call_channels = call_channels
        self.call_meta = call_meta
        self.output_types = list(input_types) + [t for t, _ in call_meta]

    def call_channels_static(self):
        return [(name, len(chs), fm, sd, off)
                for (name, chs, fm, sd, off) in self.call_channels]

    def create_operator(self, worker: int = 0) -> WindowOperator:
        return WindowOperator(self.context(worker), self)
