"""Page coalescing after selective filters: dense pages for downstream ops.

The engine's pages are FIXED-capacity device arrays with row masks; a
selective fused filter-scan emits pages whose live rows are a small
fraction of capacity, and every downstream operator (join probe, hash
aggregation) still pays full-capacity kernel work per page. This operator
COMPACTS each input page on device and packs live rows into an
accumulator, emitting only FULL pages (plus one tail) — the reference's
PageProcessor output coalescing / MergePages.java, re-shaped for static
XLA shapes:

- compact: one scatter per page (block._compact), XLA-fused;
- pack: `lax.dynamic_update_slice` at the accumulator's live count — a
  dynamic OFFSET is fine under jit (shapes stay static);
- overflow: concat(acc, incoming)[:C] emits, [C:] is the new accumulator —
  all static shapes, one compiled kernel per schema.

Downstream work drops by the filter's selectivity (a 0.02-selective Q6
scan feeds ~50x fewer pages into the aggregation), and on the remote-
tunnel TPU each page saved is a dispatch round-trip saved.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp

from ..block import Block, Page, _compact
from ..types import Type
from .operator import Operator, OperatorContext, OperatorFactory, timed


@functools.partial(jax.jit, donate_argnums=())
def _pack(acc: Page, count, page: Page):
    """(accumulator, live count, compacted incoming) ->
    (emit page, emit flag, new accumulator, new count).

    The incoming page is already compacted (live rows in prefix). Result
    shapes are static: emit is capacity C; the combined view is 2C wide."""
    cap = acc.capacity
    n_in = jnp.sum(page.mask.astype(jnp.int32))

    def combine(a, b):
        return jnp.concatenate([a, b])

    blocks = []
    for ab, pb in zip(acc.blocks, page.blocks):
        # place incoming prefix at offset `count` inside a 2C scratch
        scratch = combine(ab.data, jnp.zeros_like(pb.data))
        scratch = jax.lax.dynamic_update_slice(
            scratch, pb.data, (count,))
        nulls = None
        if ab.nulls is not None or pb.nulls is not None:
            ns = combine(ab.null_mask(), jnp.zeros_like(pb.null_mask()))
            ns = jax.lax.dynamic_update_slice(ns, pb.null_mask(), (count,))
            nulls = ns
        blocks.append((scratch, nulls, ab))
    total = count + n_in
    emit = total >= cap
    # emit the first C rows; the remainder [C:2C) becomes the accumulator
    out_blocks = []
    rest_blocks = []
    for scratch, nulls, ab in blocks:
        out_blocks.append(Block(ab.type, scratch[:cap],
                                None if nulls is None else nulls[:cap],
                                ab.dictionary))
        # when not emitting, the accumulator keeps the packed prefix
        keep = jnp.where(emit, scratch[cap:], scratch[:cap])
        kn = None
        if nulls is not None:
            kn = jnp.where(emit, nulls[cap:], nulls[:cap])
        rest_blocks.append(Block(ab.type, keep, kn, ab.dictionary))
    idx = jnp.arange(cap, dtype=jnp.int32)
    out_mask = idx < jnp.minimum(total, cap)
    new_count = jnp.where(emit, total - cap, total)
    rest_mask = idx < new_count
    return (Page(tuple(out_blocks), out_mask), emit,
            Page(tuple(rest_blocks), rest_mask), new_count)


class CoalesceOperator(Operator):
    def __init__(self, context: OperatorContext, types: List[Type], dicts):
        super().__init__(context)
        self._types = types
        self._dicts = dicts
        self._acc: Optional[Page] = None
        self._count = None
        self._pending: List[Page] = []
        self._flushed = False

    @property
    def output_types(self) -> List[Type]:
        return self._types

    def needs_input(self) -> bool:
        return not self._finishing and not self._pending

    #: live fraction above which packing cannot pay for itself
    PASSTHROUGH_SELECTIVITY = 0.5

    _mode = None  # None (undecided) | "pack" | "pass"

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        if self._mode == "pass":
            self._pending.append(page)
            return
        if self._mode is None:
            # adapt on the FIRST page: an unselective filter makes packing
            # pure overhead, so switch to permanent pass-through (per-scan
            # selectivity is stationary — one decision suffices). The sync
            # below runs once per stream, not per page — and through numpy,
            # so the decision compiles no throwaway XLA kernels.
            import numpy as np

            mask_np = np.asarray(page.mask)  # prestocheck: ignore[host-sync]
            if mask_np.mean() > self.PASSTHROUGH_SELECTIVITY:
                self._mode = "pass"
                self._pending.append(page)
                return
            self._mode = "pack"
            self._first_count = int(mask_np.sum())
        compacted = _compact(page)
        if self._acc is not None and \
                self._acc.capacity != compacted.capacity:
            # sources with per-chunk capacities (parquet/orc clamp to the
            # chunk's pow2 bucket) change shape mid-stream: flush the
            # accumulator as a partial page and restart at the new capacity
            self._pending.append(self._acc)
            self._acc = None
        if self._acc is None:
            self._acc = compacted
            # host int (counted during the mode decision) — _pack takes it
            # as a traced argument either way, and the eager jnp.sum here
            # compiled two throwaway kernels per schema
            import numpy as np

            count = getattr(self, "_first_count", None)
            if count is None:  # capacity-change restart mid-stream
                count = int(np.asarray(  # prestocheck: ignore[host-sync]
                    compacted.mask).sum())
            self._first_count = None
            self._count = np.int32(count)
            return
        out, emit, rest, new_count = _pack(self._acc, self._count, compacted)
        self._acc, self._count = rest, new_count
        # host sync on the 4-byte flag only; the page stays on device
        if bool(emit):
            self._pending.append(out)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._pending:
            page = self._pending.pop(0)
            self.context.record_output(page, page.capacity)
            return page
        if self._finishing and not self._flushed:
            self._flushed = True
            if self._acc is not None:
                tail = self._acc
                self._acc = None
                self.context.record_output(tail, tail.capacity)
                return tail
        return None

    def is_finished(self) -> bool:
        return self._finishing and self._flushed and not self._pending


class CoalesceOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, types: List[Type], dicts=None):
        super().__init__(operator_id, "Coalesce")
        self.types = types
        self.dicts = dicts or [None] * len(types)

    def create_operator(self, worker: int = 0) -> CoalesceOperator:
        return CoalesceOperator(self.context(worker), self.types, self.dicts)


class DictionaryRemapOperator(Operator):
    """Re-encode dictionary codes through per-channel remap arrays (the
    UNION dictionary-unification pass: minority branches map their codes
    into the union dictionary on device, one gather per column)."""

    def __init__(self, context: OperatorContext, types: List[Type], remaps,
                 target_dicts=None):
        super().__init__(context)
        self._types = types
        self._remaps = [None if r is None else jnp.asarray(r)
                        for r in remaps]
        self._target_dicts = target_dicts or [None] * len(types)
        self._pending: List[Page] = []

    @property
    def output_types(self) -> List[Type]:
        return self._types

    def needs_input(self) -> bool:
        return not self._finishing and not self._pending

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        blocks = []
        for b, r in zip(page.blocks, self._remaps):
            # explicit None test: a virtual FormattedDictionary has len 0
            # and would be dropped by a truthiness check
            td = self._target_dicts[len(blocks)]
            if td is None:
                td = b.dictionary
            if r is None:
                # no code translation needed, but the block must still
                # carry the UNION dictionary: downstream page merges take
                # the FIRST block's dictionary, and a null-branch block
                # with none would strip decoding from the whole column
                if td is b.dictionary:
                    blocks.append(b)
                else:
                    blocks.append(Block(b.type, b.data, b.nulls, td))
            else:
                data = jnp.take(r, jnp.clip(b.data.astype(jnp.int32), 0,
                                            r.shape[0] - 1))
                blocks.append(Block(b.type, data, b.nulls, td))
        self._pending.append(Page(tuple(blocks), page.mask))

    @timed("get_output_ns")
    def get_output(self):
        if self._pending:
            page = self._pending.pop(0)
            self.context.record_output(page, page.capacity)
            return page
        return None

    def is_finished(self) -> bool:
        return self._finishing and not self._pending


class DictionaryRemapOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, types: List[Type], remaps,
                 target_dicts=None):
        super().__init__(operator_id, "DictionaryRemap")
        self.types = types
        self.remaps = remaps
        self.target_dicts = target_dicts

    def create_operator(self, worker: int = 0) -> DictionaryRemapOperator:
        return DictionaryRemapOperator(self.context(worker), self.types,
                                       self.remaps, self.target_dicts)
