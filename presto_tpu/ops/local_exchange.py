"""Local exchange: intra-task page hand-off between pipelines.

Analogue of operator/exchange/LocalExchange.java:52 (+ LocalExchangeSink/
SourceOperator): N producer drivers push pages into a shared buffer; M
consumer drivers pull. This is what lets ONE pipeline run as SEVERAL drivers
(intra-pipeline driver parallelism, reference parallelism axis #4) with the
stateful tail running downstream of the exchange.

TPU framing: the payload is device-array pages — the exchange moves HANDLES,
never data; its job is scheduling (overlapping several scans' host
generation/upload against the consumer's device compute), not transport.

The buffer is unbounded by design: callers that drive pipelines sequentially
(tests, the mesh runner's per-fragment loops) must never deadlock on a full
buffer; device memory stays bounded by the scan prefetch depth upstream and
the consumer draining concurrently under the task executor in the live
paths."""
from __future__ import annotations

import threading
from typing import List, Optional

from ..block import Page
from ..types import Type
from .operator import Operator, OperatorContext, OperatorFactory, timed


class LocalExchangeBuffer:
    """Shared page queue with producer completion tracking.

    `max_pages` > 0 bounds the queue (the reference LocalExchange's
    maxBufferedBytes analogue): producers observe `has_room` and park as
    BLOCKED until the consumer drains. `max_bytes` > 0 bounds by PAYLOAD
    size instead (the streaming mesh exchange's consumer queues — byte
    bounds let depth adapt to page size, exactly like the scan pipeline's
    prefetch budget); a put into an EMPTY buffer always succeeds so one
    oversized page can never wedge the stream. The bound is only enabled
    when the pipelines run under the task executor — a sequentially-driven
    producer with no concurrent consumer must never deadlock on a full
    buffer.

    ``poison(exc)`` routes a producer-side failure (or a teardown while
    consumers are still blocked) to every consumer: blocked parties wake and
    the next ``poll``/blocking ``put`` raises instead of reporting a
    silently truncated stream."""

    def __init__(self, n_producers: int, max_pages: int = 0,
                 deal_slots: int = 0, max_bytes: int = 0):
        self._pages: List[Page] = []
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._open_producers = n_producers
        self.max_pages = max_pages
        self.max_bytes = max_bytes
        self.rows_in = 0
        self._bytes = 0
        self._poison: Optional[BaseException] = None
        self._abandoned = False
        # deal_slots > 0: pages are DEALT round-robin to that many consumer
        # slots instead of work-stolen from one shared list — the
        # reference's unpartitioned writer exchange, where every scaled
        # writer must receive a share regardless of scheduling order (a
        # fast prefetching producer would otherwise let early consumers
        # drain everything before late ones start)
        self.deal_slots = deal_slots
        self._dealt: List[List[Page]] = [[] for _ in range(deal_slots)]
        self._deal_next = 0

    @staticmethod
    def _page_bytes(page: Page) -> int:
        from .scan_pipeline import page_nbytes
        return page_nbytes(page)

    def put(self, page: Page, block: bool = False) -> None:
        """Append a page; with ``block=True`` wait for room under the byte/
        page bound (poison aborts the wait with the poisoning exception)."""
        with self._cv:
            while block and not self._abandoned and \
                    not self._has_room_locked():
                if self._poison is not None:
                    raise RuntimeError("local exchange buffer poisoned") \
                        from self._poison
                self._cv.wait(timeout=0.05)
            if self._poison is not None and block:
                raise RuntimeError("local exchange buffer poisoned") \
                    from self._poison
            if self._abandoned:
                return  # consumer is gone: accept and discard
            self._enqueue_locked(page)

    def try_put(self, page: Page, wait_s: float = 0.0) -> bool:
        """Bounded-blocking put: enqueue if there is room (waiting at most
        `wait_s` for some), else return False. The streaming exchange's
        shared-pool pump delivers through this so a full consumer queue
        parks the pump STEP, never a pool worker — poison still raises."""
        with self._cv:
            if not self._abandoned and not self._has_room_locked():
                if self._poison is not None:
                    raise RuntimeError("local exchange buffer poisoned") \
                        from self._poison
                if wait_s > 0:
                    self._cv.wait(timeout=wait_s)
            if self._poison is not None:
                raise RuntimeError("local exchange buffer poisoned") \
                    from self._poison
            if self._abandoned:
                return True  # consumer is gone: accept and discard
            if not self._has_room_locked():
                return False
            self._enqueue_locked(page)
            return True

    def _enqueue_locked(self, page: Page) -> None:
        """Shared enqueue tail (caller holds self._cv and has settled the
        poison/abandon/room policy): deal or append, account, wake."""
        if self.deal_slots:
            self._dealt[self._deal_next].append(page)
            self._deal_next = (self._deal_next + 1) % self.deal_slots
        else:
            self._pages.append(page)
        if self.max_bytes > 0:
            # byte accounting only for byte-bounded buffers: the
            # page-bounded local exchanges on the driver hot path must
            # not pay a per-page nbytes walk for a counter nobody reads
            self._bytes += self._page_bytes(page)
        self._cv.notify_all()

    def _buffered(self) -> int:
        return len(self._pages) + sum(len(d) for d in self._dealt)

    def _has_room_locked(self) -> bool:
        if self._buffered() == 0:
            return True
        if self.max_pages > 0 and self._buffered() >= self.max_pages:
            return False
        if self.max_bytes > 0 and self._bytes >= self.max_bytes:
            return False
        return True

    def has_room(self) -> bool:
        if self.max_pages <= 0 and self.max_bytes <= 0:
            return True
        with self._lock:
            return self._has_room_locked()

    def buffered_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def poison(self, exc: BaseException) -> None:
        """Fail every current and future blocked consumer/producer."""
        with self._cv:
            if self._poison is None:
                self._poison = exc
            self._cv.notify_all()

    def abandon(self) -> None:
        """The (sole) consumer is gone and will never drain: drop buffered
        pages and accept-and-discard future puts so producers can't block on
        a queue nobody reads (an early-finishing LIMIT above an exchange
        must not wedge the producers still streaming into it). Only valid
        for single-consumer buffers — the streaming exchange's per-worker
        queues; a shared work-stealing buffer must NOT be abandoned on one
        consumer's close."""
        with self._cv:
            self._abandoned = True
            self._pages.clear()
            for d in self._dealt:
                d.clear()
            self._bytes = 0
            self._cv.notify_all()

    def producer_finished(self) -> None:
        with self._cv:
            self._open_producers -= 1
            self._cv.notify_all()

    def poll(self, slot: Optional[int] = None) -> Optional[Page]:
        with self._cv:
            if self._poison is not None:
                raise RuntimeError("local exchange buffer poisoned") \
                    from self._poison
            pages = self._dealt[slot] if slot is not None else self._pages
            if pages:
                page = pages.pop(0)
                if self.max_bytes > 0:
                    self._bytes = max(0,
                                      self._bytes - self._page_bytes(page))
                self._cv.notify_all()
                return page
            return None

    def is_done(self, slot: Optional[int] = None) -> bool:
        with self._lock:
            if self._poison is not None:
                return False  # poll must run (and raise) — never "done"
            pages = self._dealt[slot] if slot is not None else self._pages
            return not pages and self._open_producers <= 0

    def has_output(self, slot: Optional[int] = None) -> bool:
        with self._lock:
            if self._poison is not None:
                return True  # wake blocked consumers so poll raises
            pages = self._dealt[slot] if slot is not None else self._pages
            return bool(pages) or self._open_producers <= 0


class LocalExchangeSink(Operator):
    """Tail of a producer driver: pages go into the shared buffer."""

    def __init__(self, context: OperatorContext, buffer: LocalExchangeBuffer,
                 types: List[Type]):
        super().__init__(context)
        self.buffer = buffer
        self._types = types
        self._closed_buffer = False

    @property
    def output_types(self) -> List[Type]:
        return self._types

    def needs_input(self) -> bool:
        return super().needs_input() and self.buffer.has_room()

    def is_blocked(self):
        if self.buffer.has_room():
            return None
        return self.buffer.has_room  # poll-able: consumer drain frees a slot

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        self.buffer.put(page)

    def get_output(self) -> Optional[Page]:
        return None

    def finish(self) -> None:
        if not self._finishing and not self._closed_buffer:
            self.buffer.producer_finished()
            self._closed_buffer = True
        super().finish()

    def close(self) -> None:
        self.finish()
        super().close()

    def is_finished(self) -> bool:
        return self._finishing


class LocalExchangeSource(Operator):
    """Head of the consumer driver: pulls from the shared buffer; blocked
    while producers are still running and no page is ready."""

    def __init__(self, context: OperatorContext, buffer: LocalExchangeBuffer,
                 types: List[Type], slot: Optional[int] = None):
        super().__init__(context)
        self.buffer = buffer
        self._types = types
        self._slot = slot  # dealt-mode consumer slot; None = work stealing
        self._ready = lambda: buffer.has_output(slot)

    @property
    def output_types(self) -> List[Type]:
        return self._types

    def needs_input(self) -> bool:
        return False

    def add_input(self, page: Page) -> None:
        raise RuntimeError("local exchange source takes no input")

    def is_blocked(self):
        if self.buffer.has_output(self._slot):
            return None
        return self._ready  # poll-able future

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        page = self.buffer.poll(self._slot)
        if page is not None:
            self.context.record_output(page, page.capacity)
        return page

    def is_finished(self) -> bool:
        return self._finishing or self.buffer.is_done(self._slot)


class LocalExchangeFactory:
    """One per pipeline cut; builds per-worker buffers shared by the sink and
    source factories (a worker's producers feed only that worker's consumer)."""

    def __init__(self, n_producers: int, max_pages: int = 0,
                 deal_slots: int = 0):
        self.n_producers = n_producers
        # soft bound on buffered pages (0 = unbounded): pass e.g.
        # 2 * n_producers when the pipelines run under the task executor so N
        # fast producers cannot grow HBM-resident pages without limit
        self.max_pages = max_pages
        # deal_slots > 0: round-robin dealing to that many consumers (the
        # scaled-writers distribution); 0 = shared-list work stealing
        self.deal_slots = deal_slots
        self._buffers = {}
        self._next_slot = {}
        self._lock = threading.Lock()

    def buffer(self, worker: int) -> LocalExchangeBuffer:
        with self._lock:
            b = self._buffers.get(worker)
            if b is None:
                b = LocalExchangeBuffer(self.n_producers, self.max_pages,
                                        self.deal_slots)
                self._buffers[worker] = b
            return b

    def next_slot(self, worker: int) -> Optional[int]:
        """Dealt-mode consumer slot assignment, in creation order."""
        if not self.deal_slots:
            return None
        with self._lock:
            slot = self._next_slot.get(worker, 0)
            self._next_slot[worker] = (slot + 1) % self.deal_slots
            return slot


class LocalExchangeSinkFactory(OperatorFactory):
    def __init__(self, operator_id: int, exchange: LocalExchangeFactory,
                 types: List[Type]):
        super().__init__(operator_id, "LocalExchangeSink")
        self.exchange = exchange
        self.types = types

    def create_operator(self, worker: int = 0) -> Operator:
        return LocalExchangeSink(self.context(worker),
                                 self.exchange.buffer(worker), self.types)


class LocalExchangeSourceFactory(OperatorFactory):
    def __init__(self, operator_id: int, exchange: LocalExchangeFactory,
                 types: List[Type]):
        super().__init__(operator_id, "LocalExchangeSource")
        self.exchange = exchange
        self.types = types

    def create_operator(self, worker: int = 0) -> Operator:
        return LocalExchangeSource(self.context(worker),
                                   self.exchange.buffer(worker), self.types,
                                   slot=self.exchange.next_slot(worker))
