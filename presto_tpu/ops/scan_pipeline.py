"""Staged host->HBM streaming scan pipeline.

The streaming-scan wall, rebuilt as a pipeline of independent stages
(BENCH_TPU.json: the device kernel sustains ~44M rows/s resident while the
out-of-core pcol stream delivered 1.28M rows/s — the host side, not the
chip, was the bottleneck; `hostgen_stall_s` dominated the wall):

    split readers (pool) -> ordered staging -> re-batch -> upload -> compute
    mmap + slice + remap    bytes-bounded      take_rows    async     driver
    N workers               reorder buffer     pow2 pages   device_put

- READ: a source that can decompose itself into row-range splits
  (``ConnectorPageSource.split_readers``) is read by a pool of reader
  workers concurrently — pcol chunk slicing is embarrassingly parallel
  (the header carries per-chunk offsets). Sources without split support
  run as ONE reader streaming their pages through the same machinery;
  either way this replaces the old one-thread-per-source ``_Prefetcher``.
- ORDER: decoded chunks enter a reorder buffer keyed ``(reader, seq)``;
  the decode stage consumes them in split order, so the pipeline's output
  rows are identical to the serial reader's. Backpressure is by in-flight
  BYTES, not item count, so prefetch depth adapts to chunk size. The chunk
  the decode stage needs next always bypasses a full budget — readers
  completing out of order can therefore never deadlock the pipeline.
- RE-BATCH: chunks accumulate through ``utils/batching.take_rows`` and
  leave as fixed target-row pages (pow2-clamped tail, masked), so device
  kernels see a handful of large static shapes — device occupancy stays
  high regardless of source file layout, and the XLA shape set (hence
  first-run compile count) stays small.
- UPLOAD: a dedicated stage issues the (async) ``jax.device_put`` ahead of
  the consumer, bounded by the same byte budget applied to uploaded pages
  the driver has not consumed yet.

Scheduling: every stage is written as a GENERATOR whose each step performs
one bounded unit of work (one chunk read / one re-batch / one upload) and
whose blocking points wait at most ``shared_pools.STEP_WAIT_S`` before
yielding. Under the default ``shared_pools`` session knob the generators run
on the process-wide :data:`~presto_tpu.exec.shared_pools.SCAN_POOL` —
N concurrent queries share O(pool) threads with per-query round-robin
fairness; with ``shared_pools=False`` the same generators run on per-query
dedicated threads (the differential-testing oracle, and the pre-serving
behavior bit-for-bit).

Memory: when the planner hands the pipeline a per-query memory context, the
staged + uploaded-unconsumed bytes are accounted as user memory — prefetch
competes with operator state in the query's pool, the cluster OOM killer
sees the whole footprint, and a query whose prefetch blows its budget FAILS
(the limit exception propagates to the consumer) instead of wedging.

Every stage accounts busy/stall seconds into ``utils/metrics.METRICS``
(``scan.pipeline.*``) and into a per-pipeline ``stats()`` dict that the
runner surfaces through ``QueryResult.stats`` — bench rounds attribute the
wall clock to a stage instead of guessing.
"""
from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from ..block import Block, Page
from ..exec.shared_pools import AGAIN, SCAN_POOL, STEP_WAIT_S, WAIT
from ..utils import trace
from ..utils.batching import clamp_capacity, take_rows
from ..utils.metrics import METRICS

_DONE = object()   # per-reader end-of-stream marker in the reorder buffer
_EOS = object()    # pipeline end-of-stream on the output queue
_ERR = object()    # error marker on the output queue: (_ERR, exception)

# engine defaults, the single source of truth for every construction path
# (session properties 0/None mean "use these")
DEFAULT_PREFETCH_BYTES = 256 << 20
DEFAULT_READER_THREADS = min(8, os.cpu_count() or 4)
# close()/stat waiters re-check at this cadence while parked
_WAIT_S = 0.1

_STAGE_KEYS = ("read_busy_s", "read_stall_s", "decode_busy_s",
               "decode_stall_s", "upload_busy_s", "upload_stall_s",
               "compute_stall_s")
_COUNT_KEYS = ("chunks", "pages", "rows", "bytes")

# flight-recorder noise floor for STALL spans: sub-100us waits are scheduler
# jitter, not attribution-worthy events (busy spans always record)
_TRACE_STALL_NS = 100_000


def page_nbytes(page: Page) -> int:
    n = page.mask.nbytes
    for b in page.blocks:
        n += b.data.nbytes + (b.nulls.nbytes if b.nulls is not None else 0)
    return n


@dataclasses.dataclass
class HostChunk:
    """Decoded rows of one split: compacted (live-only) host column arrays.

    The unit flowing reader -> re-batcher. ``nulls[i]`` is None when the
    contributing range declared no null mask for column i; the re-batcher
    materializes all-false masks only while a null-bearing chunk is pending.
    """

    cols: List[np.ndarray]
    nulls: List[Optional[np.ndarray]]
    types: Sequence
    dicts: Sequence
    rows: int
    nbytes: int

    @staticmethod
    def build(cols, nulls, types, dicts, rows: Optional[int] = None
              ) -> "HostChunk":
        if rows is None:
            rows = len(cols[0]) if cols else 0
        nbytes = sum(int(c.nbytes) for c in cols) + \
            sum(int(n.nbytes) for n in nulls if n is not None)
        return HostChunk(list(cols), list(nulls), list(types), list(dicts),
                         int(rows), nbytes)


class Rebatcher:
    """Accumulate decoded chunks; emit canonical device-shaped host pages.

    Full pages are exactly ``target_rows`` (all-true mask); the stream tail
    is clamped to its pow2 capacity bucket (utils/batching.clamp_capacity)
    with the usual padding mask. Chunk schema (types/dicts) is pinned by
    the first chunk — split readers of one table share table-wide
    dictionaries by construction.
    """

    def __init__(self, target_rows: int):
        assert target_rows > 0
        self.target = int(target_rows)
        self._pend: List[List[np.ndarray]] = []
        self._rows = 0
        self._ncols: Optional[int] = None
        self._types: Optional[list] = None
        self._dicts: Optional[list] = None
        self._has_nulls: List[bool] = []
        # null masks are materialized LAZILY: a null-free stream (the common
        # TPC-H case) never allocates or concatenates them; the first
        # null-bearing chunk switches the layout on and backfills zeros
        self._nulls_on = False

    @property
    def pending_rows(self) -> int:
        return self._rows

    def add(self, chunk: HostChunk) -> List[tuple]:
        """-> [(host Page, nbytes, rows)] full batches ready to upload."""
        if chunk.rows == 0:
            return []
        if self._ncols is None:
            self._ncols = len(chunk.cols)
            self._types = list(chunk.types)
            self._dicts = list(chunk.dicts)
            self._has_nulls = [False] * self._ncols
        for i, nl in enumerate(chunk.nulls):
            if nl is not None:
                self._has_nulls[i] = True
        if not self._nulls_on and any(nl is not None for nl in chunk.nulls):
            self._nulls_on = True
            for entry in self._pend:  # backfill pending null-free chunks
                n = len(entry[0])
                entry.extend(np.zeros(n, dtype=bool)
                             for _ in range(self._ncols))
        if self._ncols:
            # one pend entry = cols (then null masks once any column went
            # nullable), so a single take_rows consumes them in lockstep
            entry = [np.asarray(c) for c in chunk.cols]
            if self._nulls_on:
                for nl in chunk.nulls:
                    entry.append(np.asarray(nl) if nl is not None
                                 else np.zeros(chunk.rows, dtype=bool))
            self._pend.append(entry)
        self._rows += chunk.rows
        out = []
        while self._rows >= self.target:
            out.append(self._take(self.target, self.target))
        return out

    def flush(self) -> Optional[tuple]:
        """Emit the stream tail (pow2-clamped capacity), or None if empty."""
        if self._rows == 0:
            return None
        return self._take(self._rows, clamp_capacity(self._rows, self.target))

    def _take(self, rows: int, cap: int) -> tuple:
        if self._ncols:
            arrays = take_rows(self._pend, rows)
        else:  # zero-column scan (count(*) pruned projections): mask only
            arrays = []
        self._rows -= rows
        blocks = []
        for i in range(self._ncols or 0):
            data = arrays[i]
            if len(data) < cap:
                data = np.concatenate(
                    [data, np.zeros(cap - len(data), dtype=data.dtype)])
            nl = None
            if self._nulls_on and self._has_nulls[i]:
                nl = arrays[(self._ncols or 0) + i]
                if len(nl) < cap:
                    nl = np.concatenate(
                        [nl, np.zeros(cap - len(nl), dtype=bool)])
            blocks.append(Block(self._types[i], data, nl, self._dicts[i]))
        mask = np.ones(cap, dtype=bool) if rows == cap \
            else np.arange(cap) < rows
        page = Page(tuple(blocks), mask)
        return page, page_nbytes(page), rows


class ScanPipeline:
    """One page source driven through the staged read->re-batch->upload
    pipeline. ``next()`` is the consumer API (None = exhausted); ``close()``
    stops the stages and waits for every stage step to retire (bounded) so a
    producer mid ``jax.device_put`` can never race interpreter teardown."""

    def __init__(self, source, device=None, *,
                 reader_threads: Optional[int] = None,
                 target_rows: Optional[int] = None,
                 prefetch_bytes: Optional[int] = None,
                 rebatch: bool = True,
                 pool_key: Optional[str] = None,
                 memory=None):
        self._source = source
        self._device = device
        self._target = int(target_rows) if target_rows else 0
        self._max_bytes = max(int(prefetch_bytes or DEFAULT_PREFETCH_BYTES),
                              1)
        # pool_key set: stages run on the process-wide SCAN_POOL under the
        # query's fairness slot; None: per-query dedicated threads (oracle).
        # Sources whose reads block indefinitely on EXTERNAL progress
        # (remote exchange streams, another coordinator) cannot honor the
        # pool's bounded-step contract — one would wedge a pool worker and
        # starve every other query's stages, circularly including the very
        # upstream producers the read waits for — so they always run on
        # dedicated threads regardless of the session knob.
        if getattr(source, "external_wait", False):
            pool_key = None
        self._pool = SCAN_POOL.client(pool_key) if pool_key else None
        # per-query memory context (LocalMemoryContext): staged + uploaded
        # bytes are accounted as user memory so prefetch competes with
        # operator state and the OOM killer sees it; None = unaccounted
        self._memory = memory
        # owning query's flight recorder: dedicated stage threads re-bind it
        # (pool steps re-bind the recorder captured at submit)
        self._recorder = trace.active()
        readers = None
        if rebatch and self._target > 0:
            split = getattr(source, "split_readers", None)
            if split is not None:
                readers = split(self._target)
        if readers is None:
            # no split support: ONE reader streams the source's own pages
            # through the same staged machinery (passthrough, no re-batch)
            self._rebatch = False
            self._readers: List[Callable] = [lambda: iter(source)]
        else:
            self._rebatch = True
            self._readers = list(readers)
        self._n_threads = max(1, min(
            int(reader_threads or DEFAULT_READER_THREADS),
            len(self._readers) or 1))
        self._stop = threading.Event()
        self._cv = threading.Condition()   # reorder buffer + staging budget
        self._buf: dict = {}
        self._staged_bytes = 0
        self._needed = (0, 0)
        self._next_reader = 0
        self._upq: queue.Queue = queue.Queue()  # decode -> upload hand-off
        self._out: queue.Queue = queue.Queue()
        self._ocv = threading.Condition()  # uploaded-but-unconsumed budget
        self._out_bytes = 0
        self._error: Optional[BaseException] = None
        self._stats_lock = threading.Lock()
        self._stats = {k: 0.0 for k in _STAGE_KEYS}
        self._stats.update({k: 0 for k in _COUNT_KEYS})
        self._flushed = False
        self._started = False
        self._live_gens = 0
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------- consumer

    def next(self) -> Optional[Page]:
        """Next uploaded page, or None at end of stream. Blocks (accounted
        as compute_stall_s — the device had nothing to chew on)."""
        if not self._started:
            self._start()
        t0 = time.perf_counter_ns()
        item = self._out.get()
        dt = time.perf_counter_ns() - t0
        self._add("compute_stall_s", dt / 1e9)
        if dt >= _TRACE_STALL_NS:
            trace.record(trace.SCAN, "compute_stall", t0, dt)
        if item is _EOS:
            self._out.put(_EOS)  # keep later next() calls returning None
            self._flush_metrics()
            return None
        if isinstance(item, tuple) and item[0] is _ERR:
            self._out.put(item)  # sticky: re-raise on every later call
            self._flush_metrics()
            raise item[1]
        page, nbytes = item
        with self._ocv:
            self._out_bytes -= nbytes
            self._ocv.notify_all()
        self._account()  # releasing bytes never trips the limit
        return page

    def close(self, timeout_s: float = 2.0) -> None:
        """Stop all stages, drain, and wait for every stage generator to
        retire (bounded wait): a stage blocked on a budget observes the stop
        flag within STEP_WAIT_S and exits; anything wedged in a backend call
        is abandoned (daemon threads / dropped pool steps) rather than
        hanging teardown."""
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        with self._ocv:
            self._ocv.notify_all()
        self._upq.put(_EOS)  # wake an upload stage parked on its queue
        try:  # drain so nothing keeps device pages (HBM) alive
            while True:
                self._out.get_nowait()
        except queue.Empty:
            pass
        deadline = time.perf_counter() + timeout_s  # bound on the WHOLE wait
        with self._cv:
            while self._live_gens > 0:
                left = deadline - time.perf_counter()
                if left <= 0:
                    break
                self._cv.wait(min(left, _WAIT_S))
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.perf_counter()))
        self._threads = [t for t in self._threads if t.is_alive()]
        if self._pool is not None:
            self._pool.release()
            self._pool = None
        if self._memory is not None:
            self._memory.close()  # reservation drops with the prefetch
        self._flush_metrics()

    def stats(self) -> dict:
        with self._stats_lock:
            return {k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in self._stats.items()}

    # --------------------------------------------------------------- stages

    def _start(self) -> None:
        self._started = True
        if not self._readers:
            self._out.put(_EOS)
            return
        gens = [self._reader_gen() for _ in range(self._n_threads)]
        gens.append(self._decode_gen())
        gens.append(self._upload_gen())
        with self._cv:
            self._live_gens = len(gens)
        if self._pool is not None:
            for g in gens:
                self._pool.submit(self._guard(g))
            return
        names = [f"scan-read-{i}" for i in range(self._n_threads)]
        names += ["scan-decode", "scan-upload"]
        for g, name in zip(gens, names):
            t = threading.Thread(target=self._drive,
                                 args=(self._guard(g),), name=name,
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _drive(self, gen) -> None:
        """Dedicated-thread scheduler: the generator's internal bounded
        waits provide the blocking cadence, so draining it step-by-step is
        behaviorally the old thread loop."""
        with trace.bound(self._recorder):
            for _ in gen:
                pass

    def _guard(self, gen):
        """Wrap a stage generator: surface its failure to the consumer and
        retire it from the live count (what close() waits on)."""
        try:
            yield from gen
        except BaseException as e:  # noqa: BLE001 - surfaced to the consumer
            self._fail(e)
        finally:
            with self._cv:
                self._live_gens -= 1
                self._cv.notify_all()

    def _add(self, key: str, value) -> None:
        with self._stats_lock:
            self._stats[key] += value

    def _account(self) -> None:
        """Publish staged + uploaded-unconsumed bytes into the query memory
        context. Raises the pool's limit exception when over budget — the
        stage guard routes it to the consumer, so an over-prefetching query
        dies loudly instead of wedging."""
        m = self._memory
        if m is None:
            return
        with self._stats_lock:
            m.set_bytes(self._staged_bytes + self._out_bytes)

    def _reader_gen(self):
        """Reader stage: claim split readers one at a time, decode their
        chunks, admit them to the reorder buffer under the byte budget."""
        while not self._stop.is_set():
            with self._cv:
                ri = self._next_reader
                if ri >= len(self._readers):
                    return
                self._next_reader = ri + 1
            it = iter(self._readers[ri]())
            seq = 0
            while True:
                t0 = time.perf_counter_ns()
                try:
                    item = next(it)
                except StopIteration:
                    break
                dt = time.perf_counter_ns() - t0
                self._add("read_busy_s", dt / 1e9)
                trace.record(trace.SCAN, "read", t0, dt,
                             {"reader": ri, "seq": seq}
                             if trace.active() is not None else None)
                nbytes = item.nbytes if isinstance(item, HostChunk) \
                    else page_nbytes(item)
                ok = yield from self._stage_put_gen(ri, seq, item, nbytes)
                if not ok:
                    return
                seq += 1
                yield AGAIN  # fairness checkpoint between chunks
            ok = yield from self._stage_put_gen(ri, seq, _DONE, 0)
            if not ok:
                return

    def _stage_put_gen(self, ri: int, seq: int, item, nbytes: int):
        """Admit one decoded item into the reorder buffer under the byte
        budget. The item the decode stage needs NEXT bypasses a full budget
        (deadlock freedom); returns False when the pipeline stopped."""
        key = (ri, seq)
        t0 = time.perf_counter_ns()
        while True:
            with self._cv:
                if self._stop.is_set():
                    return False
                if not (self._staged_bytes > 0
                        and self._staged_bytes + nbytes > self._max_bytes
                        and key != self._needed):
                    self._buf[key] = (item, nbytes)
                    self._staged_bytes += nbytes
                    self._cv.notify_all()
                    break
                self._cv.wait(STEP_WAIT_S)
            yield WAIT
        self._account()
        dt = time.perf_counter_ns() - t0
        self._add("read_stall_s", dt / 1e9)
        if dt >= _TRACE_STALL_NS:
            trace.record(trace.SCAN, "read_stall", t0, dt)
        return True

    def _stage_take_gen(self, ri: int, seq: int):
        """In-order take from the reorder buffer; returns None when the
        pipeline stopped."""
        key = (ri, seq)
        t0 = time.perf_counter_ns()
        while True:
            with self._cv:
                self._needed = key
                self._cv.notify_all()
                if key in self._buf:
                    item, nbytes = self._buf.pop(key)
                    self._staged_bytes -= nbytes
                    self._cv.notify_all()
                    break
                if self._stop.is_set():
                    return None
                self._cv.wait(STEP_WAIT_S)
            yield WAIT
        self._account()
        dt = time.perf_counter_ns() - t0
        self._add("decode_stall_s", dt / 1e9)
        if dt >= _TRACE_STALL_NS:
            trace.record(trace.SCAN, "decode_stall", t0, dt)
        return item

    def _decode_gen(self):
        """Decode stage: consume the reorder buffer in split order and
        re-batch into device-shaped host pages, handing them to the
        (separate) upload stage so device_put overlaps re-batching."""
        rb = Rebatcher(self._target) if self._rebatch else None
        for ri in range(len(self._readers)):
            seq = 0
            while True:
                item = yield from self._stage_take_gen(ri, seq)
                if item is None:
                    return  # stopped
                if item is _DONE:
                    break
                seq += 1
                if rb is not None:
                    t0 = time.perf_counter_ns()
                    batches = rb.add(item)
                    dt = time.perf_counter_ns() - t0
                    self._add("decode_busy_s", dt / 1e9)
                    trace.record(trace.SCAN, "rebatch", t0, dt)
                    self._add("chunks", 1)
                    for page, nbytes, rows in batches:
                        ok = yield from self._emit_gen(page, nbytes, rows)
                        if not ok:
                            return
                else:
                    # live rows from the mask when it is host-side; a
                    # replayed device page would cost a sync to count,
                    # so its capacity stands in
                    rows = int(item.mask.sum()) \
                        if isinstance(item.mask, np.ndarray) \
                        else item.capacity
                    ok = yield from self._emit_gen(item, page_nbytes(item),
                                                   rows)
                    if not ok:
                        return
                yield AGAIN  # fairness checkpoint between chunks
        if rb is not None:
            tail = rb.flush()
            if tail is not None:
                ok = yield from self._emit_gen(*tail)
                if not ok:
                    return
        self._upq.put(_EOS)

    def _emit_gen(self, page: Page, nbytes: int, rows: int):
        """Admit a decoded page to the upload stage under the byte budget
        on uploaded-but-unconsumed pages (the stall here means the CONSUMER
        is the bottleneck — the healthy state)."""
        t0 = time.perf_counter_ns()
        while True:
            with self._ocv:
                if self._stop.is_set():
                    return False
                if not (self._out_bytes > 0
                        and self._out_bytes + nbytes > self._max_bytes):
                    self._out_bytes += nbytes
                    break
                self._ocv.wait(STEP_WAIT_S)
            yield WAIT
        self._account()
        dt = time.perf_counter_ns() - t0
        self._add("upload_stall_s", dt / 1e9)
        if dt >= _TRACE_STALL_NS:
            trace.record(trace.SCAN, "upload_stall", t0, dt)
        self._upq.put((page, nbytes, rows))
        return True

    def _upload_gen(self):
        """Upload stage: issue the (async) device_puts, decoupled from
        re-batching so host concatenation and host->device transfer
        overlap."""
        while True:
            try:
                item = self._upq.get(timeout=STEP_WAIT_S)
            except queue.Empty:
                if self._stop.is_set():
                    return
                yield WAIT
                continue
            if item is _EOS or self._stop.is_set():
                if self._error is None:  # a _fail already queued _ERR
                    self._out.put(_EOS)
                return
            page, nbytes, rows = item
            t0 = time.perf_counter_ns()
            dev = jax.tree.map(
                lambda a: jax.device_put(a, self._device), page)
            dt = time.perf_counter_ns() - t0
            self._add("upload_busy_s", dt / 1e9)
            trace.record(trace.SCAN, "upload", t0, dt,
                         {"rows": rows, "bytes": nbytes}
                         if trace.active() is not None else None)
            with self._stats_lock:
                self._stats["pages"] += 1
                self._stats["rows"] += rows
                self._stats["bytes"] += nbytes
            self._out.put((dev, nbytes))
            yield AGAIN  # fairness checkpoint between uploads

    def _fail(self, e: BaseException) -> None:
        self._error = e
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        with self._ocv:
            self._ocv.notify_all()
        self._upq.put(_EOS)  # wake an upload stage parked on its queue
        self._out.put((_ERR, e))

    def _flush_metrics(self) -> None:
        with self._stats_lock:
            if self._flushed:
                return
            self._flushed = True
            snap = dict(self._stats)
        METRICS.count_many(snap, prefix="scan.pipeline.")
