"""Aggregate function library.

Analogue of presto-main operator/aggregation/ (87 files: sum/count/avg/min/max/
approx_distinct/stddev/...) and AccumulatorCompiler.java:80. The reference compiles
per-function accumulator classes over flat state memory; here each function is a small
descriptor whose pieces (input transform, segment-combine, final transform) slot into
the segment-reduce grouping kernels — state is a struct-of-arrays indexed by group id,
which is exactly what TPU scatter/segment ops want.

Every function must be decomposable as
    partial:   contribution_j = input_map(x_j)          (per row)
    combine:   state_g = REDUCE_j-in-g contribution_j    (sum / min / max per column)
    final:     output_g = final_map(state_g)
which covers the algebraic aggregates. Non-algebraic ones (approx_percentile) get
fixed-size sketch states (qdigest/HLL analogues) in later revisions.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (BIGINT, BOOLEAN, DOUBLE, REAL, Type, DecimalType, UNKNOWN,
                     is_floating, is_string)

# reduce kinds understood by the grouping kernels
SUM, MIN, MAX = "sum", "min", "max"

_I64_MAX = np.int64(2**63 - 1)
_I64_MIN = np.int64(-(2**63))


@dataclasses.dataclass
class StateColumn:
    """One array in the aggregate's state struct."""
    dtype: np.dtype
    reduce: str          # SUM | MIN | MAX
    identity: object     # fill value for empty groups


@dataclasses.dataclass
class AggregateFunction:
    """Descriptor: how to turn input rows into state contributions and state to output."""
    name: str
    output_type: Type
    state: List[StateColumn]
    # (input_arrays, valid_mask) -> per-row contribution arrays (one per state column)
    input_map: Callable
    # state arrays -> output array
    final_map: Callable
    intermediate_types: List[Type] = dataclasses.field(default_factory=list)


def _ones_i64(args, mask):
    shape = jnp.shape(mask)
    return (jnp.where(mask, jnp.int64(1), jnp.int64(0)),)


def resolve_aggregate(name: str, arg_types: Sequence[Type],
                      distinct: bool = False) -> AggregateFunction:
    """FunctionManager.resolveFunction analogue for aggregates."""
    name = name.lower()
    if name == "count":
        if not arg_types:  # count(*)
            return AggregateFunction(
                "count", BIGINT,
                [StateColumn(np.dtype(np.int64), SUM, 0)],
                _ones_i64,
                lambda s: s[0],
                [BIGINT])
        t = arg_types[0]
        return AggregateFunction(
            "count", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, jnp.int64(1), jnp.int64(0)),),
            lambda s: s[0],
            [BIGINT])

    if name == "sum":
        t = arg_types[0]
        # second state column = contributing-row count; SQL sum over an empty/all-null
        # group is NULL, surfaced via the (data, null_mask) final_map contract
        if isinstance(t, DecimalType):
            out = DecimalType(18, t.scale)
            return AggregateFunction(
                "sum", out,
                [StateColumn(np.dtype(np.int64), SUM, 0),
                 StateColumn(np.dtype(np.int64), SUM, 0)],
                lambda args, mask: (jnp.where(mask, args[0].astype(jnp.int64), 0),
                                    jnp.where(mask, jnp.int64(1), jnp.int64(0))),
                lambda s: (s[0], s[1] == 0),
                [out, BIGINT])
        if is_floating(t):
            return AggregateFunction(
                "sum", DOUBLE,
                [StateColumn(np.dtype(np.float64), SUM, 0.0),
                 StateColumn(np.dtype(np.int64), SUM, 0)],
                lambda args, mask: (jnp.where(mask, args[0].astype(jnp.float64), 0.0),
                                    jnp.where(mask, jnp.int64(1), jnp.int64(0))),
                lambda s: (s[0], s[1] == 0),
                [DOUBLE, BIGINT])
        return AggregateFunction(
            "sum", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, args[0].astype(jnp.int64), 0),
                                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [BIGINT, BIGINT])

    if name == "avg":
        t = arg_types[0]
        scale = t.scale if isinstance(t, DecimalType) else 0
        div = 10.0 ** scale
        return AggregateFunction(
            "avg", DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, args[0].astype(jnp.float64) / div, 0.0),
                                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0] / jnp.maximum(s[1], 1).astype(jnp.float64), s[1] == 0),
            [DOUBLE, BIGINT])

    if name in ("min", "max"):
        t = arg_types[0]
        if is_string(t):
            # min/max on varchar reduces over dictionary CODES — correct only for
            # lexicographically-sorted dictionaries (block_from_strings builds sorted
            # ones). The planner must re-encode through Dictionary.sort_keys() before
            # aggregating an unsorted dictionary; AggregateCall.output_dictionary
            # carries the dictionary to the output block.
            dtype = np.dtype(np.int32)
            ident = np.int32(2**31 - 1) if name == "min" else np.int32(-(2**31))
        else:
            dtype = t.np_dtype
            if dtype.kind == "f":
                ident = np.inf if name == "min" else -np.inf
            else:
                info = np.iinfo(dtype)
                ident = info.max if name == "min" else info.min
        red = MIN if name == "min" else MAX
        return AggregateFunction(
            name, t,
            [StateColumn(dtype, red, ident),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask, _i=ident: (jnp.where(mask, args[0], jnp.asarray(_i)),
                                          jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [t, BIGINT])

    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        pop = name.endswith("_pop")
        is_std = name.startswith("stddev")
        t = arg_types[0]
        scale = t.scale if isinstance(t, DecimalType) else 0
        div = 10.0 ** scale

        def input_map(args, mask):
            x = jnp.where(mask, args[0].astype(jnp.float64) / div, 0.0)
            return (x, x * x, jnp.where(mask, jnp.int64(1), jnp.int64(0)))

        def final_map(s, _pop=pop, _std=is_std):
            n = jnp.maximum(s[2], 1).astype(jnp.float64)
            mean = s[0] / n
            var = s[1] / n - mean * mean
            if not _pop:
                var = var * n / jnp.maximum(n - 1, 1)
            var = jnp.maximum(var, 0.0)
            return (jnp.sqrt(var) if _std else var), s[2] == 0

        return AggregateFunction(
            name, DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            input_map, final_map,
            [DOUBLE, DOUBLE, BIGINT])

    if name == "bool_or" or name == "bool_and":
        is_or = name == "bool_or"
        return AggregateFunction(
            name, BOOLEAN,
            [StateColumn(np.dtype(np.int64), MAX if is_or else MIN, 0 if is_or else 1),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (
                jnp.where(mask, args[0].astype(jnp.int64), 0 if is_or else 1),
                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0] != 0, s[1] == 0),
            [BOOLEAN, BIGINT])

    if name == "count_if":
        return AggregateFunction(
            "count_if", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask & (args[0].astype(jnp.bool_)),
                                          jnp.int64(1), jnp.int64(0)),),
            lambda s: s[0],
            [BIGINT])

    if name == "every":
        return resolve_aggregate("bool_and", arg_types, distinct)

    if name in ("arbitrary", "any_value"):
        # deterministic "any": max over values (dictionary codes for varchar,
        # same caveat-free since ANY value is acceptable)
        t = arg_types[0]
        dtype = np.dtype(np.int32) if is_string(t) else t.np_dtype
        if dtype.kind == "f":
            ident = -np.inf
        elif dtype.kind == "b":
            ident = False
        else:
            ident = np.iinfo(dtype).min
        return AggregateFunction(
            name, t,
            [StateColumn(dtype, MAX, ident),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask, _i=ident: (
                jnp.where(mask, args[0], jnp.asarray(_i)),
                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [t, BIGINT])

    if name in ("covar_samp", "covar_pop", "corr"):
        tx, ty = arg_types[0], arg_types[1]
        dx = 10.0 ** (tx.scale if isinstance(tx, DecimalType) else 0)
        dy = 10.0 ** (ty.scale if isinstance(ty, DecimalType) else 0)
        want_corr = name == "corr"
        pop = name == "covar_pop"

        def input_map(args, mask):
            x = jnp.where(mask, args[0].astype(jnp.float64) / dx, 0.0)
            y = jnp.where(mask, args[1].astype(jnp.float64) / dy, 0.0)
            return (x, y, x * y, x * x, y * y,
                    jnp.where(mask, jnp.int64(1), jnp.int64(0)))

        def final_map(s, _corr=want_corr, _pop=pop):
            n = jnp.maximum(s[5], 1).astype(jnp.float64)
            mx, my = s[0] / n, s[1] / n
            cov = s[2] / n - mx * my
            if _corr:
                vx = jnp.maximum(s[3] / n - mx * mx, 0.0)
                vy = jnp.maximum(s[4] / n - my * my, 0.0)
                denom = jnp.sqrt(vx * vy)
                out = jnp.where(denom > 0, cov / jnp.maximum(denom, 1e-300), 0.0)
                return out, (s[5] == 0) | (denom <= 0)
            if not _pop:
                cov = cov * n / jnp.maximum(n - 1, 1)
                return cov, s[5] <= 1
            return cov, s[5] == 0

        return AggregateFunction(
            name, DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0) for _ in range(5)] +
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            input_map, final_map,
            [DOUBLE] * 5 + [BIGINT])

    if name == "approx_distinct":
        # min-hash sketch: K independent uniform-min registers per group,
        # merged by MIN (associative => partial/final steps compose). The
        # reference's HLL (approx error ~2.3%) needs 2048 byte registers; K=64
        # scalar registers give ~1/sqrt(K) ~ 12% typical error, which honors
        # the function's approximate contract on this engine's state model.
        K = 64
        t = arg_types[0]

        def input_map(args, mask, _k=K):
            a0 = args[0]
            if jnp.issubdtype(a0.dtype, jnp.floating):
                # bitcast, not value cast: 1.25 and 1.75 must hash apart
                x = jax.lax.bitcast_convert_type(
                    a0.astype(jnp.float64), jnp.int64).astype(jnp.uint64)
            else:
                x = a0.astype(jnp.int64).astype(jnp.uint64)
            outs = []
            for j in range(_k):
                h = _sketch_mix(x ^ jnp.uint64(0x9E3779B97F4A7C15 * (j + 1) & 0xFFFFFFFFFFFFFFFF))
                u = (h >> jnp.uint64(11)).astype(jnp.float64) / float(1 << 53)
                outs.append(jnp.where(mask, u, 1.0))
            return tuple(outs)

        def final_map(s, _k=K):
            total = s[0]
            for j in range(1, _k):
                total = total + s[j]
            # E[min of n uniforms] = 1/(n+1); sum of K mins ~ Gamma(K, 1/(n+1))
            est = _k / jnp.maximum(total, 1e-12) - 1.0
            return jnp.round(jnp.maximum(est, 0.0)).astype(jnp.int64)

        return AggregateFunction(
            "approx_distinct", BIGINT,
            [StateColumn(np.dtype(np.float64), MIN, 1.0) for _ in range(K)],
            input_map, final_map,
            [DOUBLE] * K)

    raise NotImplementedError(f"aggregate function {name}({arg_types})")


def _sketch_mix(x):
    from .hash_join import _mix64
    return _mix64(x)


@dataclasses.dataclass
class AggregateCall:
    """One aggregate in a GROUP BY: function + input channels + step."""
    function: AggregateFunction
    input_channels: List[int]          # channels in the input page
    mask_channel: Optional[int] = None  # FILTER (WHERE ...) / mark-distinct channel
    # when consuming partial states (FINAL step), channels of the state columns:
    intermediate_channels: Optional[List[int]] = None
    # dictionary for the output block (min/max over varchar passes codes through):
    output_dictionary: Optional[object] = None
