"""Aggregate function library.

Analogue of presto-main operator/aggregation/ (87 files: sum/count/avg/min/max/
approx_distinct/stddev/...) and AccumulatorCompiler.java:80. The reference compiles
per-function accumulator classes over flat state memory; here each function is a small
descriptor whose pieces (input transform, segment-combine, final transform) slot into
the segment-reduce grouping kernels — state is a struct-of-arrays indexed by group id,
which is exactly what TPU scatter/segment ops want.

Every function must be decomposable as
    partial:   contribution_j = input_map(x_j)          (per row)
    combine:   state_g = REDUCE_j-in-g contribution_j    (sum / min / max per column)
    final:     output_g = final_map(state_g)
which covers the algebraic aggregates. Non-algebraic ones (approx_percentile) get
fixed-size sketch states (qdigest/HLL analogues) in later revisions.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..types import (BIGINT, BOOLEAN, DOUBLE, REAL, Type, DecimalType, UNKNOWN,
                     is_floating, is_string)

# reduce kinds understood by the grouping kernels
SUM, MIN, MAX = "sum", "min", "max"

_I64_MAX = np.int64(2**63 - 1)
_I64_MIN = np.int64(-(2**63))


@dataclasses.dataclass
class StateColumn:
    """One array in the aggregate's state struct."""
    dtype: np.dtype
    reduce: str          # SUM | MIN | MAX
    identity: object     # fill value for empty groups


@dataclasses.dataclass
class AggregateFunction:
    """Descriptor: how to turn input rows into state contributions and state to output."""
    name: str
    output_type: Type
    state: List[StateColumn]
    # (input_arrays, valid_mask) -> per-row contribution arrays (one per state column)
    input_map: Callable
    # state arrays -> output array
    final_map: Callable
    intermediate_types: List[Type] = dataclasses.field(default_factory=list)


def _ones_i64(args, mask):
    shape = jnp.shape(mask)
    return (jnp.where(mask, jnp.int64(1), jnp.int64(0)),)


def resolve_aggregate(name: str, arg_types: Sequence[Type],
                      distinct: bool = False) -> AggregateFunction:
    """FunctionManager.resolveFunction analogue for aggregates."""
    name = name.lower()
    if name == "count":
        if not arg_types:  # count(*)
            return AggregateFunction(
                "count", BIGINT,
                [StateColumn(np.dtype(np.int64), SUM, 0)],
                _ones_i64,
                lambda s: s[0],
                [BIGINT])
        t = arg_types[0]
        return AggregateFunction(
            "count", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, jnp.int64(1), jnp.int64(0)),),
            lambda s: s[0],
            [BIGINT])

    if name == "sum":
        t = arg_types[0]
        # second state column = contributing-row count; SQL sum over an empty/all-null
        # group is NULL, surfaced via the (data, null_mask) final_map contract
        if isinstance(t, DecimalType):
            out = DecimalType(18, t.scale)
            return AggregateFunction(
                "sum", out,
                [StateColumn(np.dtype(np.int64), SUM, 0),
                 StateColumn(np.dtype(np.int64), SUM, 0)],
                lambda args, mask: (jnp.where(mask, args[0].astype(jnp.int64), 0),
                                    jnp.where(mask, jnp.int64(1), jnp.int64(0))),
                lambda s: (s[0], s[1] == 0),
                [out, BIGINT])
        if is_floating(t):
            return AggregateFunction(
                "sum", DOUBLE,
                [StateColumn(np.dtype(np.float64), SUM, 0.0),
                 StateColumn(np.dtype(np.int64), SUM, 0)],
                lambda args, mask: (jnp.where(mask, args[0].astype(jnp.float64), 0.0),
                                    jnp.where(mask, jnp.int64(1), jnp.int64(0))),
                lambda s: (s[0], s[1] == 0),
                [DOUBLE, BIGINT])
        return AggregateFunction(
            "sum", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, args[0].astype(jnp.int64), 0),
                                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [BIGINT, BIGINT])

    if name == "avg":
        t = arg_types[0]
        scale = t.scale if isinstance(t, DecimalType) else 0
        div = 10.0 ** scale
        return AggregateFunction(
            "avg", DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, args[0].astype(jnp.float64) / div, 0.0),
                                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0] / jnp.maximum(s[1], 1).astype(jnp.float64), s[1] == 0),
            [DOUBLE, BIGINT])

    if name in ("min", "max"):
        t = arg_types[0]
        if is_string(t):
            # min/max on varchar reduces over dictionary CODES — correct only for
            # lexicographically-sorted dictionaries (block_from_strings builds sorted
            # ones). The planner must re-encode through Dictionary.sort_keys() before
            # aggregating an unsorted dictionary; AggregateCall.output_dictionary
            # carries the dictionary to the output block.
            dtype = np.dtype(np.int32)
            ident = np.int32(2**31 - 1) if name == "min" else np.int32(-(2**31))
        else:
            dtype = t.np_dtype
            if dtype.kind == "f":
                ident = np.inf if name == "min" else -np.inf
            else:
                info = np.iinfo(dtype)
                ident = info.max if name == "min" else info.min
        red = MIN if name == "min" else MAX
        return AggregateFunction(
            name, t,
            [StateColumn(dtype, red, ident),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask, _i=ident: (jnp.where(mask, args[0], jnp.asarray(_i)),
                                          jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [t, BIGINT])

    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        pop = name.endswith("_pop")
        is_std = name.startswith("stddev")
        t = arg_types[0]
        scale = t.scale if isinstance(t, DecimalType) else 0
        div = 10.0 ** scale

        def input_map(args, mask):
            x = jnp.where(mask, args[0].astype(jnp.float64) / div, 0.0)
            return (x, x * x, jnp.where(mask, jnp.int64(1), jnp.int64(0)))

        def final_map(s, _pop=pop, _std=is_std):
            n = jnp.maximum(s[2], 1).astype(jnp.float64)
            mean = s[0] / n
            var = s[1] / n - mean * mean
            if not _pop:
                var = var * n / jnp.maximum(n - 1, 1)
            var = jnp.maximum(var, 0.0)
            return (jnp.sqrt(var) if _std else var), s[2] == 0

        return AggregateFunction(
            name, DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            input_map, final_map,
            [DOUBLE, DOUBLE, BIGINT])

    if name == "bool_or" or name == "bool_and":
        is_or = name == "bool_or"
        return AggregateFunction(
            name, BOOLEAN,
            [StateColumn(np.dtype(np.int64), MAX if is_or else MIN, 0 if is_or else 1),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (
                jnp.where(mask, args[0].astype(jnp.int64), 0 if is_or else 1),
                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0] != 0, s[1] == 0),
            [BOOLEAN, BIGINT])

    if name == "approx_distinct":
        # dense HLL-ish: 2^11 registers of max(leading-rank); merged by MAX — a fixed
        # 2048-wide state row per group. Heavy for high-cardinality group-bys; fine
        # for the global/low-group case it is typically used in.
        raise NotImplementedError("approx_distinct arrives with the sketch-state rev")

    raise NotImplementedError(f"aggregate function {name}({arg_types})")


@dataclasses.dataclass
class AggregateCall:
    """One aggregate in a GROUP BY: function + input channels + step."""
    function: AggregateFunction
    input_channels: List[int]          # channels in the input page
    mask_channel: Optional[int] = None  # FILTER (WHERE ...) / mark-distinct channel
    # when consuming partial states (FINAL step), channels of the state columns:
    intermediate_channels: Optional[List[int]] = None
    # dictionary for the output block (min/max over varchar passes codes through):
    output_dictionary: Optional[object] = None
