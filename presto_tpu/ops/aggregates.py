"""Aggregate function library.

Analogue of presto-main operator/aggregation/ (87 files: sum/count/avg/min/max/
approx_distinct/stddev/...) and AccumulatorCompiler.java:80. The reference compiles
per-function accumulator classes over flat state memory; here each function is a small
descriptor whose pieces (input transform, segment-combine, final transform) slot into
the segment-reduce grouping kernels — state is a struct-of-arrays indexed by group id,
which is exactly what TPU scatter/segment ops want.

Every function must be decomposable as
    partial:   contribution_j = input_map(x_j)          (per row)
    combine:   state_g = REDUCE_j-in-g contribution_j    (sum / min / max per column)
    final:     output_g = final_map(state_g)
which covers the algebraic aggregates. Non-algebraic ones (approx_percentile) get
fixed-size sketch states (qdigest/HLL analogues) in later revisions.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..types import (BIGINT, BOOLEAN, DOUBLE, REAL, Type, DecimalType, UNKNOWN,
                     is_floating, is_string)

# reduce kinds understood by the grouping kernels
SUM, MIN, MAX = "sum", "min", "max"
# paired (joint) kinds: an AMIN/AMAX state column holds an order-preserving
# int64 ordering key; the IMMEDIATELY FOLLOWING column must be ACARRY and
# receives the payload of the row that won the ordering (min_by/max_by,
# reference operator/aggregation/minmaxby/AbstractMinMaxBy.java). The
# grouping kernels reduce the pair jointly (segment argmin/argmax + gather).
AMIN, AMAX, ACARRY = "amin", "amax", "acarry"

_I64_MAX = np.int64(2**63 - 1)
_I64_MIN = np.int64(-(2**63))


@dataclasses.dataclass
class StateColumn:
    """One array in the aggregate's state struct.

    width > 1 makes this a VECTOR state: the per-group entry is a (width,)
    array, per-row contributions are (rows, width), and the grouping kernels
    reduce over the leading axis only — the sketch-aggregate shape
    (approx_percentile histograms, approx_distinct HLL registers), which maps
    to one wide segment-reduce instead of `width` scalar ones."""
    dtype: np.dtype
    reduce: str          # SUM | MIN | MAX
    identity: object     # fill value for empty groups
    width: int = 1


@dataclasses.dataclass
class AggregateFunction:
    """Descriptor: how to turn input rows into state contributions and state to output."""
    name: str
    output_type: Type
    state: List[StateColumn]
    # (input_arrays, valid_mask) -> per-row contribution arrays (one per state column)
    input_map: Callable
    # state arrays -> output array
    final_map: Callable
    intermediate_types: List[Type] = dataclasses.field(default_factory=list)
    # splittable: state columns can ride pages between PARTIAL and FINAL steps
    # (vector states cannot — the exchange planner keeps those single-phase)
    splittable: bool = True
    # canonical resolve-time identity (name, arg type names, distinct, params)
    # for the global kernel cache (utils/kernel_cache.agg_call_key) — set by
    # resolve_aggregate; two functions with equal fingerprints compile to
    # behaviorally identical contributions
    fingerprint: tuple = ()
    # string-producing aggregates (ml learn_*): a Dictionary allocated at
    # RESOLVE time so the plan layout can reference it; final_map fills it
    # with the actual values (codes index into it) when the query runs
    output_dict: object = None
    # which arg indices' NULLs exclude the row from the aggregate; None =
    # all (the @SqlNullable default). min_by/max_by skip only NULL ORDERING
    # rows — a NULL payload still participates and can win.
    null_skip_channels: Optional[tuple] = None
    # input_map is called as input_map(args, arg_null_masks, mask) when set
    needs_arg_nulls: bool = False


def _ones_i64(args, mask):
    shape = jnp.shape(mask)
    return (jnp.where(mask, jnp.int64(1), jnp.int64(0)),)


def resolve_aggregate(name: str, arg_types: Sequence[Type],
                      distinct: bool = False,
                      params: Sequence[object] = ()) -> AggregateFunction:
    """FunctionManager.resolveFunction analogue for aggregates.

    `params` carries literal (non-column) arguments extracted by the planner —
    e.g. approx_percentile's fraction."""
    fn = _resolve_aggregate(name, arg_types, distinct, params)
    # the resolve arguments fully determine the function's behavior, so they
    # ARE its kernel-cache identity
    fn.fingerprint = (name.lower(), tuple(t.name for t in arg_types),
                      bool(distinct), tuple(params))
    return fn


def _resolve_aggregate(name: str, arg_types: Sequence[Type],
                       distinct: bool = False,
                       params: Sequence[object] = ()) -> AggregateFunction:
    name = name.lower()
    if name == "count":
        if not arg_types:  # count(*)
            return AggregateFunction(
                "count", BIGINT,
                [StateColumn(np.dtype(np.int64), SUM, 0)],
                _ones_i64,
                lambda s: s[0],
                [BIGINT])
        t = arg_types[0]
        return AggregateFunction(
            "count", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, jnp.int64(1), jnp.int64(0)),),
            lambda s: s[0],
            [BIGINT])

    if name == "sum":
        t = arg_types[0]
        # second state column = contributing-row count; SQL sum over an empty/all-null
        # group is NULL, surfaced via the (data, null_mask) final_map contract
        if isinstance(t, DecimalType):
            out = DecimalType(18, t.scale)
            return AggregateFunction(
                "sum", out,
                [StateColumn(np.dtype(np.int64), SUM, 0),
                 StateColumn(np.dtype(np.int64), SUM, 0)],
                lambda args, mask: (jnp.where(mask, args[0].astype(jnp.int64), 0),
                                    jnp.where(mask, jnp.int64(1), jnp.int64(0))),
                lambda s: (s[0], s[1] == 0),
                [out, BIGINT])
        if is_floating(t):
            return AggregateFunction(
                "sum", DOUBLE,
                [StateColumn(np.dtype(np.float64), SUM, 0.0),
                 StateColumn(np.dtype(np.int64), SUM, 0)],
                lambda args, mask: (jnp.where(mask, args[0].astype(jnp.float64), 0.0),
                                    jnp.where(mask, jnp.int64(1), jnp.int64(0))),
                lambda s: (s[0], s[1] == 0),
                [DOUBLE, BIGINT])
        return AggregateFunction(
            "sum", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, args[0].astype(jnp.int64), 0),
                                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [BIGINT, BIGINT])

    if name == "avg":
        t = arg_types[0]
        scale = t.scale if isinstance(t, DecimalType) else 0
        div = 10.0 ** scale
        return AggregateFunction(
            "avg", DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask, args[0].astype(jnp.float64) / div, 0.0),
                                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0] / jnp.maximum(s[1], 1).astype(jnp.float64), s[1] == 0),
            [DOUBLE, BIGINT])

    if name in ("min", "max"):
        t = arg_types[0]
        if is_string(t):
            # min/max on varchar reduces over dictionary CODES — correct only for
            # lexicographically-sorted dictionaries (block_from_strings builds sorted
            # ones). The planner must re-encode through Dictionary.sort_keys() before
            # aggregating an unsorted dictionary; AggregateCall.output_dictionary
            # carries the dictionary to the output block.
            dtype = np.dtype(np.int32)
            ident = np.int32(2**31 - 1) if name == "min" else np.int32(-(2**31))
        else:
            dtype = t.np_dtype
            if dtype.kind == "f":
                ident = np.inf if name == "min" else -np.inf
            else:
                info = np.iinfo(dtype)
                ident = info.max if name == "min" else info.min
        red = MIN if name == "min" else MAX
        return AggregateFunction(
            name, t,
            [StateColumn(dtype, red, ident),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask, _i=ident: (jnp.where(mask, args[0], jnp.asarray(_i)),
                                          jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [t, BIGINT])

    if name in ("min_by", "max_by"):
        # min_by(x, y): x of the row with minimal y. State = (sortable-int64
        # ordering key, carried payload, count); the kernels reduce the
        # AMIN/ACARRY pair jointly. Reference:
        # operator/aggregation/minmaxby/AbstractMinMaxBy.java.
        if len(arg_types) != 2:
            raise NotImplementedError(
                f"{name} takes exactly 2 arguments (the top-n form is not "
                f"supported)")
        tx, ty = arg_types[0], arg_types[1]
        is_min = name == "min_by"
        okind = AMIN if is_min else AMAX
        oident = _I64_MAX if is_min else _I64_MIN
        carry_dtype = np.dtype(np.int32) if is_string(tx) else tx.np_dtype
        carry_ident = False if carry_dtype.kind == "b" else carry_dtype.type(0)

        def input_map(args, arg_nulls, mask, _oident=oident):
            x, y = args[0], args[1]
            ys = jnp.where(mask, _sortable_i64(y), jnp.int64(_oident))
            carry = jnp.where(mask, x, jnp.asarray(carry_ident,
                                                   dtype=carry_dtype))
            xn = arg_nulls[0]
            carry_null = jnp.where(mask, xn.astype(jnp.int64), jnp.int64(0))
            return (ys, carry, carry_null,
                    jnp.where(mask, jnp.int64(1), jnp.int64(0)))

        return AggregateFunction(
            name, tx,
            [StateColumn(np.dtype(np.int64), okind, oident),
             StateColumn(carry_dtype, ACARRY, carry_ident),
             StateColumn(np.dtype(np.int64), ACARRY, 0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            input_map,
            lambda s: (s[1], (s[3] == 0) | (s[2] != 0)),
            [], splittable=False,
            null_skip_channels=(1,), needs_arg_nulls=True)

    if name in ("array_agg", "map_agg", "histogram"):
        # ragged collectors (ArrayAggregationFunction.java:50,
        # MapAggregationFunction.java, histogram/Histogram.java): routed to
        # ops/collect_agg.CollectAggregationBuilder; the state column is the
        # int32 HANDLE into the host ArrayValues store allocated here
        from ..block import ArrayValues
        from ..types import ArrayType, MapType
        if name == "array_agg":
            out_t = ArrayType(arg_types[0])
            store = ArrayValues("array")
        elif name == "map_agg":
            out_t = MapType(arg_types[0], arg_types[1])
            store = ArrayValues("map")
        else:
            out_t = MapType(arg_types[0], BIGINT)
            store = ArrayValues("map")
        return AggregateFunction(
            name, out_t,
            [StateColumn(np.dtype(np.int32), "collect", -1)],
            None,  # the collect builder bypasses input_map
            lambda s: (s[0], s[0] < 0),
            [], splittable=False, output_dict=store)

    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        pop = name.endswith("_pop")
        is_std = name.startswith("stddev")
        t = arg_types[0]
        scale = t.scale if isinstance(t, DecimalType) else 0
        div = 10.0 ** scale

        def input_map(args, mask):
            x = jnp.where(mask, args[0].astype(jnp.float64) / div, 0.0)
            return (x, x * x, jnp.where(mask, jnp.int64(1), jnp.int64(0)))

        def final_map(s, _pop=pop, _std=is_std):
            n = jnp.maximum(s[2], 1).astype(jnp.float64)
            mean = s[0] / n
            var = s[1] / n - mean * mean
            if not _pop:
                var = var * n / jnp.maximum(n - 1, 1)
            var = jnp.maximum(var, 0.0)
            return (jnp.sqrt(var) if _std else var), s[2] == 0

        return AggregateFunction(
            name, DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.float64), SUM, 0.0),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            input_map, final_map,
            [DOUBLE, DOUBLE, BIGINT])

    if name == "bool_or" or name == "bool_and":
        is_or = name == "bool_or"
        return AggregateFunction(
            name, BOOLEAN,
            [StateColumn(np.dtype(np.int64), MAX if is_or else MIN, 0 if is_or else 1),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (
                jnp.where(mask, args[0].astype(jnp.int64), 0 if is_or else 1),
                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0] != 0, s[1] == 0),
            [BOOLEAN, BIGINT])

    if name == "count_if":
        return AggregateFunction(
            "count_if", BIGINT,
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask: (jnp.where(mask & (args[0].astype(jnp.bool_)),
                                          jnp.int64(1), jnp.int64(0)),),
            lambda s: s[0],
            [BIGINT])

    if name == "every":
        return resolve_aggregate("bool_and", arg_types, distinct)

    if name in ("arbitrary", "any_value"):
        # deterministic "any": max over values (dictionary codes for varchar,
        # same caveat-free since ANY value is acceptable)
        t = arg_types[0]
        dtype = np.dtype(np.int32) if is_string(t) else t.np_dtype
        if dtype.kind == "f":
            ident = -np.inf
        elif dtype.kind == "b":
            ident = False
        else:
            ident = np.iinfo(dtype).min
        return AggregateFunction(
            name, t,
            [StateColumn(dtype, MAX, ident),
             StateColumn(np.dtype(np.int64), SUM, 0)],
            lambda args, mask, _i=ident: (
                jnp.where(mask, args[0], jnp.asarray(_i)),
                jnp.where(mask, jnp.int64(1), jnp.int64(0))),
            lambda s: (s[0], s[1] == 0),
            [t, BIGINT])

    if name in ("covar_samp", "covar_pop", "corr"):
        tx, ty = arg_types[0], arg_types[1]
        dx = 10.0 ** (tx.scale if isinstance(tx, DecimalType) else 0)
        dy = 10.0 ** (ty.scale if isinstance(ty, DecimalType) else 0)
        want_corr = name == "corr"
        pop = name == "covar_pop"

        def input_map(args, mask):
            x = jnp.where(mask, args[0].astype(jnp.float64) / dx, 0.0)
            y = jnp.where(mask, args[1].astype(jnp.float64) / dy, 0.0)
            return (x, y, x * y, x * x, y * y,
                    jnp.where(mask, jnp.int64(1), jnp.int64(0)))

        def final_map(s, _corr=want_corr, _pop=pop):
            n = jnp.maximum(s[5], 1).astype(jnp.float64)
            mx, my = s[0] / n, s[1] / n
            cov = s[2] / n - mx * my
            if _corr:
                vx = jnp.maximum(s[3] / n - mx * mx, 0.0)
                vy = jnp.maximum(s[4] / n - my * my, 0.0)
                denom = jnp.sqrt(vx * vy)
                out = jnp.where(denom > 0, cov / jnp.maximum(denom, 1e-300), 0.0)
                return out, (s[5] == 0) | (denom <= 0)
            if not _pop:
                cov = cov * n / jnp.maximum(n - 1, 1)
                return cov, s[5] <= 1
            return cov, s[5] == 0

        return AggregateFunction(
            name, DOUBLE,
            [StateColumn(np.dtype(np.float64), SUM, 0.0) for _ in range(5)] +
            [StateColumn(np.dtype(np.int64), SUM, 0)],
            input_map, final_map,
            [DOUBLE] * 5 + [BIGINT])

    if name == "approx_distinct":
        # HyperLogLog, m=2048 registers (standard error 1.04/sqrt(m) ~ 2.3%,
        # matching the reference's default HLL accuracy,
        # operator/aggregation/ApproximateCountDistinctAggregation). One
        # VECTOR state per group: register j holds max(rho) of hashes landing
        # in bucket j; per-row contribution is a one-hot (rows, m) scatter
        # reduced by MAX — one wide segment-reduce on the VPU.
        M = 2048
        LOG2M = 11

        def input_map(args, mask, _m=M):
            h = _hash_to_u64(args[0])
            bucket = (h >> jnp.uint64(64 - LOG2M)).astype(jnp.int32)
            rest = (h << jnp.uint64(LOG2M)) | jnp.uint64((1 << LOG2M) - 1)
            # rho = leading zeros + 1, via the float exponent (msb index);
            # float64's 52-bit mantissa can misplace the msb on ~2^-52 of
            # inputs — irrelevant at sketch accuracy
            msb = jnp.floor(jnp.log2(rest.astype(jnp.float64)))
            rho = jnp.clip(64.0 - msb, 1.0, 64.0 - LOG2M + 1.0
                           ).astype(jnp.float32)
            # wide-state contribution: (bucket, value) pair; the grouping
            # kernels scatter value into state[group, bucket] with MAX
            return ((jnp.where(mask, bucket, _m), rho),)

        def final_map(s, _m=M):
            regs = s[0]                               # (groups, m) f32
            est = (0.7213 / (1 + 1.079 / _m)) * _m * _m / \
                jnp.sum(jnp.exp2(-regs), axis=-1)
            zeros = jnp.sum(regs == 0, axis=-1)
            # small-range correction (linear counting)
            small = _m * jnp.log(_m / jnp.maximum(zeros, 1).astype(jnp.float64))
            est = jnp.where((est <= 2.5 * _m) & (zeros > 0), small, est)
            return jnp.round(est).astype(jnp.int64)

        return AggregateFunction(
            "approx_distinct", BIGINT,
            [StateColumn(np.dtype(np.float32), MAX, 0.0, width=M)],
            input_map, final_map, [], splittable=False)

    if name == "approx_percentile":
        # log-bucketed histogram sketch: octaves 2^-16..2^31 x 8 sub-buckets
        # x 2 signs (+1 zero bucket) of f64 counts as ONE vector state; the
        # percentile is read off the per-group cumulative histogram with the
        # bucket's geometric midpoint (reference: qdigest-based
        # approx_percentile, ApproximateLongPercentileAggregations).
        # Relative error ~= half a sub-bucket ~= 4% for 2^-16 <= |v| < 2^32;
        # smaller magnitudes clamp into the lowest octave.
        OCT_LO, OCT_HI, SUB = -16, 31, 8
        N_OCT = OCT_HI - OCT_LO + 1
        HALF = N_OCT * SUB
        K = 2 * HALF + 1
        t = arg_types[0]
        int_out = not is_floating(t)  # decimals/ints stay scaled ints

        centers = np.zeros(K, dtype=np.float64)
        for i in range(N_OCT):
            for sub_i in range(SUB):
                mid = 2.0 ** (OCT_LO + i) * (1.0 + (sub_i + 0.5) / SUB)
                centers[HALF + 1 + i * SUB + sub_i] = mid
                centers[HALF - 1 - i * SUB - sub_i] = -mid
        centers_j = jnp.asarray(centers)

        def bucket_of(v):
            mag = jnp.abs(v.astype(jnp.float64))
            exp = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(mag, 1e-300))),
                           OCT_LO, OCT_HI)
            sub = jnp.clip(jnp.floor((mag / jnp.exp2(exp) - 1.0) * SUB),
                           0, SUB - 1)
            off = ((exp - OCT_LO) * SUB + sub + 1).astype(jnp.int32)
            return jnp.where(v == 0, HALF,
                             jnp.where(v > 0, HALF + off, HALF - off))

        def input_map(args, mask, _k=K):
            b = jnp.where(mask, bucket_of(args[0]), _k)
            return ((b.astype(jnp.int32), jnp.ones_like(b, jnp.float64)),)

        # the percentile fraction is bound at resolve time via params (the
        # planner extracts the literal second argument)
        pct = float(params[0]) if params else 0.5
        if not 0.0 < pct <= 1.0:
            raise ValueError("approx_percentile fraction must be in (0, 1]")

        def final_map(s, _p=pct):
            hist = s[0]                              # (groups, K) f64 counts
            total = jnp.sum(hist, axis=-1)
            target = jnp.ceil(_p * jnp.maximum(total, 1.0))
            cum = jnp.cumsum(hist, axis=-1)
            idx = jnp.argmax(cum >= target[..., None], axis=-1)
            vals = centers_j[idx]
            out = jnp.round(vals).astype(jnp.int64) if int_out else vals
            return out, total == 0

        out_t = t if int_out else DOUBLE
        return AggregateFunction(
            "approx_percentile", out_t,
            [StateColumn(np.dtype(np.float64), SUM, 0.0, width=K)],
            input_map, final_map, [], splittable=False)

    ext = EXTERNAL_AGGREGATES.get(name)
    if ext is not None:
        return ext(arg_types, distinct, params)
    raise NotImplementedError(f"aggregate function {name}({arg_types})")


# pluggable aggregates (Plugin.getFunctions analogue for accumulator
# functions): presto_tpu.functions.* register `(arg_types, distinct, params)
# -> AggregateFunction` resolvers here; sql/analyzer.register_aggregate_name
# makes the parser route the call through aggregation planning
EXTERNAL_AGGREGATES: dict = {}


def register_aggregate(name: str, resolver) -> None:
    EXTERNAL_AGGREGATES[name.lower()] = resolver  # prestocheck: ignore[unbounded-cache] - plugin registry: one entry per registered function, not per request


def _sortable_i64(y):
    """Order-preserving map of a column into int64 (min_by/max_by ordering
    key). Integers/dates/bools widen; floats use the IEEE-754 total-order
    bit trick (negative values flip all bits, positives flip the sign bit,
    then re-biased into signed order)."""
    if jnp.issubdtype(y.dtype, jnp.floating):
        u = jax.lax.bitcast_convert_type(
            y.astype(jnp.float64), jnp.uint64)
        u = jnp.where((u >> jnp.uint64(63)) == 1, ~u,
                      u | jnp.uint64(1) << jnp.uint64(63))
        return jax.lax.bitcast_convert_type(
            u ^ (jnp.uint64(1) << jnp.uint64(63)), jnp.int64)
    return y.astype(jnp.int64)


def _hash_to_u64(a0):
    """Column -> uniform uint64 hash (bitcast floats so 1.25 != 1.75)."""
    if jnp.issubdtype(a0.dtype, jnp.floating):
        x = jax.lax.bitcast_convert_type(
            a0.astype(jnp.float64), jnp.int64).astype(jnp.uint64)
    else:
        x = a0.astype(jnp.int64).astype(jnp.uint64)
    return _sketch_mix(x)


def _sketch_mix(x):
    from .hash_join import _mix64
    return _mix64(x)


@dataclasses.dataclass
class AggregateCall:
    """One aggregate in a GROUP BY: function + input channels + step."""
    function: AggregateFunction
    input_channels: List[int]          # channels in the input page
    mask_channel: Optional[int] = None  # FILTER (WHERE ...) / mark-distinct channel
    # when consuming partial states (FINAL step), channels of the state columns:
    intermediate_channels: Optional[List[int]] = None
    # dictionary for the output block (min/max over varchar passes codes through):
    output_dictionary: Optional[object] = None
