"""Physical operator protocol and operator context.

Analogue of operator/Operator.java:20 (needsInput/addInput/getOutput/isBlocked/finish)
and operator/OperatorContext.java. The protocol is kept — it is what lets the Driver
pipeline arbitrary operator chains and lets blocking (join build, exchange) propagate —
but operators here hold *device arrays* and their compute methods are jitted closures,
so one addInput/getOutput hop is one fused XLA kernel launch, not a virtual call per row.

Stats: every operator records wall time + rows/pages in/out, rolled up by the driver
into pipeline/task stats (OperatorStats analogue for EXPLAIN ANALYZE).
"""
from __future__ import annotations

import abc
import dataclasses
import time
from typing import Any, Callable, List, Optional, Sequence

from ..block import Page
from ..memory import AggregatedMemoryContext, MemoryTrackingContext
from ..types import Type
from ..utils import trace


@dataclasses.dataclass
class OperatorStats:
    """operator/OperatorStats.java (narrowed)."""
    operator_id: int = 0
    name: str = ""
    add_input_calls: int = 0
    get_output_calls: int = 0
    input_rows: int = 0
    input_pages: int = 0
    output_rows: int = 0
    output_pages: int = 0
    add_input_ns: int = 0
    get_output_ns: int = 0
    finish_ns: int = 0
    # time this operator held its driver BLOCKED (build wait, backpressure),
    # attributed by the Driver when the parked driver next runs
    blocked_ns: int = 0
    peak_memory_bytes: int = 0

    def total_ns(self) -> int:
        return self.add_input_ns + self.get_output_ns + self.finish_ns

    def to_dict(self) -> dict:
        """JSON-safe form for the cluster control plane: each worker ships
        its task's operator stats inside TaskInfo so the coordinator's
        distributed EXPLAIN ANALYZE can roll them up (the reference ships
        OperatorStats inside TaskStatus the same way)."""
        return {"operator_id": self.operator_id, "name": self.name,
                "input_rows": self.input_rows,
                "output_rows": self.output_rows,
                "total_ns": self.total_ns(), "blocked_ns": self.blocked_ns,
                "peak_memory_bytes": self.peak_memory_bytes,
                "input_pages": self.input_pages,
                "output_pages": self.output_pages}


class OperatorContext:
    def __init__(self, operator_id: int, name: str,
                 memory: Optional[MemoryTrackingContext] = None,
                 worker: int = 0,
                 revoke_check: Optional[Callable[[], bool]] = None,
                 spill=None):
        self.worker = worker
        self.stats = OperatorStats(operator_id, name)
        self.memory = memory or MemoryTrackingContext(
            AggregatedMemoryContext(), AggregatedMemoryContext(), AggregatedMemoryContext())
        # memory-pressure probe: operators self-revoke (spill device state to
        # host, then host to disk when `spill` is attached) from their own
        # thread when this fires — thread-safe where an external revoker
        # thread mutating operator state would not be
        self._revoke_check = revoke_check
        # the query's disk tier (exec/spill.SpillManager) or None: operators
        # that can persist host-resident state use it as the ladder's last
        # revocation rung before the OOM killer would fire
        self.spill = spill
        self.user_memory = self.memory.user.new_local_memory_context(name)
        self.revocable_memory = self.memory.revocable.new_local_memory_context(name)

    def should_revoke(self) -> bool:
        return self._revoke_check is not None and self._revoke_check()

    def update_revocable(self, used: int, on_revoke: Callable[[], None]) -> None:
        """Account the operator's revocable device bytes; spill (on the calling
        thread) when the pool is over the revoke target."""
        self.revocable_memory.set_bytes(used)
        self.stats.peak_memory_bytes = max(self.stats.peak_memory_bytes, used)
        if used and self.should_revoke():
            # only fires under memory pressure (rare): the spill decision is
            # exactly what a post-mortem needs to see in the journal
            from ..utils import events
            events.emit("memory.spill", severity=events.WARN,
                        operator=self.stats.name, revocable_bytes=used)
            on_revoke()

    def release_memory(self) -> None:
        self.user_memory.close()
        self.revocable_memory.close()

    def record_input(self, page: Page, rows: int) -> None:
        self.stats.add_input_calls += 1
        self.stats.input_pages += 1
        self.stats.input_rows += rows

    def record_output(self, page: Page, rows: int) -> None:
        self.stats.output_pages += 1
        self.stats.output_rows += rows


class Operator(abc.ABC):
    """operator/Operator.java:20 — page-at-a-time pull/push protocol.

    Lifecycle: while not finished: if needs_input and input available: add_input(page);
    out = get_output(); finish() when upstream exhausted. is_blocked() returns a
    callable/future-like or None (blocking drives yield, like ListenableFuture in the
    reference)."""

    def __init__(self, context: OperatorContext):
        self.context = context
        self._finishing = False

    @property
    @abc.abstractmethod
    def output_types(self) -> List[Type]:
        ...

    def needs_input(self) -> bool:
        return not self._finishing

    @abc.abstractmethod
    def add_input(self, page: Page) -> None:
        ...

    @abc.abstractmethod
    def get_output(self) -> Optional[Page]:
        ...

    def finish(self) -> None:
        self._finishing = True

    def is_finished(self) -> bool:
        return self._finishing

    def is_blocked(self) -> Optional[Callable[[], bool]]:
        """None = not blocked; else a poll-able 'done?' callable."""
        return None

    def close(self) -> None:
        # drop this operator's reservations so pool pressure subsides as
        # operators retire (otherwise should_revoke stays latched and every
        # later operator spills on every page)
        self.context.release_memory()

    # spill protocol (operator/Operator.java:68 startMemoryRevoke/finishMemoryRevoke)
    def revocable_bytes(self) -> int:
        return 0

    def start_memory_revoke(self) -> None:
        pass

    def finish_memory_revoke(self) -> None:
        pass


class OperatorFactory(abc.ABC):
    """operator/OperatorFactory — one per plan node, creates per-driver instances.

    ONE factory serves every worker task of its fragment (the reference ships the
    factory list to each worker; here workers share the process, so sharing the
    factory also shares its jit-compiled kernels — each kernel traces once, not
    once per worker). `worker` selects worker-scoped state (splits, exchange
    pages, lookup-source slots)."""

    def __init__(self, operator_id: int, name: str):
        self.operator_id = operator_id
        self.name = name
        # wired by the local planner when the query has a memory context:
        self.memory_ctx = None        # MemoryTrackingContext (query-level)
        self.revoke_check = None      # () -> bool: pool over revoke target?
        self.spill_manager = None     # exec/spill.SpillManager (disk tier)

    @abc.abstractmethod
    def create_operator(self, worker: int = 0) -> Operator:
        ...

    def context(self, worker: int = 0) -> "OperatorContext":
        mem = self.memory_ctx.fork() if self.memory_ctx is not None else None
        return OperatorContext(self.operator_id, self.name, memory=mem,
                               worker=worker, revoke_check=self.revoke_check,
                               spill=self.spill_manager)

    def no_more_operators(self) -> None:
        pass


def timed(stats_field: str):
    """Decorator: accumulate wall-clock ns of an operator method into stats.

    Doubles as the flight recorder's operator tap: when a query trace is
    active, every call above the noise floor becomes an `operator` span —
    the stats and the timeline are measured by the same clock read."""
    method = stats_field.rsplit("_", 1)[0]  # "add_input_ns" -> "add_input"

    def deco(fn):
        def wrapper(self, *a, **kw):
            t0 = time.perf_counter_ns()
            try:
                return fn(self, *a, **kw)
            finally:
                dt = time.perf_counter_ns() - t0
                stats = self.context.stats
                setattr(stats, stats_field, getattr(stats, stats_field) + dt)
                if trace.active() is not None and \
                        dt >= trace.MIN_OPERATOR_SPAN_NS:
                    trace.record(trace.OPERATOR, f"{stats.name}.{method}",
                                 t0, dt)
        return wrapper
    return deco
