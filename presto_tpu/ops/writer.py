"""Table writer operator: pipeline sink feeding a ConnectorPageSink.

Analogue of operator/TableWriterOperator.java (+ TableFinishOperator's commit
step, which here happens in the runner after all writer drivers finish):
pages stream into the connector sink; at finish the operator emits ONE row —
the written-row count — exactly the wire shape INSERT/CTAS return."""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..block import Block, Page
from ..spi.connector import ConnectorPageSink
from ..types import BIGINT, Type
from .operator import Operator, OperatorContext, OperatorFactory, timed


class TableWriterOperator(Operator):
    def __init__(self, context: OperatorContext, sink: ConnectorPageSink,
                 remaps=None, column_dicts=None, casts=None):
        super().__init__(context)
        self.sink = sink
        # per-column dictionary-code remap arrays (None = pass through) and
        # the TABLE's dictionaries to rebind blocks to — written pages must
        # reference the table's (possibly extended) private dictionaries
        self.remaps = remaps
        self.column_dicts = column_dicts
        # per-column target Type (None = pass through): INSERT of typeless
        # NULL literals (UNKNOWN) retypes the block to the table's column
        self.casts = casts
        self._rows = 0
        self._emitted = False

    @property
    def output_types(self) -> List[Type]:
        return [BIGINT]

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        # the writer IS the device->host boundary: pages sink into host
        # files, so the transfers below are the operator's job, not overhead
        self._rows += int(np.asarray(page.mask).sum())  # prestocheck: ignore[host-sync]
        if self.casts is not None and any(c is not None for c in self.casts):
            blocks = []
            for b, t in zip(page.blocks, self.casts):
                if t is None:
                    blocks.append(b)
                else:
                    data = np.asarray(b.data).astype(t.np_dtype)  # prestocheck: ignore[host-sync]
                    blocks.append(Block(t, data, b.nulls, b.dictionary))
            page = Page(tuple(blocks), page.mask)
        if self.remaps is not None or self.column_dicts is not None:
            blocks = []
            mask_np = np.asarray(page.mask)  # prestocheck: ignore[host-sync]
            for i, b in enumerate(page.blocks):
                data = b.data
                remap = self.remaps[i] if self.remaps else None
                if callable(remap):  # virtual-source value-level re-encode
                    live = mask_np if b.nulls is None else \
                        (mask_np & ~np.asarray(b.nulls))  # prestocheck: ignore[host-sync]
                    data = remap(np.asarray(data), live)  # prestocheck: ignore[host-sync]
                elif remap is not None:
                    codes = np.clip(np.asarray(data).astype(np.int64), 0,  # prestocheck: ignore[host-sync]
                                    len(remap) - 1)
                    data = remap[codes]
                d = self.column_dicts[i] if self.column_dicts else b.dictionary
                blocks.append(Block(b.type, data, b.nulls, d))
            page = Page(tuple(blocks), page.mask)
        self.sink.append_page(page)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._finishing and not self._emitted:
            self._emitted = True
            out = Page((Block(BIGINT, np.asarray([self._rows],  # prestocheck: ignore[host-sync]
                                                 dtype=np.int64)),),
                       np.ones(1, dtype=bool))
            self.context.record_output(out, 1)
            return out
        return None

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class TableWriterOperatorFactory(OperatorFactory):
    """One sink per worker (the runner collects every sink's fragments for the
    metadata commit — TableFinishOperator's role)."""

    def __init__(self, operator_id: int, sink_provider, insert_handle,
                 remaps=None, column_dicts=None, casts=None):
        super().__init__(operator_id, "TableWriter")
        self._provider = sink_provider
        self._handle = insert_handle
        self._remaps = remaps
        self._column_dicts = column_dicts
        self._casts = casts
        self.sinks: List[ConnectorPageSink] = []

    def create_operator(self, worker: int = 0) -> TableWriterOperator:
        sink = self._provider.create_page_sink(self._handle)
        self.sinks.append(sink)
        return TableWriterOperator(self.context(worker), sink,
                                   self._remaps, self._column_dicts,
                                   self._casts)
