"""TopN, full sort, and limit operators.

Analogue of operator/TopNOperator.java:35 (+GroupedTopNBuilder.java:49),
operator/OrderByOperator.java (PagesIndex sort) and operator/LimitOperator.java.

TPU re-design: the reference keeps a row heap; a heap is serial. Here TopN keeps a
fixed N-row device buffer and, per page, sorts [buffer ++ page] by the order key and
keeps the first N — O((N+cap) log) fully on the VPU's bitonic sorter, which for the
N<<cap case is the same asymptotics as the heap without the pointer chasing.

Order keys: multi-column, asc/desc, nulls-last. DESC on numerics sorts by the negated
(or bit-flipped) value; varchar sorts by dictionary rank (Dictionary.sort_keys).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..block import Block, Dictionary, Page
from ..types import Type, is_string
from .operator import Operator, OperatorContext, OperatorFactory, timed
from .sorting import lexsort_fast


@dataclasses.dataclass(frozen=True)
class SortOrder:
    channel: int
    descending: bool = False
    nulls_first: bool = False


def _sort_key_arrays(page: Page, orders: Sequence[SortOrder]) -> Tuple[jnp.ndarray, ...]:
    """Build lexsort key arrays (major key LAST, per jnp.lexsort convention).
    Invalid rows always sort to the very end (handled by caller appending ~mask)."""
    keys = []
    for o in reversed(orders):
        b = page.blocks[o.channel]
        x = b.data
        if is_string(b.type) and b.dictionary is not None:
            d = b.dictionary
            if hasattr(d, "values"):
                ranks = jnp.asarray(d.sort_keys())
                x = ranks[x]
            elif not getattr(d, "monotonic", False):
                # virtual dictionaries sort by code only when the format is
                # order-preserving (e.g. zero-padded Supplier#%09d)
                raise NotImplementedError(
                    f"ORDER BY over non-monotonic virtual dictionary {d!r}")
        if x.dtype == jnp.bool_:
            x = x.astype(jnp.int32)
        if b.nulls is not None:
            # neutralize the undefined payload under a null so null rows
            # order by the REMAINING sort keys (ties among nulls break on
            # the next ORDER BY column, matching the N-way merge comparator
            # in cluster/exchange_client.py MergingRemoteSource)
            x = jnp.where(b.nulls, jnp.zeros((), dtype=x.dtype), x)
        if o.descending:
            x = -x
        keys.append(x)
        if b.nulls is not None:
            # appended AFTER the value => more significant in lexsort: null rows sort
            # wholly before/after non-null rows regardless of their payload value
            nullv = jnp.asarray(-1 if o.nulls_first else 1, dtype=jnp.int32)
            keys.append(jnp.where(b.nulls, nullv, 0))
    return tuple(keys)


def topn_merge_stage(page: Page, buffer: Optional[Page],
                     orders: Tuple[SortOrder, ...], n: int) -> Page:
    """Pure TopN contribution: sort [page ++ buffer], keep the first n rows.

    The un-jitted stage body — the standalone operator dispatches the jitted
    `_topn_merge` below; a fused pipeline segment (ops/fused_segment.py)
    inlines this into its one-kernel-per-page composition with the buffer
    threaded through as a jit argument."""
    if buffer is not None:
        blocks = tuple(
            Block(b.type,
                  jnp.concatenate([b.data, bb.data]),
                  None if b.nulls is None and bb.nulls is None else
                  jnp.concatenate([b.null_mask(), bb.null_mask()]),
                  b.dictionary)
            for b, bb in zip(page.blocks, buffer.blocks))
        merged = Page(blocks, jnp.concatenate([page.mask, buffer.mask]))
    else:
        merged = page
    keys = _sort_key_arrays(merged, orders) + (~merged.mask,)
    order = lexsort_fast(keys)
    top = order[:n]
    blocks = []
    for b in merged.blocks:
        nulls = b.nulls[top] if b.nulls is not None else None
        blocks.append(Block(b.type, b.data[top], nulls, b.dictionary))
    return Page(tuple(blocks), merged.mask[top])


# shared across operator instances: one compile per (schema, orders, n)
_topn_merge = functools.partial(jax.jit, static_argnames=("orders", "n"))(
    topn_merge_stage)


class TopNOperator(Operator):
    def __init__(self, context: OperatorContext, n: int, orders: List[SortOrder],
                 types: List[Type], dicts: List[Optional[Dictionary]]):
        super().__init__(context)
        self.n = n
        self.orders = tuple(orders)
        self._types = types
        self._dicts = dicts
        self._buffer: Optional[Page] = None
        self._emitted = False

    @property
    def output_types(self) -> List[Type]:
        return self._types

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        self._buffer = _topn_merge(page, self._buffer, self.orders, self.n)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._finishing and not self._emitted:
            self._emitted = True
            if self._buffer is not None:
                self.context.record_output(self._buffer, self.n)
                return self._buffer
        return None

    def is_finished(self) -> bool:
        return self._finishing and self._emitted


class TopNOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, n: int, orders: List[SortOrder],
                 types: List[Type], dicts: Optional[List[Optional[Dictionary]]] = None):
        super().__init__(operator_id, "TopN")
        self.n = n
        self.orders = orders
        self.types = types
        self.dicts = dicts or [None] * len(types)

    def create_operator(self, worker: int = 0) -> TopNOperator:
        return TopNOperator(self.context(worker),
                            self.n, self.orders, self.types, self.dicts)


class OrderByOperator(Operator):
    """Full sort: buffers all pages, sorts once at finish (OrderByOperator.java).
    Spill arrives with the revocation rev; a query-sized sort fits HBM for the TPC
    workloads this round targets."""

    def __init__(self, context: OperatorContext, orders: List[SortOrder],
                 types: List[Type], dicts, output_channels: Optional[List[int]] = None):
        super().__init__(context)
        self.orders = orders
        self._types = types
        self._dicts = dicts
        self.output_channels = output_channels
        self._pages: List[Page] = []
        self._result: Optional[List[Page]] = None

    @property
    def output_types(self) -> List[Type]:
        if self.output_channels is None:
            return self._types
        return [self._types[c] for c in self.output_channels]

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        self._pages.append(page)

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        self._result = self._sort() if self._pages else []

    def _sort(self) -> List[Page]:
        cap = self._pages[0].capacity
        merged_blocks = []
        for i in range(len(self._pages[0].blocks)):
            datas = jnp.concatenate([p.blocks[i].data for p in self._pages])
            anynull = any(p.blocks[i].nulls is not None for p in self._pages)
            nulls = (jnp.concatenate([p.blocks[i].null_mask() for p in self._pages])
                     if anynull else None)
            b0 = self._pages[0].blocks[i]
            merged_blocks.append(Block(b0.type, datas, nulls, b0.dictionary))
        mask = jnp.concatenate([p.mask for p in self._pages])
        merged = Page(tuple(merged_blocks), mask)
        keys = _sort_key_arrays(merged, self.orders) + (~merged.mask,)
        order = lexsort_fast(keys)
        blocks = []
        for b in merged.blocks:
            nulls = b.nulls[order] if b.nulls is not None else None
            blocks.append(Block(b.type, b.data[order], nulls, b.dictionary))
        sorted_page = Page(tuple(blocks), merged.mask[order])
        if self.output_channels is not None:
            sorted_page = sorted_page.select_channels(self.output_channels)
        # re-page to capacity-sized pages
        out = []
        total = sorted_page.capacity
        for lo in range(0, total, cap):
            hi = min(lo + cap, total)
            blocks = []
            for b in sorted_page.blocks:
                seg = b.data[lo:hi]
                if hi - lo < cap:
                    seg = jnp.concatenate([seg, jnp.zeros(cap - (hi - lo), seg.dtype)])
                nseg = None
                if b.nulls is not None:
                    nseg = b.nulls[lo:hi]
                    if hi - lo < cap:
                        nseg = jnp.concatenate(
                            [nseg, jnp.zeros(cap - (hi - lo), jnp.bool_)])
                blocks.append(Block(b.type, seg, nseg, b.dictionary))
            m = sorted_page.mask[lo:hi]
            if hi - lo < cap:
                m = jnp.concatenate([m, jnp.zeros(cap - (hi - lo), jnp.bool_)])
            out.append(Page(tuple(blocks), m))
        return out

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._result:
            out = self._result.pop(0)
            self.context.record_output(out, out.capacity)
            return out
        return None

    def is_finished(self) -> bool:
        return self._finishing and self._result is not None and not self._result


class OrderByOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, orders, types, dicts=None,
                 output_channels=None):
        super().__init__(operator_id, "OrderBy")
        self.orders = orders
        self.types = types
        self.dicts = dicts or [None] * len(types)
        self.output_channels = output_channels

    def create_operator(self, worker: int = 0) -> OrderByOperator:
        return OrderByOperator(self.context(worker),
                               self.orders, self.types, self.dicts,
                               self.output_channels)


class LimitOperator(Operator):
    """operator/LimitOperator.java — passes through the first `limit` live rows."""

    def __init__(self, context: OperatorContext, limit: int, types: List[Type]):
        super().__init__(context)
        self.remaining = limit
        self._types = types
        self._pending: Optional[Page] = None

    @property
    def output_types(self) -> List[Type]:
        return self._types

    def needs_input(self) -> bool:
        return not self._finishing and self._pending is None and self.remaining > 0

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        live = jnp.cumsum(page.mask.astype(jnp.int32))
        keep = page.mask & (live <= self.remaining)
        taken = int(jnp.sum(keep.astype(jnp.int32)))
        self.remaining -= taken
        self._pending = page.with_mask(keep)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        out, self._pending = self._pending, None
        if out is not None:
            self.context.record_output(out, out.capacity)
        if self.remaining <= 0:
            self._finishing = True
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None


class LimitOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, limit: int, types: List[Type]):
        super().__init__(operator_id, "Limit")
        self.limit = limit
        self.types = types

    def create_operator(self, worker: int = 0) -> LimitOperator:
        return LimitOperator(self.context(worker), self.limit, self.types)
