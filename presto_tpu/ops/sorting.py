"""Shared sort-order kernels.

`jnp.lexsort` lowers to one stable variadic sort pass per key, and XLA's
comparator-based sorts are ~5-8x slower than the single-array sort fast path
(measured on both the CPU and TPU backends). Since SQL group/order keys are
almost always ints with modest ranges (keys, dates, dictionary codes, flags),
`lexsort_fast` packs every key column into ONE int64 — bias each column to
zero by its batch minimum, multiply into mixed-radix digits, append the row
index as the lowest digit — and sorts that single array. The row index digit
makes the pack unique per row, so the result is stable and the permutation
falls out of a modulo. A `lax.cond` guards the packed-domain overflow case
and falls back to the general lexsort inside the same compiled kernel.

Float keys take the general path unconditionally: their bit patterns span
nearly the whole int64 line, so the packed domain can never fit — and the
order-preserving f64->s64 bitcast is rejected by XLA's TPU x64 rewriter
anyway. The dtype check is static (trace time), so float-keyed sorts compile
straight to jnp.lexsort with zero overhead.

This is the engine's answer to the reference's compiled `OrderingCompiler`
(sql/gen/OrderingCompiler.java): specialize the comparator at runtime —
except here the specialization turns the comparator into integer arithmetic
the hardware sorts natively.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

def _to_sortable_i64(k: jnp.ndarray) -> jnp.ndarray:
    """Map an integral/bool key column to int64 preserving its sort order."""
    return k.astype(jnp.int64)


def lexsort_fast(keys: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
    """Drop-in `jnp.lexsort(keys)`: stable permutation ordering rows by the
    key columns, LAST key primary (the numpy/jnp lexsort convention).

    Returns int32 positions. Jit-safe: the packed/fallback choice is a
    `lax.cond` on the measured key ranges, so one compiled kernel serves any
    data distribution.
    """
    assert keys, "lexsort_fast needs at least one key"
    n = keys[0].shape[0]
    if n == 0:
        return jnp.zeros(0, dtype=jnp.int32)
    if any(jnp.issubdtype(k.dtype, jnp.floating) for k in keys):
        # float bit spans overflow the packed domain in all but degenerate
        # cases, and the TPU backend cannot bitcast f64->s64 at all: the
        # general sort is both the safe and the fast choice here
        return jnp.lexsort(tuple(keys)).astype(jnp.int32)
    ks = [_to_sortable_i64(k) for k in keys]
    mins = [jnp.min(k) for k in ks]
    maxs = [jnp.max(k) for k in ks]

    # overflow check in float64: int64 `max - min` itself wraps for wide
    # domains (e.g. float bit patterns spanning nearly the whole i64 line),
    # so the spans feeding the branch decision must never touch int math.
    # 2**61 leaves margin for the <=2^11 ulp error of rounding i64 -> f64.
    span = jnp.asarray(float(n), dtype=jnp.float64)
    for mn, mx in zip(mins, maxs):
        span = span * (mx.astype(jnp.float64) - mn.astype(jnp.float64) + 1.0)
    fits = span < float(2 ** 61)

    iota = jnp.arange(n, dtype=jnp.int64)

    def packed(_):
        # under `fits`, every per-column span (and their product) < 2^61,
        # so the int arithmetic below cannot overflow
        base = jnp.zeros(n, dtype=jnp.int64)
        # primary key (last) becomes the most significant digit
        for k, mn, mx in zip(reversed(ks), reversed(mins), reversed(maxs)):
            r = jnp.maximum(mx - mn + 1, 1)
            base = base * r + (k - mn)
        return (jnp.sort(base * n + iota) % n).astype(jnp.int32)

    def general(_):
        return jnp.lexsort(tuple(keys)).astype(jnp.int32)

    return jax.lax.cond(fits, packed, general, None)
