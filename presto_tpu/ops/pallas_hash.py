"""Masked open-addressing hash tables in Pallas: join build/probe + grouping.

The engine's join and aggregation strategies are sort-based because XLA's
scatter is weak on TPU — but the reference engine's hash build
(operator/PagesHash.java:34, MultiChannelGroupByHash.java:54) is the
motivating case, and the deferred VERDICT ask is "one Pallas kernel that
wins — or a written negative result". This module is that kernel pair:

- **insert** — a power-of-two-slot table with linear probing. Insertion is
  vectorized over PROBE DISTANCE, not serialized over rows: every still-
  pending row bids for slot ``(h(key) + d) & (S - 1)`` in round ``d``; the
  winner of an empty slot (scatter-min over row ids — the scatter-bound
  build the reference does with CAS loops) claims it, rows whose key already
  owns the slot adopt it (insert-or-lookup: the grouping path's group id),
  and everyone else carries to round ``d + 1``. The trip count is FIXED at
  trace time (mask-based termination, no data-dependent control flow — the
  Pallas/TPU contract); rows still pending after the last round raise the
  ``overflow`` flag so callers fall back to the sorted path instead of
  silently dropping rows.
- **probe** — fixed-trip linear scan from ``h(key)``: a key match yields the
  stored row id, an EMPTY slot terminates as a miss (mask-based ``done``
  accumulation). The required trip count is the longest occupied run in the
  table — measured by the build (a doubled-array prefix-max, not a host
  loop) and handed to the probe as a static, pow2-bucketed trip count so
  adversarial clustering can never truncate a scan.

Both kernels run through ``pl.pallas_call``; off-TPU they run with
``interpret=True`` so correctness and benches run in tier-1 on CPU today and
the SAME kernel is TPU-ready. Load factor is held at <= 0.5
(``table_slots`` returns 2N slots), which keeps expected probe distances
O(1) under the mix64 hash the rest of the engine already routes with.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from ..utils import kernel_cache

# insert rounds per build attempt: with load <= 0.5 and a mixed hash the
# expected max probe distance is O(log n / log log n); 64 rounds is far past
# any non-adversarial clustering, and the overflow flag catches the rest
INSERT_TRIPS = 64
# a probe that must scan this many slots per row has already lost to the
# sorted path; builds whose longest occupied run exceeds it fall back
PROBE_TRIPS_CAP = 1 << 12
# table-size ceiling (slots): beyond this the build falls back to sorted —
# on a real TPU a larger table would also outgrow VMEM residency
MAX_TABLE_SLOTS = 1 << 22

EMPTY = -1  # free-slot / miss sentinel (plain int: kernels must not capture jnp constants)


@functools.lru_cache(maxsize=1)
def interpret_mode() -> bool:
    """Pallas interprets everywhere except on a real TPU backend — the
    kernels are correctness-identical either way (the differential suite
    runs them interpreted on CPU in tier-1)."""
    return jax.default_backend() != "tpu"


def table_slots(n_rows: int) -> Optional[int]:
    """Power-of-two slot count at load factor <= 0.5, or None when the
    table would exceed the slot ceiling (callers fall back to sorted)."""
    slots = 1 << max(4, (2 * max(int(n_rows), 1) - 1).bit_length())
    return slots if slots <= MAX_TABLE_SLOTS else None


# THE engine-wide 64-bit mixer: exchange routing, join builds and these
# tables must hash identically so they can never disagree on placement —
# one definition, imported (hash_join imports this module lazily, so there
# is no cycle)
from .hash_join import _mix64  # noqa: E402


def _hash_base(comps: Sequence[jnp.ndarray], slots: int) -> jnp.ndarray:
    """Row -> home slot. Multi-component keys fold through the mixer the
    same way combined_key does; the table compares FULL components on every
    probe, so hash collisions cost probes, never correctness."""
    acc = _mix64(comps[0])
    for c in comps[1:]:
        acc = _mix64(acc ^ (c.astype(jnp.uint64) *
                            jnp.uint64(0x9E3779B97F4A7C15)))
    return (acc & jnp.uint64(slots - 1)).astype(jnp.int32)


def _max_occupied_run(used: jnp.ndarray) -> jnp.ndarray:
    """Longest circular run of occupied slots (the probe's worst-case scan:
    adjacent clusters merge, so this can exceed any single insert's probe
    distance). Doubled-array prefix-max of the last-empty index — load
    <= 0.5 guarantees an empty slot, so no run wraps the full table."""
    S = used.shape[0]
    u2 = jnp.concatenate([used, used])
    idx = jnp.arange(2 * S, dtype=jnp.int32)
    last_empty = lax.cummax(jnp.where(u2, jnp.int32(-1), idx))
    return jnp.max((idx - last_empty)[S:])


# ---------------------------------------------------------------------------
# insert kernel
# ---------------------------------------------------------------------------

def _insert_body(ncomps: int, slots: int, trips: int):
    """Kernel body for ``pl.pallas_call``: refs are
    [comp_0..comp_{n-1}, mask] -> [slot_comp_0.., slot_rows, gid, stats]."""

    def kernel(*refs):
        comp_refs = refs[:ncomps]
        mask_ref = refs[ncomps]
        out_comps = refs[ncomps + 1: 2 * ncomps + 1]
        rows_ref = refs[2 * ncomps + 1]
        gid_ref = refs[2 * ncomps + 2]
        stats_ref = refs[2 * ncomps + 3]
        comps = [r[:] for r in comp_refs]
        mask = mask_ref[:]
        n = mask.shape[0]
        h = _hash_base(comps, slots)
        rowid = lax.broadcasted_iota(jnp.int32, (n, 1), 0).reshape(n)

        def one_round(_d, carry):
            used, slot_comps, slot_rows, gid, pending, dist = carry
            cand = (h + dist) & (slots - 1)
            # bid for empty slots: the scatter-min winner claims the slot
            tryers = pending & ~used[cand]
            bid_tgt = jnp.where(tryers, cand, slots)
            claims = jnp.full(slots, n, dtype=jnp.int32).at[bid_tgt].min(
                rowid, mode="drop")
            winner = tryers & (claims[cand] == rowid)
            wtgt = jnp.where(winner, cand, slots)
            used = used.at[wtgt].set(True, mode="drop")
            slot_comps = tuple(
                sc.at[wtgt].set(c, mode="drop")
                for sc, c in zip(slot_comps, comps))
            slot_rows = slot_rows.at[wtgt].set(rowid, mode="drop")
            # a slot now holding this row's key resolves it (claimed by this
            # row, claimed this round by a same-key sibling, or pre-existing)
            same = used[cand]
            for sc, c in zip(slot_comps, comps):
                same = same & (sc[cand] == c)
            resolved = pending & same
            gid = jnp.where(resolved, cand, gid)
            pending = pending & ~resolved
            dist = jnp.where(pending, dist + 1, dist)
            return used, slot_comps, slot_rows, gid, pending, dist

        init = (jnp.zeros(slots, dtype=jnp.bool_),
                tuple(jnp.zeros(slots, dtype=jnp.int64)
                      for _ in range(ncomps)),
                jnp.full(slots, EMPTY, dtype=jnp.int32),
                jnp.full(n, EMPTY, dtype=jnp.int32),
                mask,
                jnp.zeros(n, dtype=jnp.int32))
        used, slot_comps, slot_rows, gid, pending, _dist = lax.fori_loop(
            0, trips, one_round, init)
        for ref, sc in zip(out_comps, slot_comps):
            ref[:] = sc
        rows_ref[:] = slot_rows
        gid_ref[:] = gid
        stats_ref[:] = jnp.stack([
            jnp.any(pending).astype(jnp.int32),          # overflow
            _max_occupied_run(used).astype(jnp.int32),   # probe scan bound
            jnp.sum(used.astype(jnp.int32)),             # distinct keys (ng)
        ]).astype(jnp.int32)
    return kernel


def insert_table(comps: Tuple[jnp.ndarray, ...], mask: jnp.ndarray,
                 slots: int, trips: int = 0):
    """Traceable insert-or-lookup: build the open-addressing table over the
    masked rows of ``comps`` (each component cast to int64).

    Returns ``(slot_comps, slot_rows, gid, stats)``:
    - slot_comps: per-component (slots,) int64 key storage (empty = garbage,
      gated by slot_rows)
    - slot_rows: (slots,) int32 — FIRST inserting row id per slot, EMPTY(-1)
      for free slots
    - gid: (n,) int32 — each masked row's slot (its dense-ish group id);
      EMPTY for masked-off or overflowed rows
    - stats: (3,) int32 — [overflow_flag, max_occupied_run, distinct_keys]
    """
    trips = trips or INSERT_TRIPS
    ncomps = len(comps)
    comps = tuple(c.astype(jnp.int64) for c in comps)
    n = comps[0].shape[0]
    out_shape = (
        tuple(jax.ShapeDtypeStruct((slots,), jnp.int64)
              for _ in range(ncomps)) +
        (jax.ShapeDtypeStruct((slots,), jnp.int32),
         jax.ShapeDtypeStruct((n,), jnp.int32),
         jax.ShapeDtypeStruct((3,), jnp.int32)))
    outs = pl.pallas_call(
        _insert_body(ncomps, slots, trips),
        out_shape=out_shape,
        interpret=interpret_mode(),
    )(*comps, mask)
    slot_comps = tuple(outs[:ncomps])
    slot_rows, gid, stats = outs[ncomps], outs[ncomps + 1], outs[ncomps + 2]
    return slot_comps, slot_rows, gid, stats


def insert_table_jit(ncomps: int, n: int, slots: int,
                     trips: int = 0):
    """Cached jitted wrapper for the eager (operator-level) build call —
    keyed on the static shape signature so identical builds across queries
    and workers replay one compile."""
    trips = trips or INSERT_TRIPS
    return kernel_cache.get_or_install(
        ("pallas-insert", ncomps, n, slots, trips, interpret_mode()),
        lambda: jax.jit(functools.partial(insert_table, slots=slots,
                                          trips=trips)))


# ---------------------------------------------------------------------------
# probe kernel
# ---------------------------------------------------------------------------

def _probe_body(slots: int, trips: int):
    def kernel(sk_ref, sr_ref, key_ref, mask_ref, out_ref):
        sk = sk_ref[:]
        sr = sr_ref[:]
        key = key_ref[:]
        mask = mask_ref[:]
        n = key.shape[0]
        h = _hash_base([key], slots)

        def one_trip(d, carry):
            row, done = carry
            cand = (h + d) & (slots - 1)
            srow = sr[cand]
            occupied = srow >= 0
            hit = ~done & occupied & (sk[cand] == key)
            row = jnp.where(hit, srow, row)
            # an empty slot ends the cluster: everything after is a miss
            done = done | hit | ~occupied
            return row, done

        row, _done = lax.fori_loop(
            0, trips, one_trip,
            (jnp.full(n, EMPTY, dtype=jnp.int32), ~mask))
        out_ref[:] = row
    return kernel


def probe_table(slot_keys: jnp.ndarray, slot_rows: jnp.ndarray,
                keys: jnp.ndarray, mask: jnp.ndarray, trips: int):
    """Traceable probe: per masked probe row, the matching build row id or
    EMPTY(-1) — the miss mask is ``result < 0``. ``trips`` must be the
    build's max-occupied-run bound (pow2-bucketed by the caller so the trace
    signature stays small); masked rows never match."""
    keys = keys.astype(jnp.int64)
    n = keys.shape[0]
    # traceable helper: only ever invoked inside the module-level-jitted
    # probe_match_pallas wrapper (ops/hash_join.py), so the fresh
    # pallas_call identity is cached by the outer trace, not re-dispatched
    return pl.pallas_call(  # prestocheck: ignore[cache-key-hygiene]
        _probe_body(slot_keys.shape[0], trips),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        interpret=interpret_mode(),
    )(slot_keys, slot_rows, keys, mask)


def probe_trips_for(max_run: int) -> int:
    """Static probe trip count for a measured longest occupied run: the run
    plus its terminating empty slot, bucketed to pow2 (bounded compile
    diversity — one probe kernel per bucket, not per build)."""
    return 1 << max(3, int(max_run)).bit_length()
