"""Hash aggregation on TPU: sort-based and direct-index grouping kernels.

Analogue of operator/HashAggregationOperator.java:47 with
operator/aggregation/builder/InMemoryHashAggregationBuilder and the group-by hashes
(MultiChannelGroupByHash.java:54, BigintGroupByHash fast path).

TPU re-design (NOT a translation): open-addressing with per-row scatter is serial and
hostile to the VPU, so grouping is done with the two strategies that vectorize:

1. DIRECT: when every group key is a small-domain integer (dictionary codes, flags),
   group id = linear index over the domain product; aggregation is one segment-reduce
   into a dense state table. This is the BigintGroupByHash analogue and covers TPC-H
   Q1 (4 groups) with zero sorts.
2. SORT: general case — lexicographic sort of the key columns, adjacent-difference
   group boundaries, segment-reduce. Exact (no hash collisions), static shapes,
   O(n log n) on the TPU's bitonic sorter. Research on TPU databases reaches the same
   conclusion: sort + segment-reduce beats scatter hash tables on this hardware.
3. PALLAS (the `hash_kernels` session property): the SORT builder swaps its
   per-page sort+reduce for insert-or-accumulate through the open-addressing
   Pallas table (ops/pallas_hash.py) once the first page proves the group
   count is table-friendly — each row's key claims/finds a slot, and
   contributions segment-reduce straight into the slot table. Slot-indexed
   partials feed the existing fold unchanged; an insert overflow falls back
   to the sort kernel permanently (never a wrong result). This is the
   measured answer to "does a scatter hash table ever beat sort here" —
   differential-tested row-identical either way.

Cross-page accumulation keeps a compact state table (<= max_groups) plus a pending
buffer of per-page partials; when the buffer fills it is folded into the table by the
same sort+segment kernel (the tree-combine is the analogue of partial->final
aggregation inside one operator).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..block import Block, Dictionary, Page
from ..types import BIGINT, BOOLEAN, Type, is_string
from ..utils import kernel_cache
from .aggregates import ACARRY, AMAX, AMIN, MAX, MIN, SUM, AggregateCall
from .operator import Operator, OperatorContext, OperatorFactory, timed
from .sorting import lexsort_fast


def _builder_key(tag, b, page: "Page" = None, input_dicts=None) -> tuple:
    """Kernel-cache identity of a builder's static config: everything its
    jitted kernel reads from `self` (channels, call fingerprints, domains)
    PLUS the input page's dictionary versions — _call_contributions embeds
    `d.sort_keys()` as a trace constant for min/max over unsorted
    dictionaries, and Dictionary.extend mutates IN PLACE (same identity), so
    the (token, len) version must be part of the key or an INSERT-extended
    dictionary would replay a stale kernel. `input_dicts` supplies the
    dictionaries directly when the caller knows the builder's input layout
    without a live page (the fused-segment compiler)."""
    dicts = ()
    if page is not None:
        dicts = tuple(kernel_cache.dict_key(blk.dictionary)
                      for blk in page.blocks)
    elif input_dicts is not None:
        dicts = tuple(kernel_cache.dict_key(d) for d in input_dicts)
    return ("agg", tag,
            tuple(t.name for t in getattr(b, "key_types", ())),
            getattr(b, "_key_channels", None),
            tuple(getattr(b, "domains", ())),
            b.from_intermediate,
            dicts,
            tuple(kernel_cache.agg_call_key(c) for c in b.calls))


def _segment_reduce(kind: str, values, seg_ids, num_segments: int):
    if kind == SUM:
        return jax.ops.segment_sum(values, seg_ids, num_segments=num_segments)
    if kind == MIN:
        return jax.ops.segment_min(values, seg_ids, num_segments=num_segments)
    if kind == MAX:
        return jax.ops.segment_max(values, seg_ids, num_segments=num_segments)
    raise AssertionError(kind)


WIDE_STATE_MAX_GROUPS = 1 << 13  # scatter-table bound for sketch aggregates


def _state_widths(calls) -> Tuple[int, ...]:
    return tuple(col.width for c in calls for col in c.function.state)


def _empty_state(kind_count_widths):
    """Zero-group state arrays matching each column's width."""
    return tuple(jnp.zeros((0, w) if w > 1 else 0, dtype=np.float64)
                 for w in kind_count_widths)


def _reduce_contrib(kind: str, c, gid, num_segments: int, width: int,
                    ident):
    """Reduce one contribution column into a (num_segments[, width]) state.

    Wide (vector) state columns arrive as a `(bucket, value)` tuple per row and
    scatter into state[group, bucket] — never materializing a rows x width
    one-hot. Scalar columns segment-reduce as before. 2-D plain arrays are
    already-built states being re-grouped (combine path)."""
    if isinstance(c, tuple):
        bucket, vals = c
        base = jnp.full((num_segments, width), ident, dtype=vals.dtype)
        at = base.at[gid, bucket]
        upd = at.add if kind == SUM else (at.min if kind == MIN else at.max)
        return upd(vals, mode="drop")
    return _segment_reduce(kind, c, gid, num_segments)


def _reduce_all(contribs, kinds, identities, widths, gid, out_groups):
    """Reduce every contribution column into (out_groups,) states, handling
    the AMIN/AMAX + ACARRY pairs jointly: segment argmin/argmax over the
    ordering key, then gather the winning row's payload (min_by/max_by).
    Rows routed to the trash segment (gid == out_groups) are excluded."""
    n_seg = out_groups + 1
    states = []
    i = 0
    while i < len(kinds):
        kind = kinds[i]
        if kind in (AMIN, AMAX):
            y = contribs[i]
            seg = jax.ops.segment_min if kind == AMIN else jax.ops.segment_max
            m = seg(y, gid, num_segments=n_seg)
            nr = y.shape[0]
            idx = jnp.arange(nr, dtype=jnp.int32)
            best = jnp.where(y == m[gid], idx, nr)
            first = jax.ops.segment_min(best, gid, num_segments=n_seg)
            win = jnp.clip(first, 0, max(nr - 1, 0))
            states.append(m[:out_groups])
            i += 1
            while i < len(kinds) and kinds[i] == ACARRY:
                states.append(contribs[i][win][:out_groups])
                i += 1
            continue
        states.append(_reduce_contrib(kind, contribs[i], gid, n_seg,
                                      widths[i], identities[i])[:out_groups])
        i += 1
    return states


def _merge_tables(kinds, old, new):
    """Element-wise combine of two same-shape state tables (cross-page fold
    of the direct builder), joint over AMIN/AMAX + ACARRY pairs."""
    out = []
    i = 0
    while i < len(kinds):
        kind = kinds[i]
        if kind in (AMIN, AMAX):
            better = (new[i] < old[i]) if kind == AMIN else (new[i] > old[i])
            out.append(jnp.where(better, new[i], old[i]))
            i += 1
            while i < len(kinds) and kinds[i] == ACARRY:
                out.append(jnp.where(better, new[i], old[i]))
                i += 1
            continue
        if kind == SUM:
            out.append(old[i] + new[i])
        elif kind == MIN:
            out.append(jnp.minimum(old[i], new[i]))
        else:
            out.append(jnp.maximum(old[i], new[i]))
        i += 1
    return out


def _where_valid(gvalid, s, ident):
    """Identity-fill invalid group slots, broadcasting over vector states."""
    cond = gvalid[:, None] if s.ndim == 2 else gvalid
    return jnp.where(cond, s, jnp.asarray(ident, dtype=s.dtype))


def _fill(shape, dtype, value):
    return jnp.full(shape, value, dtype=dtype)


def _null_safe_keys(page: Page, key_channels) -> Tuple:
    """Interleaved (value, is_null) arrays per key channel.

    SQL groups NULL as its OWN key (reference: MultiChannelGroupByHash over
    nullable blocks), so the null flag joins the sort key and the value lane is
    zeroed under NULL — two NULL rows always collide, and never with value 0."""
    out = []
    for c in key_channels:
        b = page.blocks[c]
        if b.nulls is not None:
            flag = b.nulls
            data = jnp.where(flag, jnp.zeros((), dtype=b.data.dtype), b.data)
        else:
            flag = jnp.zeros(page.mask.shape, dtype=jnp.bool_)
            data = b.data
        out.append(data)
        out.append(flag)
    return tuple(out)


def _call_contributions(calls, page: Page, from_intermediate: bool):
    """Per-row state contributions for every call, SQL-null-aware: a NULL input row
    contributes nothing (mask excludes it), matching the reference accumulators'
    @SqlNullable handling."""
    datas = tuple(b.data for b in page.blocks)
    mask = page.mask
    contribs = []
    for call in calls:
        if from_intermediate:
            for ch in call.intermediate_channels:
                contribs.append(datas[ch])
        else:
            args = []
            for ai, c in enumerate(call.input_channels):
                a = datas[c]
                d = page.blocks[c].dictionary
                name = call.function.name
                ordering_arg = name in ("min", "max") or \
                    (name in ("min_by", "max_by") and ai == 1)
                if ordering_arg and d is not None and not d.is_sorted():
                    # codes of an INSERT-extended dictionary are append-ordered,
                    # not lexicographic — compare RANKS instead; min/max's
                    # output path maps the winning rank back to a code
                    # (min_by/max_by discard the ordering state, so no
                    # back-mapping is needed there)
                    a = jnp.asarray(d.sort_keys())[a]
                args.append(a)
            args = tuple(args)
            m = mask
            skip = call.function.null_skip_channels
            for ai, c in enumerate(call.input_channels):
                if skip is not None and ai not in skip:
                    continue  # NULL here does not exclude the row (min_by x)
                if page.blocks[c].nulls is not None:
                    m = m & ~page.blocks[c].nulls
            if call.mask_channel is not None:
                mc = datas[call.mask_channel].astype(jnp.bool_)
                if page.blocks[call.mask_channel].nulls is not None:
                    mc = mc & ~page.blocks[call.mask_channel].nulls
                m = m & mc
            if call.function.needs_arg_nulls:
                arg_nulls = tuple(page.blocks[c].null_mask()
                                  for c in call.input_channels)
                contribs.extend(call.function.input_map(args, arg_nulls, m))
            else:
                contribs.extend(call.function.input_map(args, m))
    return contribs


# ---------------------------------------------------------------------------
# sort-based grouping kernel
# ---------------------------------------------------------------------------

def sort_group_reduce(keys: Tuple[jnp.ndarray, ...], mask: jnp.ndarray,
                      contribs: Tuple, kinds: Tuple[str, ...],
                      identities: Tuple, out_groups: int,
                      widths: Optional[Tuple[int, ...]] = None):
    """Group rows by `keys` (exact, multi-column) and reduce `contribs`.

    Returns (group_keys, group_states, group_valid_mask). Invalid input rows and
    groups beyond out_groups are dropped (caller sizes out_groups to capacity).
    """
    n = mask.shape[0]
    widths = widths or (1,) * len(kinds)
    invalid = ~mask
    order = lexsort_fast(tuple(reversed(keys)) + (invalid,))
    sk = tuple(k[order] for k in keys)
    sv = mask[order]
    sc = tuple((c[0][order], c[1][order]) if isinstance(c, tuple) else c[order]
               for c in contribs)

    first = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    diff = jnp.zeros(n, dtype=jnp.bool_)
    for k in sk:
        diff = diff | (k != jnp.roll(k, 1))
    new_group = sv & (first | diff)
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    num_groups = jnp.where(n > 0, gid[-1] + 1, 0)
    gid = jnp.where(sv, gid, out_groups)  # trash bin
    gid = jnp.minimum(gid, out_groups)    # overflow also lands in the bin

    states = _reduce_all(sc, kinds, identities, widths, gid, out_groups)
    # group keys: first sorted row per group, ONE segment_min + a cheap
    # gather per key column (the old per-key scatter into an out_groups
    # table cost a full scatter pass per key — the dominant fold cost on
    # multi-key aggregations). Empty slots gather garbage; gvalid masks them.
    first = jax.ops.segment_min(jnp.arange(n, dtype=jnp.int32), gid,
                                num_segments=out_groups + 1)[:out_groups]
    safe = jnp.clip(first, 0, max(n - 1, 0))
    gkeys = [k[safe] for k in sk]
    gvalid = jnp.arange(out_groups, dtype=jnp.int32) < jnp.minimum(num_groups, out_groups)
    # overwrite empty-group states with identities so MIN/MAX don't leak sentinels
    fixed_states = [_where_valid(gvalid, s, ident)
                    for s, ident in zip(states, identities)]
    return tuple(gkeys), tuple(fixed_states), gvalid, num_groups


# ---------------------------------------------------------------------------
# builders
# ---------------------------------------------------------------------------

# disk-spill partitioning: target bytes per partition (the merge-on-read
# working set) — the partition count adapts so each partition's merge fits
# comfortably in host RAM
_DISK_PARTITION_TARGET_BYTES = 64 << 20
_DISK_MAX_PARTITIONS = 256


def _key_row_hash(keys) -> np.ndarray:
    """Deterministic per-row uint64 hash over the interleaved key columns —
    the disk-spill partitioner. VALUE-cast (not bit-cast) to int64 so float
    +0.0/-0.0 (equal keys) hash equal; NULL lanes are already canonical
    (zeroed value + flag, _null_safe_keys). Must agree between write time
    and merge-on-read: it only sees numpy values, which round-trip pcol
    bit-exactly."""
    n = len(keys[0])
    h = np.full(n, 0x9E3779B97F4A7C15, dtype=np.uint64)
    for k in keys:
        with np.errstate(invalid="ignore"):
            v = np.asarray(k).astype(np.int64, copy=False).view(np.uint64)
        h = h ^ v
        # splitmix64 finalizer (wraps mod 2^64; numpy uint64 arrays wrap
        # silently)
        h = (h ^ (h >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        h = (h ^ (h >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        h = h ^ (h >> np.uint64(31))
    return h


class GroupedAggregationBuilder:
    """Sort-strategy accumulator (InMemoryHashAggregationBuilder analogue)."""

    compact_table = True  # finish() returns a prefix-valid table

    def __init__(self, key_types: Sequence[Type], key_dicts: Sequence[Optional[Dictionary]],
                 calls: Sequence[AggregateCall], page_capacity: int,
                 max_groups: int = 1 << 20, from_intermediate: bool = False,
                 hash_grouping: str = "off"):
        self.user_key_types = list(key_types)
        # internal key signature interleaves a BOOLEAN null-flag column per key
        # (_null_safe_keys): every internal loop over key arrays (fold, spill
        # merge, finish) then handles NULL groups with no special cases
        self.key_types = [x for t in key_types for x in (t, BOOLEAN)]
        self.key_dicts = list(key_dicts)
        self.calls = list(calls)
        self.max_groups = max_groups
        self.from_intermediate = from_intermediate
        self.kinds: Tuple[str, ...] = tuple(
            col.reduce for c in calls for col in c.function.state)
        self.identities: Tuple = tuple(
            col.identity for c in calls for col in c.function.state)
        self.widths: Tuple[int, ...] = _state_widths(calls)
        # vector (sketch) states scatter into (groups, width) tables — bound
        # BOTH the per-page group table and the device accumulator; overflow
        # beyond max_groups spills compacted partials to host RAM as usual
        self._wide_cap = WIDE_STATE_MAX_GROUPS if any(
            w > 1 for w in self.widths) else None
        if self._wide_cap is not None:
            self.max_groups = min(self.max_groups, self._wide_cap)
        self._acc = None            # (keys, states, valid) compact table, <= max_groups
        self._pending: List = []    # list of (keys, states, mask) partials
        self._pending_rows = 0
        # installed lazily (set_channels runs after __init__) via the global
        # kernel cache so equal-config builders across queries share one compile
        self._page_kernel = None
        # spilled partial tables on HOST RAM (numpy) — rung 2 of the ladder
        # (SpillableHashAggregationBuilder analogue): device HBM holds at
        # most max_groups live groups; overflow and revocation move
        # compacted partials to host, merged exactly at finish()
        self._spilled: List = []    # list of (np keys tuple, np states tuple, np valid)
        # rung 3, DISK: under sustained pressure the operator calls
        # spill_to_disk() and the host partials become hash-partitioned,
        # sorted, partially-reduced PCOL runs (exec/spill.py). The partition
        # count adapts to the OBSERVED group cardinality as runs accumulate
        # (the dynamic hybrid-hash-join design: commit to a partition count
        # at runtime, not up front) — merge-on-read at finish() then works
        # one partition at a time, so peak host RAM is bounded by the
        # largest partition, not the whole group table.
        self._spill_mgr = None      # exec/spill.SpillManager (attach_spill)
        self._disk_runs: List = []  # SpillRun list, meta={"P","part","nk"}
        self._disk_parts = 1        # pow2 partition count; grows, never shrinks
        # adaptive compact-table size: starts at the first fold's true group count
        # (rounded up to a power of two) and grows on demand — the rehash analogue
        # of MultiChannelGroupByHash.java:363-409, but table growth here re-runs one
        # sort kernel at the next size bucket instead of rehashing in place
        self._table_size: Optional[int] = None
        # adaptive PER-PAGE strategy, decided once from the first page's true
        # group count (one scalar sync, the price the fold already pays):
        # - defer=True: grouping is NOT reducing (groups ~ rows), so the
        #   per-page sort+reduce is pure overhead — pages contribute their
        #   raw (keys, contribs, mask) rows and ONE fold does all the sort
        #   work. No further syncs: raw absorption is shape-static.
        # - _out_groups: grouping reduces a lot — later partials emit a
        #   SHRUNKEN table sized to the observed count, so fold inputs and
        #   per-page segment reductions scale with groups, not capacity.
        #   Needs a per-page overflow check (one scalar sync), so it engages
        #   only on the synchronous CPU backend; accelerators keep full-size
        #   partials and their fully async dispatch.
        self._defer: Optional[bool] = None
        self._out_groups: Optional[int] = None
        self._raw_kernel = None
        # Pallas insert-or-accumulate grouping (ops/pallas_hash.py), the
        # `hash_kernels` session property's agg half: "force" engages
        # wherever CORRECT (integer-comparable keys, scalar states, grouping
        # that reduces), "auto" only where the same heuristic that shrinks
        # partial tables expects a win, "off" (default) keeps pure
        # sort+segment-reduce. Decided once from the first page's true group
        # count (_decide_strategy); an insert overflow at any later page
        # falls back to the sort kernel permanently — never a wrong result.
        self._hash_grouping = hash_grouping
        self._hash_slots: Optional[int] = None
        self._hash_kernel = None
        self.hash_pages = 0  # pages grouped by the Pallas kernel (telemetry)

    # --- per page ---------------------------------------------------------

    def _page_partial(self, page: Page, out_groups: int):
        mask = page.mask
        keys = _null_safe_keys(page, self._key_channels)
        contribs = _call_contributions(self.calls, page, self.from_intermediate)
        return sort_group_reduce(keys, mask, tuple(contribs), self.kinds,
                                 self.identities, out_groups, self.widths)

    def _page_raw(self, page: Page):
        """Defer mode: per-row keys/contributions, no per-page reduction.
        Structurally identical to a partial's (keys, states, valid) triple,
        so the fold/spill machinery consumes both interchangeably."""
        keys = _null_safe_keys(page, self._key_channels)
        contribs = _call_contributions(self.calls, page, self.from_intermediate)
        return keys, tuple(contribs), page.mask

    def set_channels(self, key_channels: Sequence[int]):
        self._key_channels = tuple(key_channels)
        return self

    def share_kernels(self, donor: "GroupedAggregationBuilder") -> None:
        """Adopt a sibling builder's jitted kernel (identical static config) so
        per-worker builder instances trace/compile once per factory, not once
        per worker."""
        self._page_kernel = donor._page_kernel

    def page_out_groups(self, capacity: int) -> int:
        og = capacity if self._wide_cap is None \
            else min(capacity, self._wide_cap)
        if self._out_groups is not None:
            og = min(og, self._out_groups)
        return og

    def defer_raw(self) -> bool:
        """True once the first page proved grouping does not reduce."""
        return self._defer is True

    def _install_page_kernel(self, page: Page) -> None:
        if self._page_kernel is None:
            self._page_kernel = kernel_cache.get_or_install(
                _builder_key("sort", self, page), lambda: jax.jit(
                    self._page_partial, static_argnames=("out_groups",)))

    def _install_raw_kernel(self, page: Page) -> None:
        if self._raw_kernel is None:
            self._raw_kernel = kernel_cache.get_or_install(
                _builder_key("sort-raw", self, page),
                lambda: jax.jit(self._page_raw))

    def add_page(self, page: Page) -> None:
        if self.defer_raw():
            self._install_raw_kernel(page)
            self.absorb_raw(self._raw_kernel(page), page.capacity)
            return
        if self._hash_slots is not None:
            if self._absorb_hash_page(page):
                return
            # insert overflow (more distinct keys than the table holds, or
            # pathological clustering): permanent fallback to the sort
            # kernel — the page recomputes below, results stay exact
            self._hash_slots = None
        self._install_page_kernel(page)
        out_groups = self.page_out_groups(page.capacity)
        if not self.absorb_partial(self._page_kernel(page, out_groups),
                                   page.capacity, out_groups):
            # shrunken table overflowed: redo this one page at full size
            out_groups = self.page_out_groups(page.capacity)
            ok = self.absorb_partial(self._page_kernel(page, out_groups),
                                     page.capacity, out_groups)
            assert ok, "full-size partial cannot overflow"

    def absorb_raw(self, raw, capacity: int) -> None:
        """Defer mode: install one page's per-row (keys, contribs, mask)."""
        keys, contribs, mask = raw
        self._pending.append((keys, contribs, mask))
        self._pending_rows += capacity
        if self._pending_rows >= 4 * self.max_groups:
            self._fold()

    def absorb_partial(self, partial, capacity: int, out_groups: int) -> bool:
        """Install one page's (gkeys, gstates, gvalid, ng) partial — computed
        by this builder's own kernel or by a fused pipeline segment whose
        final stage ran the identical `_page_partial` config. Returns False
        when a SHRUNKEN table overflowed (the page's tail groups were clamped
        into the trash bin): the caller must recompute that page at the
        then-reset full size."""
        gkeys, gstates, gvalid, ng = partial
        full = capacity if self._wide_cap is None \
            else min(capacity, self._wide_cap)
        if out_groups < full:
            # shrunken partial: verify the observed bound still holds (one
            # scalar sync — the shrink is only picked on sync-cheap backends)
            if int(ng) > out_groups:
                self._out_groups = None  # data disproved the bound
                return False
        elif self._wide_cap is not None and int(ng) > out_groups:
            # a capped group table would silently merge groups — fail loudly
            # (sketch aggregates target few groups; the reference's qdigest /
            # HLL states would OOM long before this bound too)
            raise RuntimeError(
                f"sketch aggregate over more than {out_groups} groups in one "
                f"page is not supported")
        elif self._defer is None and self._wide_cap is None:
            self._decide_strategy(int(ng), capacity)
        self._pending.append((gkeys, gstates, gvalid))
        # account the partial's actual table rows (static shape, no sync):
        # shrunken partials then reach the fold threshold by live state, not
        # by input capacity, sparing needless mid-stream folds
        self._pending_rows += int(gvalid.shape[0])
        if self._pending_rows >= 4 * self.max_groups:
            self._fold()
        return True

    def _decide_strategy(self, first_ng: int, capacity: int) -> None:
        """One-shot adaptation from the first page's true group count (one
        scalar sync, same price a fold pays). Groups ~ rows: per-page
        sort+reduce buys nothing — defer pages as raw rows into the fold.
        Groups << rows: shrink later partials' tables to the observed count
        (CPU backend only: the overflow guard syncs per page). With the
        `hash_kernels` knob on, the same observed count also decides the
        Pallas insert-or-accumulate table size."""
        self._defer = first_ng > capacity // 2
        if self._defer:
            return
        import jax as _jax

        on_cpu = _jax.default_backend() == "cpu"
        if on_cpu and first_ng <= capacity // 8:
            self._out_groups = max(1024, _pow2(int(first_ng * 1.5) + 1))
        if self._hash_grouping != "off" and self._keys_hashable():
            # "auto" mirrors the shrunken-table heuristic (sync-cheap
            # backend, strongly reducing grouping); "force" engages wherever
            # the table is merely CORRECT — grouping reduces at all and the
            # keys compare as int64 (the bench / differential posture)
            friendly = capacity // 8 if self._hash_grouping == "auto" \
                else capacity // 2
            # decline upfront when the capped table provably cannot hold
            # the observed count at load <= 0.5 — otherwise the first hash
            # page would pay a full (interpreted) insert just to overflow
            slot_cap = 1 << 16
            if first_ng <= min(friendly, slot_cap // 4) and \
                    (self._hash_grouping == "force" or on_cpu):
                self._hash_slots = max(1 << 10, _pow2(4 * first_ng))

    def _keys_hashable(self) -> bool:
        """Pallas grouping compares keys as int64 slot components: floats
        (bit-pattern equality != SQL equality on -0.0) and vector (sketch)
        states stay on the sort path."""
        if self._wide_cap is not None:
            return False
        return all(np.issubdtype(np.dtype(t.np_dtype), np.integer)
                   or np.dtype(t.np_dtype) == np.bool_
                   for t in self.key_types)

    # --- pallas insert-or-accumulate (ops/pallas_hash.py) ------------------

    def _page_hash_partial(self, page: Page, slots: int):
        """One page -> a SLOT-INDEXED partial (gkeys, states, used, stats):
        the open-addressing insert assigns every live row its key's slot as
        the group id, contributions segment-reduce straight into the slot
        table (insert-or-accumulate — no sort), and the slot key components
        decode back to the builder's interleaved (value, null-flag) key
        signature. Holes (unclaimed slots) are masked by `used`; the fold
        consumes holey partials exactly like compact ones (invalid rows
        route to its trash segment)."""
        from . import pallas_hash as ph

        mask = page.mask
        keys = _null_safe_keys(page, self._key_channels)
        contribs = _call_contributions(self.calls, page,
                                       self.from_intermediate)
        comps = tuple(k.astype(jnp.int64) for k in keys)
        slot_comps, slot_rows, gid, stats = ph.insert_table(
            comps, mask, slots)
        # masked / overflowed rows -> the trash segment (overflow also
        # raises the stats flag: the caller discards this partial entirely)
        gid = jnp.where(mask & (gid >= 0), gid, slots)
        states = _reduce_all(tuple(contribs), self.kinds, self.identities,
                             self.widths, gid, slots)
        used = slot_rows >= 0
        gkeys = tuple(sc.astype(np.dtype(t.np_dtype))
                      for sc, t in zip(slot_comps, self.key_types))
        fixed = tuple(_where_valid(used, s, ident)
                      for s, ident in zip(states, self.identities))
        return gkeys, fixed, used, stats

    def _install_hash_kernel(self, page: Page, slots: int) -> None:
        if self._hash_kernel is None:
            self._hash_kernel = kernel_cache.get_or_install(
                _builder_key(("pallas-hash", slots), self, page),
                lambda: jax.jit(self._page_hash_partial,
                                static_argnames=("slots",)))

    def _absorb_hash_page(self, page: Page) -> bool:
        """Group one page through the Pallas table. Returns False on insert
        overflow (one scalar sync per page — the same price the shrunken
        sort path pays; both engage only where _decide_strategy accepted
        that cost): the caller re-runs the page through the sort kernel."""
        slots = self._hash_slots
        self._install_hash_kernel(page, slots)
        gkeys, states, used, stats = self._hash_kernel(page, slots=slots)
        if int(np.asarray(stats)[0]):
            from ..utils.metrics import METRICS
            METRICS.count("pallas.agg_fallbacks")
            return False
        self.hash_pages += 1
        from ..utils.metrics import METRICS
        METRICS.count("pallas.agg_pages")
        self._pending.append((gkeys, states, used))
        self._pending_rows += int(used.shape[0])
        if self._pending_rows >= 4 * self.max_groups:
            self._fold()
        return True

    # --- combine ----------------------------------------------------------

    def _fold(self, final: bool = False) -> None:
        """Merge pending partials (+ current table) into a fresh compact table.
        If the live group count exceeds max_groups, the inputs are SPILLED to
        host RAM instead (merged exactly at finish) — never silently dropped.
        `final` marks the finish()-time fold: no further folds will read the
        table, so the tighten-to-pow2 slicing pass is skipped."""
        parts = list(self._pending)
        self._pending = []
        self._pending_rows = 0
        if self._acc is not None:
            parts.append(self._acc)
            self._acc = None
        # pad the part count to its pow2 bucket with zero-row dummies so the
        # fused combine kernel's trace signature is bounded by O(log parts)
        # distinct counts, not one compile per exact count
        n_parts = len(parts)
        want = _pow2_count(n_parts)
        if want > n_parts:
            # numpy zeros: eager jnp.zeros dispatches compile a throwaway
            # kernel per dtype; np arrays device_put at the jit call
            z_keys = tuple(np.zeros(0, dtype=p.dtype)
                           for p in parts[0][0])
            z_states = tuple(
                np.zeros((0,) + tuple(s.shape[1:]), dtype=s.dtype)
                for s in parts[0][1])
            z_valid = np.zeros(0, dtype=np.bool_)
            parts = parts + [(z_keys, z_states, z_valid)] * (want - n_parts)
        key_parts = tuple(tuple(p[0][i] for p in parts)
                          for i in range(len(self.key_types)))
        state_parts = tuple(tuple(p[1][i] for p in parts)
                            for i in range(len(self.kinds)))
        valid_parts = tuple(p[2] for p in parts)
        total_rows = sum(int(v.shape[0]) for v in valid_parts)
        size = self._table_size or _pow2(min(total_rows, self.max_groups))
        while True:
            # concat + sort + reduce in ONE jitted dispatch (the eager
            # per-column concatenates were a dispatch each — costly on a
            # remote accelerator)
            gkeys, gstates, gvalid, ngroups = _combine_parts_kernel(
                key_parts, valid_parts, state_parts, self.kinds,
                self.identities, size, self.widths)
            n = int(ngroups)
            if n <= size or size >= self.max_groups:
                break
            size = min(_pow2(n), self.max_groups)  # grow and refold
        if n > self.max_groups:
            # more live groups than the device table can hold: move the (still
            # complete) input rows to host and keep accumulating fresh
            self._spilled.append((
                tuple(np.concatenate([np.asarray(x) for x in kp])
                      for kp in key_parts),
                tuple(np.concatenate([np.asarray(x) for x in sp])
                      for sp in state_parts),
                np.concatenate([np.asarray(v) for v in valid_parts])))
            self._table_size = None
            return
        # shrink the table to the true group count's bucket: gvalid is a prefix,
        # so slicing keeps every live group and future folds sort less. The
        # FINAL fold skips this — nothing reads the table again, and the
        # slice kernels would be pure overhead
        tight = min(_pow2(max(n, 1)), self.max_groups)
        if tight < size and not final:
            gkeys = tuple(k[:tight] for k in gkeys)
            gstates = tuple(s[:tight] for s in gstates)
            gvalid = gvalid[:tight]
        self._table_size = tight
        self._acc = (gkeys, gstates, gvalid)

    # --- spill (HBM -> host RAM; FileSingleStreamSpiller analogue) ---------

    def memory_bytes(self) -> int:
        """Device-resident bytes (pending partials + compact table)."""
        per_row = sum(np.dtype(t.np_dtype).itemsize for t in self.key_types) + \
            sum(np.dtype(col.dtype).itemsize * col.width
                for c in self.calls for col in c.function.state) + 1
        rows = self._pending_rows
        if self._acc is not None:
            rows += int(self._acc[2].shape[0])
        return rows * per_row

    def spill(self) -> None:
        """Move ALL device state to host (start_memory_revoke path)."""
        parts = list(self._pending)
        self._pending = []
        self._pending_rows = 0
        if self._acc is not None:
            parts.append(self._acc)
            self._acc = None
            self._table_size = None
        for p in parts:
            self._spilled.append((
                tuple(np.asarray(k) for k in p[0]),
                tuple(np.asarray(s) for s in p[1]),
                np.asarray(p[2])))

    def _merge_spilled(self):
        """Exact host-side merge of spilled partials + device table: sort rows
        by key tuple, segment boundaries, per-kind reduceat. Unbounded group
        counts are fine here — host RAM is the spill medium. When disk runs
        exist, the merge goes partition-at-a-time instead (_merge_disk)."""
        parts = list(self._spilled)
        self._spilled = []
        if self._acc is not None:
            parts.append((tuple(np.asarray(k) for k in self._acc[0]),
                          tuple(np.asarray(s) for s in self._acc[1]),
                          np.asarray(self._acc[2])))
            self._acc = None
        if self._disk_runs:
            return self._merge_disk(parts)
        keys, states = self._host_merge_parts(parts)
        n = len(keys[0]) if keys else 0
        if n == 0:
            z = tuple(jnp.zeros(0, dtype=t.np_dtype) for t in self.key_types)
            return z, _empty_state(self.widths), jnp.zeros(0, dtype=jnp.bool_)
        return tuple(keys), tuple(states), np.ones(n, dtype=bool)

    def _host_merge_parts(self, parts):
        """Merge (keys, states, valid) numpy triples exactly: filter valid,
        lexsort by key tuple, reduceat per kind -> ([key col...], [state
        col...]) with ONE row per distinct key, sorted. The shared core of
        the host-RAM merge and the per-partition disk merge."""
        nk = len(self.key_types)
        keys = [np.concatenate([np.asarray(p[0][i]) for p in parts])
                for i in range(nk)]
        states = [np.concatenate([np.asarray(p[1][i]) for p in parts])
                  for i in range(len(self.kinds))]
        valid = np.concatenate([np.asarray(p[2]) for p in parts])
        keys = [k[valid] for k in keys]
        states = [s[valid] for s in states]
        if len(keys[0]) == 0:
            return keys, states
        order = np.lexsort(tuple(reversed(keys)))
        keys = [k[order] for k in keys]
        states = [s[order] for s in states]
        boundary = np.zeros(len(keys[0]), dtype=bool)
        boundary[0] = True
        for k in keys:
            boundary[1:] |= k[1:] != k[:-1]
        starts = np.flatnonzero(boundary)
        # stay on HOST: the merged table can exceed device capacity (that is
        # why it spilled); _build_result pages it out page-capacity at a time
        out_keys = [k[starts] for k in keys]
        out_states = []
        i = 0
        nrows = len(keys[0])
        while i < len(self.kinds):
            kind = self.kinds[i]
            s = states[i]
            if kind in (AMIN, AMAX):
                y = states[i]
                red = np.minimum if kind == AMIN else np.maximum
                m = red.reduceat(y, starts)
                counts = np.diff(np.append(starts, nrows))
                cand = np.where(y == np.repeat(m, counts),
                                np.arange(nrows), nrows)
                win = np.clip(np.minimum.reduceat(cand, starts), 0,
                              max(nrows - 1, 0))
                out_states.append(m)
                i += 1
                while i < len(self.kinds) and self.kinds[i] == ACARRY:
                    out_states.append(states[i][win])
                    i += 1
                continue
            red = {SUM: np.add, MIN: np.minimum, MAX: np.maximum}[kind]
            out_states.append(red.reduceat(s, starts))
            i += 1
        return out_keys, out_states

    # --- disk tier (host RAM -> PCOL runs; exec/spill.py) ------------------

    def attach_spill(self, mgr) -> None:
        """Wire the query's SpillManager (or None) — done once per operator
        from its OperatorContext."""
        self._spill_mgr = mgr

    def disk_eligible(self) -> bool:
        # wide (vector/sketch) states scatter into 2-D tables pcol does not
        # speak; they stay on the host rung. Dtype eligibility is checked
        # per flush (spill_to_disk declines, never raises).
        return self._spill_mgr is not None and self._wide_cap is None

    def host_spill_bytes(self) -> int:
        """Host-RAM bytes held by spilled partials — the disk-flushable
        rung the operator reports as revocable when disk is attached."""
        total = 0
        for p in self._spilled:
            for a in p[0]:
                total += np.asarray(a).nbytes
            for a in p[1]:
                total += np.asarray(a).nbytes
            total += np.asarray(p[2]).nbytes
        return total

    def _adapt_disk_parts(self, new_rows: int, row_bytes: int) -> None:
        """Grow the pow2 partition count from OBSERVED cardinality: distinct
        rows seen so far (disk runs are an upper bound — duplicates across
        runs merge away) sized so one partition's merge stays near the
        target working set. Grow-only: a run written at P=4 is still
        addressable when later runs use P=16 (part = hash & (P-1), so the
        coarse index is a suffix of the fine one)."""
        est_rows = new_rows + sum(r.rows for r in self._disk_runs)
        want = _pow2_count(
            max(1, (est_rows * max(row_bytes, 1)
                    + _DISK_PARTITION_TARGET_BYTES - 1)
                // _DISK_PARTITION_TARGET_BYTES))
        self._disk_parts = min(max(self._disk_parts, want),
                               _DISK_MAX_PARTITIONS)

    def spill_to_disk(self) -> int:
        """Flush the host-RAM partials as hash-partitioned, sorted,
        partially-reduced PCOL runs; returns bytes written (0 = declined:
        no manager, wide states, or a dtype pcol cannot store — the state
        simply stays in host RAM; disk is an optimisation rung, never a
        correctness requirement)."""
        mgr = self._spill_mgr
        if mgr is None or self._wide_cap is not None or not self._spilled:
            return 0
        from ..exec.spill import storage_type_for
        sample = self._spilled[0]
        probe = [np.asarray(a) for a in sample[0]] + \
                [np.asarray(a) for a in sample[1]]
        if any(a.ndim != 1 or storage_type_for(a.dtype) is None
               for a in probe):
            return 0
        parts = self._spilled
        self._spilled = []
        keys, states = self._host_merge_parts(parts)
        n = len(keys[0]) if keys else 0
        if n == 0:
            return 0
        row_bytes = sum(a.dtype.itemsize for a in keys) + \
            sum(a.dtype.itemsize for a in states)
        self._adapt_disk_parts(n, row_bytes)
        P = self._disk_parts
        part = (_key_row_hash(keys) & np.uint64(P - 1)).astype(np.int64)
        names = [f"k{i}" for i in range(len(keys))] + \
                [f"s{i}" for i in range(len(states))]
        written = 0
        for p in range(P):
            sel = part == p
            if not sel.any():
                continue
            # boolean selection preserves order: each partition stays
            # sorted by key tuple — a sorted, partially-reduced run
            cols = [a[sel] for a in keys] + [a[sel] for a in states]
            run = mgr.write_columns(
                names, cols, kind="agg",
                meta={"P": P, "part": p, "nk": len(keys)})
            self._disk_runs.append(run)
            written += run.nbytes
        return written

    def _merge_disk(self, resident_parts):
        """Exact merge-on-read over the disk runs + the in-RAM residual,
        one finest-granularity partition at a time. Runs written at a
        coarser P contribute rows to every fine partition that refines
        theirs — the recomputed hash filter keeps the merge exact across
        mixed granularities. Peak host RAM is one partition's rows, not
        the whole group table."""
        mgr = self._spill_mgr
        runs = self._disk_runs
        self._disk_runs = []
        res_keys, res_states = (self._host_merge_parts(resident_parts)
                                if resident_parts else ([], []))
        have_res = bool(res_keys) and len(res_keys[0]) > 0
        p_max = self._disk_parts
        res_part = None
        if have_res:
            res_part = (_key_row_hash(res_keys)
                        & np.uint64(p_max - 1)).astype(np.int64)
        out_keys: List[List[np.ndarray]] = [[] for _ in self.key_types]
        out_states: List[List[np.ndarray]] = [[] for _ in self.kinds]
        total = 0
        for f in range(p_max):
            chunk_parts = []
            for run in runs:
                if run.meta["part"] != (f & (run.meta["P"] - 1)):
                    continue
                cols = mgr.read_columns(run)
                nk = run.meta["nk"]
                rkeys = [c[0] for c in cols[:nk]]
                rstates = [c[0] for c in cols[nk:]]
                if run.meta["P"] < p_max:
                    sel = (_key_row_hash(rkeys)
                           & np.uint64(p_max - 1)).astype(np.int64) == f
                    rkeys = [k[sel] for k in rkeys]
                    rstates = [s[sel] for s in rstates]
                if len(rkeys[0]) == 0:
                    continue
                chunk_parts.append(
                    (tuple(rkeys), tuple(rstates),
                     np.ones(len(rkeys[0]), dtype=bool)))
            if have_res:
                sel = res_part == f
                if sel.any():
                    chunk_parts.append(
                        (tuple(k[sel] for k in res_keys),
                         tuple(s[sel] for s in res_states),
                         np.ones(int(sel.sum()), dtype=bool)))
            if not chunk_parts:
                continue
            mk, ms = self._host_merge_parts(chunk_parts)
            if not mk or len(mk[0]) == 0:
                continue
            for i, k in enumerate(mk):
                out_keys[i].append(k)
            for i, s in enumerate(ms):
                out_states[i].append(s)
            total += len(mk[0])
        for run in runs:
            mgr.release(run)
        if total == 0:
            z = tuple(jnp.zeros(0, dtype=t.np_dtype) for t in self.key_types)
            return z, _empty_state(self.widths), jnp.zeros(0, dtype=jnp.bool_)
        return (tuple(np.concatenate(c) for c in out_keys),
                tuple(np.concatenate(c) for c in out_states),
                np.ones(total, dtype=bool))

    def finish(self):
        """-> (keys, states, valid) on device, compact."""
        if self._pending or self._acc is None:
            if not self._pending and self._acc is None \
                    and not self._spilled and not self._disk_runs:
                # empty input: zero groups
                z = tuple(jnp.zeros(0, dtype=t.np_dtype) for t in self.key_types)
                return z, _empty_state(self.widths), \
                    jnp.zeros(0, dtype=jnp.bool_)
            if self._pending:
                self._fold(final=True)
        if self._spilled or self._disk_runs:
            out = self._merge_spilled()
        else:
            out = self._acc
        # drop device references: the first builder per cache key stays alive
        # in the kernel cache (its jitted bound method), so lingering state
        # would pin the final group tables in HBM past the query's end
        self._acc = None
        self._pending = []
        self._spilled = []
        self._table_size = None
        return out


@functools.partial(jax.jit, static_argnames=("cap", "dtypes"))
def _slice_result_page(arrs, nulls, valid, lo, cap, dtypes):
    """Assemble one output page: per-column [lo, lo+cap) slice, pad, and
    dtype cast, in a single dispatch (the eager-slice loop cost one device
    round-trip per column)."""
    def seg(a, dt):
        n = a.shape[0]
        padded = jnp.concatenate([a, jnp.zeros(cap, dtype=a.dtype)])
        return jax.lax.dynamic_slice_in_dim(
            padded, jnp.clip(lo, 0, n), cap)

    datas = tuple(seg(a, dt).astype(dt)
                  for a, dt in zip(arrs, dtypes))
    nmasks = tuple(None if nl is None else seg(nl, jnp.bool_)
                   for nl in nulls)
    m = seg(valid, jnp.bool_)
    return datas, nmasks, m


@functools.partial(jax.jit, static_argnames=("kinds", "identities",
                                             "max_groups", "widths"))
def _combine_kernel(keys, valid, states, kinds, identities, max_groups,
                    widths=None):
    return sort_group_reduce(keys, valid, states, kinds, identities,
                             max_groups, widths)


@functools.partial(jax.jit, static_argnames=("kinds", "identities",
                                             "max_groups", "widths"))
def _combine_parts_kernel(key_parts, valid_parts, state_parts, kinds,
                          identities, max_groups, widths=None):
    """_combine_kernel with the cross-part concatenation fused in: one
    dispatch folds N pending partials into the compact table."""
    keys = tuple(jnp.concatenate(list(kp)) for kp in key_parts)
    states = tuple(jnp.concatenate(list(sp)) for sp in state_parts)
    valid = jnp.concatenate(list(valid_parts))
    return sort_group_reduce(keys, valid, states, kinds, identities,
                             max_groups, widths)


def _pow2(n: int) -> int:
    return 1 << max(4, (n - 1).bit_length())


def _pow2_count(n: int) -> int:
    """Next power of two >= n (no floor) — part-count bucketing."""
    return 1 << max(0, (n - 1).bit_length())


class DirectAggregationBuilder:
    """Small-domain strategy: dense state table indexed by linear key code.

    BigintGroupByHash analogue; domain = product of per-key dictionary/domain sizes."""

    compact_table = False  # domain-indexed table: valid mask has holes

    def __init__(self, key_types, key_dicts, domains: Sequence[int], calls,
                 from_intermediate: bool = False):
        self.key_types = list(key_types)
        self.key_dicts = list(key_dicts)
        # one extra slot per key for its NULL group (code == base domain):
        # SQL groups NULL as its own key even in the dense-domain strategy
        self.base_domains = [int(d) for d in domains]
        self.domains = [int(d) + 1 for d in domains]
        self.calls = list(calls)
        self.from_intermediate = from_intermediate
        self.D = int(np.prod(self.domains))
        self.kinds = tuple(col.reduce for c in calls for col in c.function.state)
        self.identities = tuple(col.identity for c in calls for col in c.function.state)
        self.widths = _state_widths(calls)
        self._table = None  # tuple of (D,) / (D, width) state arrays
        self._seen = None   # (D,) bool: group occurred
        self._kernel = None  # lazy: set_channels runs after __init__

    def set_channels(self, key_channels):
        self._key_channels = tuple(key_channels)
        return self

    def share_kernels(self, donor: "DirectAggregationBuilder") -> None:
        self._kernel = donor._kernel

    def _accumulate(self, page: Page, table, seen):
        datas = tuple(b.data for b in page.blocks)
        mask = page.mask
        gid = jnp.zeros(page.mask.shape[0], dtype=jnp.int32)
        for ch, base, dom in zip(self._key_channels, self.base_domains,
                                 self.domains):
            code = jnp.clip(datas[ch].astype(jnp.int32), 0, base - 1)
            if page.blocks[ch].nulls is not None:
                code = jnp.where(page.blocks[ch].nulls, base, code)
            gid = gid * dom + code
        gid = jnp.where(mask, gid, self.D)  # dead rows -> trash segment
        contribs = _call_contributions(self.calls, page, self.from_intermediate)
        parts = _reduce_all(contribs, self.kinds, self.identities,
                            self.widths, gid, self.D)
        new_table = _merge_tables(self.kinds, table, parts)
        new_seen = seen | (jax.ops.segment_sum(
            mask.astype(jnp.int32), gid, num_segments=self.D + 1)[: self.D] > 0)
        return tuple(new_table), new_seen

    def init_state(self):
        """(table, seen) accumulator, materialized on first use — threaded
        through the page kernel as jit arguments (fused segments pass it the
        same way)."""
        if self._table is None:
            self._table = tuple(
                _fill((self.D, col.width) if col.width > 1 else (self.D,),
                      np.dtype(col.dtype), col.identity)
                for c in self.calls for col in c.function.state)
            self._seen = jnp.zeros(self.D, dtype=jnp.bool_)
        return self._table, self._seen

    def absorb_state(self, state) -> None:
        self._table, self._seen = state

    def add_page(self, page: Page) -> None:
        if self._kernel is None:
            self._kernel = kernel_cache.get_or_install(
                _builder_key("direct", self, page),
                lambda: jax.jit(self._accumulate))
        table, seen = self.init_state()
        self.absorb_state(self._kernel(page, table, seen))

    def finish(self):
        if self._table is None:
            z = tuple(x for t in self.key_types
                      for x in (jnp.zeros(0, dtype=t.np_dtype),
                                jnp.zeros(0, dtype=jnp.bool_)))
            s = tuple(jnp.zeros(0, dtype=np.float64) for _ in self.kinds)
            return z, s, jnp.zeros(0, dtype=jnp.bool_)
        # decode linear gid back to interleaved (value, null_flag) key columns
        D = self.D
        idx = jnp.arange(D, dtype=jnp.int32)
        pairs = []
        rem = idx
        for base, dom, t in zip(reversed(self.base_domains),
                                reversed(self.domains),
                                reversed(self.key_types)):
            code = rem % dom
            flag = code == base
            pairs.append((jnp.where(flag, 0, code).astype(t.np_dtype), flag))
            rem = rem // dom
        keys = tuple(x for v, f in reversed(pairs) for x in (v, f))
        table, seen = self._table, self._seen
        self._table = self._seen = None  # see GroupedAggregationBuilder.finish
        return keys, table, seen


class GlobalAggregationBuilder:
    """No GROUP BY: scalar states (AggregationOperator analogue)."""

    def __init__(self, calls: Sequence[AggregateCall], from_intermediate: bool = False):
        self.calls = list(calls)
        self.from_intermediate = from_intermediate
        self.kinds = tuple(col.reduce for c in calls for col in c.function.state)
        self.identities = tuple(col.identity for c in calls for col in c.function.state)
        self.widths = _state_widths(calls)
        self._state = None
        self._kernel = None  # lazy: keyed on the first page's dict versions

    def set_channels(self, key_channels):
        return self

    def share_kernels(self, donor: "GlobalAggregationBuilder") -> None:
        self._kernel = donor._kernel

    def _accumulate(self, page: Page, state):
        mask = page.mask
        contribs = _call_contributions(self.calls, page, self.from_intermediate)
        state = self._state_or(state)
        new_state = []
        i = 0
        while i < len(self.kinds):
            kind = self.kinds[i]
            c = contribs[i]
            ident = self.identities[i]
            w = self.widths[i]
            s = state[i]
            if kind in (AMIN, AMAX):
                # joint pair reduce over rows, then combine with the state
                y = contribs[i]
                am = (jnp.argmin if kind == AMIN else jnp.argmax)(y)
                red_y = y[am]
                better = (red_y < s) if kind == AMIN else (red_y > s)
                new_state.append(jnp.where(better, red_y, s))
                i += 1
                while i < len(self.kinds) and self.kinds[i] == ACARRY:
                    new_state.append(jnp.where(better, contribs[i][am],
                                               state[i]))
                    i += 1
                continue
            if isinstance(c, tuple):
                bucket, vals = c
                base = jnp.full((w,), ident, dtype=vals.dtype)
                at = base.at[bucket]
                red = (at.add if kind == SUM else
                       (at.min if kind == MIN else at.max))(vals, mode="drop")
            else:
                if self.from_intermediate:
                    cond = mask if c.ndim == 1 else mask[:, None]
                    c = jnp.where(cond, c, jnp.asarray(ident, dtype=c.dtype))
                # axis=0 keeps (rows, width) vector contributions per-column
                red = {SUM: jnp.sum, MIN: jnp.min,
                       MAX: jnp.max}[kind](c, axis=0)
            new_state.append({SUM: lambda a, b: a + b,
                              MIN: jnp.minimum, MAX: jnp.maximum}[kind](s, red))
            i += 1
        return tuple(new_state)

    def _state_or(self, state):
        return state

    def _identity_state(self):
        return tuple(
            jnp.full((col.width,), col.identity, dtype=np.dtype(col.dtype))
            if col.width > 1 else
            jnp.asarray(col.identity, dtype=np.dtype(col.dtype))
            for c in self.calls for col in c.function.state)

    def init_state(self):
        if self._state is None:
            self._state = self._identity_state()
        return self._state

    def absorb_state(self, state) -> None:
        self._state = state

    def add_page(self, page: Page) -> None:
        if self._kernel is None:
            self._kernel = kernel_cache.get_or_install(
                _builder_key("global", self, page),
                lambda: jax.jit(self._accumulate))
        self.absorb_state(self._kernel(page, self.init_state()))

    def finish(self):
        if self._state is None:
            self._state = self._identity_state()
        keys = ()
        states = tuple(jnp.reshape(s, (1, -1) if s.ndim else (1,))
                       for s in self._state)
        self._state = None  # see GroupedAggregationBuilder.finish
        return keys, states, jnp.ones(1, dtype=jnp.bool_)


# ---------------------------------------------------------------------------
# operator
# ---------------------------------------------------------------------------

PARTIAL, FINAL, SINGLE = "partial", "final", "single"


class HashAggregationOperator(Operator):
    """Steps: PARTIAL emits [keys..., state_cols...]; FINAL consumes those;
    SINGLE does both (HashAggregationOperator.java:352-390 step wiring)."""

    def __init__(self, context: OperatorContext, builder, key_channels: List[int],
                 key_types: List[Type], key_dicts, calls: List[AggregateCall],
                 step: str, output_capacity: int):
        super().__init__(context)
        self.builder = builder.set_channels(key_channels)
        # disk tier: hand the builder the query's SpillManager so revocation
        # can walk host partials down to PCOL runs (grouped builder only —
        # global/direct builders have no spillable state)
        attach = getattr(self.builder, "attach_spill", None)
        if attach is not None:
            attach(context.spill)
        self.key_types = key_types
        self.key_dicts = key_dicts
        self.calls = calls
        self.step = step
        self.output_capacity = output_capacity
        self._result_pages: Optional[List[Page]] = None

    @property
    def output_types(self) -> List[Type]:
        out = list(self.key_types)
        for c in self.calls:
            if self.step == PARTIAL:
                out.extend(c.function.intermediate_types)
            else:
                out.append(c.function.output_type)
        return out

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        self.builder.add_page(page)
        if getattr(self.builder, "memory_bytes", None) is not None:
            self.context.update_revocable(self.revocable_bytes(),
                                          self.start_memory_revoke)

    # spill protocol (operator/Operator.java:68 startMemoryRevoke analogue):
    # the revoker asks; ONE revoke call walks the whole ladder — the builder
    # moves its device table to host RAM, then (when the query has a disk
    # tier and the state shape is disk-eligible) flushes the host partials
    # to PCOL runs. With no disk tier the host partials stay (the pre-disk
    # behavior) and only device bytes count as revocable.
    def _disk_capable(self) -> bool:
        eligible = getattr(self.builder, "disk_eligible", None)
        return self.context.spill is not None and eligible is not None \
            and eligible()

    def revocable_bytes(self) -> int:
        b = getattr(self.builder, "memory_bytes", None)
        total = b() if b is not None else 0
        if self._disk_capable():
            total += self.builder.host_spill_bytes()
        return total

    def start_memory_revoke(self) -> None:
        spill = getattr(self.builder, "spill", None)
        if spill is not None:
            spill()
            if self._disk_capable():
                self.builder.spill_to_disk()
            self.context.revocable_memory.set_bytes(self.revocable_bytes())

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._result_pages:
            out = self._result_pages.pop(0)
            self.context.record_output(out, out.capacity)
            return out
        return None

    def is_finished(self) -> bool:
        return self._finishing and self._result_pages is not None and not self._result_pages

    def finish(self) -> None:
        super().finish()
        if self._result_pages is None:
            self._build_result()

    def _build_result(self) -> None:
        keys, states, valid = self.builder.finish()
        self.context.revocable_memory.set_bytes(0)  # builder state consumed
        pages: List[Page] = []
        # sort-builder tables are compact (valid is a prefix): trim to live groups.
        # direct-builder tables are domain-indexed with holes: keep the full (small)
        # table and let the page masks carry liveness.
        if getattr(self.builder, "compact_table", True):
            # count on host: result building runs once per query, and the
            # eager jnp.sum dispatch compiled a kernel per valid-shape
            total = int(np.asarray(valid).sum())
        else:
            total = int(valid.shape[0])
        cap = self.output_capacity
        # final transform per aggregate
        out_cols: List[Tuple] = []  # (type, data, dictionary, nulls)
        # builders return interleaved (value, null_flag) arrays per key column
        for i, (t, d) in enumerate(zip(self.key_types, self.key_dicts)):
            kv, kf = keys[2 * i], keys[2 * i + 1]
            nulls = kf if bool(np.asarray(kf).any()) else None
            out_cols.append((t, kv, d, nulls))
        si = 0
        for call in self.calls:
            ncols = len(call.function.state)
            group_states = states[si: si + ncols]
            si += ncols
            if self.step == PARTIAL:
                for it, s in zip(call.function.intermediate_types, group_states):
                    out_cols.append((it, s, None, None))
            else:
                out = call.function.final_map(group_states)
                nulls = None
                if isinstance(out, tuple):  # (data, null_mask) contract
                    out, nulls = out
                d = call.output_dictionary
                if call.function.name in ("min", "max") and d is not None \
                        and not d.is_sorted():
                    # states held sort RANKS (see _call_contributions): map the
                    # winning rank back to its dictionary code (empty groups
                    # clip to an arbitrary code; their null flag masks them)
                    order = jnp.asarray(d.sort_order())
                    # states may arrive as f64 from the mesh exchange's
                    # common-dtype collectives: index with ints
                    out = order[jnp.clip(out, 0, len(order) - 1
                                         ).astype(jnp.int32)]
                out_cols.append((call.function.output_type,
                                 jnp.asarray(out, dtype=call.function.output_type.np_dtype),
                                 d, nulls))
        dtypes = tuple(np.dtype(t.np_dtype) for (t, _a, _d, _n) in out_cols)
        arrs = tuple(a for (_t, a, _d, _n) in out_cols)
        nulls_in = tuple(n for (_t, _a, _d, n) in out_cols)
        for lo in range(0, max(total, 1), cap):
            # one fused dispatch assembles the whole output page (slice +
            # pad + dtype cast across every column)
            datas, nmasks, m = _slice_result_page(
                arrs, nulls_in, valid, jnp.asarray(lo, jnp.int32), cap,
                dtypes)
            blocks = [Block(t, dd, nn, d) for (t, _a, d, _n), dd, nn
                      in zip(out_cols, datas, nmasks)]
            pages.append(Page(tuple(blocks), m))
            if total == 0:
                break
        self._result_pages = pages


def make_builder(key_types, key_dicts, key_domains, calls, page_capacity,
                 max_groups=1 << 20, from_intermediate=False,
                 direct_domain_limit=1 << 16, hash_grouping="off"):
    """Strategy pick (LocalExecutionPlanner's group-by-hash choice analogue)."""
    from .collect_agg import COLLECT_NAMES, CollectAggregationBuilder
    if any(c.function.name in COLLECT_NAMES for c in calls):
        # ragged collectors keep every row; one sorted pass at finish
        return CollectAggregationBuilder(key_types, key_dicts, calls,
                                         page_capacity, max_groups,
                                         from_intermediate)
    if not key_types:
        return GlobalAggregationBuilder(calls, from_intermediate)
    wide = any(w > 1 for w in _state_widths(calls))
    if key_domains is not None and all(d is not None for d in key_domains):
        D = int(np.prod(key_domains))
        # vector (sketch) states make the dense table D x width: keep the
        # direct strategy only while that stays small
        if D <= (direct_domain_limit if not wide
                 else min(direct_domain_limit, WIDE_STATE_MAX_GROUPS)):
            return DirectAggregationBuilder(key_types, key_dicts, key_domains, calls,
                                            from_intermediate)
    return GroupedAggregationBuilder(key_types, key_dicts, calls, page_capacity,
                                     max_groups, from_intermediate,
                                     hash_grouping=hash_grouping)


class HashAggregationOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, key_channels, key_types, key_dicts,
                 key_domains, calls, step: str, page_capacity: int,
                 max_groups: int = 1 << 20, hash_grouping: str = "off"):
        super().__init__(operator_id, f"HashAggregation({step})")
        self.key_channels = list(key_channels)
        self.key_types = list(key_types)
        self.key_dicts = list(key_dicts)
        self.key_domains = key_domains
        self.calls = list(calls)
        self.step = step
        self.page_capacity = page_capacity
        self.max_groups = max_groups
        # "hash_kernels" session property -> the sort builder's Pallas
        # insert-or-accumulate mode (off | auto | force)
        self.hash_grouping = hash_grouping
        self._kernel_donor = None

    def create_operator(self, worker: int = 0) -> Operator:
        from_intermediate = self.step == FINAL
        builder = make_builder(self.key_types, self.key_dicts, self.key_domains,
                               self.calls, self.page_capacity, self.max_groups,
                               from_intermediate,
                               hash_grouping=self.hash_grouping)
        # all builders of this factory share one jitted kernel: instance state
        # (tables, pending buffers) is per-builder, the traced computation is
        # pure factory config — workers must not each pay the trace+compile
        if self._kernel_donor is None:
            self._kernel_donor = builder
        else:
            builder.share_kernels(self._kernel_donor)
        return HashAggregationOperator(
            self.context(worker), builder,
            self.key_channels, self.key_types, self.key_dicts, self.calls,
            self.step, self.page_capacity)
