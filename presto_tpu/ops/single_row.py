"""EnforceSingleRow: scalar-subquery cardinality guard.

Analogue of presto-main operator/EnforceSingleRowOperator.java (planned by
plan/EnforceSingleRowNode): buffers its input, fails if more than one row arrives,
and emits exactly one row — an all-null row when the input is empty, matching SQL
scalar-subquery semantics.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..block import Block, Dictionary, Page
from ..types import Type
from .operator import Operator, OperatorContext, OperatorFactory, timed


class EnforceSingleRowOperator(Operator):
    def __init__(self, context: OperatorContext, types: List[Type],
                 dicts: List[Optional[Dictionary]]):
        super().__init__(context)
        self.types = types
        self.dicts = dicts
        self._row: Optional[Page] = None
        self._emitted = False

    @property
    def output_types(self) -> List[Type]:
        return self.types

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        n = page.size()
        if n == 0:
            return
        if self._row is not None or n > 1:
            raise RuntimeError("scalar subquery returned more than one row")
        compacted = page.compact()
        # keep only the first slot (capacity-1 page) to bound memory
        # at most ONE live row ever reaches this point (enforced above),
        # so these syncs run once per query, not per page
        blocks = tuple(
            Block(b.type, jnp.asarray(np.asarray(b.data)[:1]),  # prestocheck: ignore[host-sync]
                  jnp.asarray(np.asarray(b.nulls)[:1]) if b.nulls is not None else None,  # prestocheck: ignore[host-sync]
                  b.dictionary)
            for b in compacted.blocks)
        self._row = Page(blocks, jnp.ones(1, dtype=jnp.bool_))

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if not self._finishing or self._emitted:
            return None
        self._emitted = True
        if self._row is not None:
            return self._row
        # empty input -> one all-null row
        blocks = tuple(
            Block(t, jnp.zeros(1, dtype=t.np_dtype),
                  jnp.ones(1, dtype=jnp.bool_), d)
            for t, d in zip(self.types, self.dicts))
        return Page(blocks, jnp.ones(1, dtype=jnp.bool_))

    def is_finished(self) -> bool:
        return self._emitted


class EnforceSingleRowOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, types: List[Type],
                 dicts: Optional[List[Optional[Dictionary]]] = None):
        super().__init__(operator_id, "EnforceSingleRow")
        self.types = types
        self.dicts = dicts or [None] * len(types)

    def create_operator(self, worker: int = 0) -> EnforceSingleRowOperator:
        return EnforceSingleRowOperator(self.context(worker), self.types, self.dicts)
