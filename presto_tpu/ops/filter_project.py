"""Filter + project: compiled page processor and its operator.

Analogue of operator/FilterAndProjectOperator.java:32 + operator/project/
PageProcessor.java:53 + sql/gen/PageFunctionCompiler.java:97. The reference compiles
filter and each projection to bytecode and runs them position-batch-at-a-time with
dictionary awareness; here the *entire* filter+projection set is one jitted function
over the page pytree — XLA fuses the predicate, the projections, and the mask update
into a single TPU kernel, which is the whole point of the batch-columnar design.

The filter result lands in the page MASK (lazy selection). Downstream operators work
under masks; compaction (the materializing step) happens only where density pays for
itself — before joins or exchanges (PageProcessor's selectedPositions made the same
lazy/materialize tradeoff).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..block import Block, Page
from ..types import Type
from .expressions import CompiledExpression, ExpressionCompiler, InputLayout, RowExpression
from .operator import Operator, OperatorContext, OperatorFactory, timed


class PageProcessor:
    """One jitted fn: page -> page with projected blocks + filtered mask."""

    def __init__(self, layout: InputLayout, filter_expr: Optional[RowExpression],
                 projections: Sequence[RowExpression], compact_output: bool = False):
        self._filter_expr = filter_expr
        self._projection_exprs = list(projections)
        self.compact_output = compact_output
        self._build(layout)

    def _build(self, layout: InputLayout) -> None:
        from ..utils import kernel_cache as kc

        filter_expr = self._filter_expr
        projections = self._projection_exprs
        compiler = ExpressionCompiler(layout)
        self.filter = compiler.compile(filter_expr) if filter_expr is not None else None
        self.projections = [compiler.compile(p) for p in projections]
        self.output_types_ = [p.type for p in self.projections]
        self.output_dicts = [p.dictionary for p in self.projections]
        # global kernel cache (PageFunctionCompiler.java:97's expression cache):
        # equal (layout, exprs) compile to behaviorally identical closures, so
        # repeated queries share one jitted kernel instead of re-tracing +
        # re-compiling per plan (~0.5s/query host overhead otherwise)
        self._layout_key = kc.layout_key(layout.types, layout.dictionaries)
        self.cache_key = ("page-processor",
                          self._layout_key,
                          kc.expr_key(filter_expr),
                          tuple(kc.expr_key(p) for p in projections),
                          self.compact_output)
        self._jitted = kc.get_or_install(self.cache_key,
                                         lambda: jax.jit(self._process))

    def _process(self, page: Page) -> Page:
        datas = tuple(b.data for b in page.blocks)
        nulls = tuple(b.nulls for b in page.blocks)
        mask = page.mask
        if self.filter is not None:
            fd, fn_ = self.filter(datas, nulls)
            keep = fd if fn_ is None else (fd & ~fn_)
            mask = mask & keep
        blocks = []
        for proj, dict_ in zip(self.projections, self.output_dicts):
            d, n = proj(datas, nulls)
            d = jnp.broadcast_to(d, page.mask.shape) if d.ndim == 0 else d
            if n is not None and n.ndim == 0:
                n = jnp.broadcast_to(n, page.mask.shape)
            blocks.append(Block(proj.type, d, n, dict_))
        out = Page(tuple(blocks), mask)
        if self.compact_output:
            from ..block import _compact
            out = _compact(out)
        return out

    def __call__(self, page: Page) -> Page:
        from ..utils import kernel_cache as kc

        # dictionaries can gain entries between plan time and this page
        # (INSERT-extended dictionaries; ArrayValues stores populated by an
        # upstream collect aggregation mid-query): expressions resolve
        # dictionary CONTENTS at compile time, so a version change must
        # rebuild against the live layout (cheap key compare per page)
        cur = kc.layout_key([b.type for b in page.blocks],
                            [b.dictionary for b in page.blocks])
        if cur != self._layout_key:
            self._build(InputLayout([b.type for b in page.blocks],
                                    [b.dictionary for b in page.blocks]))
        return self._jitted(page)

    @property
    def output_types(self) -> List[Type]:
        return self.output_types_


class FilterProjectOperator(Operator):
    def __init__(self, context: OperatorContext, processor: PageProcessor):
        super().__init__(context)
        self.processor = processor
        self._pending: Optional[Page] = None

    @property
    def output_types(self) -> List[Type]:
        return self.processor.output_types

    def needs_input(self) -> bool:
        return not self._finishing and self._pending is None

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        self._pending = self.processor(page)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        out, self._pending = self._pending, None
        if out is not None:
            self.context.record_output(out, out.capacity)
        return out

    def is_finished(self) -> bool:
        return self._finishing and self._pending is None


class FilterProjectOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, layout: Optional[InputLayout] = None,
                 filter_expr: Optional[RowExpression] = None,
                 projections: Sequence[RowExpression] = (),
                 compact_output: bool = False,
                 processor: Optional[PageProcessor] = None):
        super().__init__(operator_id, "FilterProject")
        self.processor = processor if processor is not None else \
            PageProcessor(layout, filter_expr, projections, compact_output)

    def create_operator(self, worker: int = 0) -> Operator:
        return FilterProjectOperator(self.context(worker), self.processor)
