"""Collect aggregation: array_agg / map_agg / histogram (ragged outputs).

Analogue of the reference's accumulator-state collectors
(operator/aggregation/arrayagg/ArrayAggregationFunction.java:50,
MapAggregationFunction.java, histogram/Histogram.java) — re-designed for the
engine's sort-based grouping: where the reference appends rows into per-group
BlockBuilders, here the builder keeps every input row on device, and at
finish ONE lexicographic sort by the group keys makes each group's values a
CONTIGUOUS SEGMENT — the ragged result is exactly the (offsets, values)
device pair of spi/block/ArrayBlock.java, with offsets = the group-boundary
positions. Host materialization happens once at the output boundary: the
segments install into a block.ArrayValues store and the output column is the
int32 HANDLE array (the same codes+host-store scheme varchar uses).

Mixing with algebraic aggregates in one GROUP BY is supported: the collected
rows feed the ordinary sort_group_reduce for those calls (both passes sort by
the same null-safe keys, so group order aligns).

Scope: local tier (LocalQueryRunner / task executor). The SPMD and cluster
tiers keep these single-phase and run them on the gathered side (splittable
is False, so the exchange planner never splits them)."""
from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..block import ArrayValues, Block, Dictionary, Page
from ..types import Type
from .aggregates import AggregateCall
from .hash_agg import (_call_contributions, _null_safe_keys, _reduce_all,
                       _state_widths)
from .sorting import lexsort_fast

#: aggregate names the collect builder implements
COLLECT_NAMES = ("array_agg", "map_agg", "histogram")


def _pow2(n: int) -> int:
    return 1 << max(10, (max(n, 1) - 1).bit_length())


@functools.partial(jax.jit, static_argnames=("kinds", "identities",
                                             "widths"))
def _collect_combined(keys, mask, contribs, cols, kinds, identities, widths):
    """ONE lexicographic sort by the null-safe keys feeds both halves:
    algebraic states via segment reduction over the shared permutation, and
    the collect columns permuted with the group-boundary mask — the device
    half of the ragged pair (boundaries ARE the offsets)."""
    from .hash_agg import _where_valid

    n = mask.shape[0]
    invalid = ~mask
    order = lexsort_fast(tuple(reversed(keys)) + (invalid,))
    sk = tuple(k[order] for k in keys)
    sv = mask[order]
    sc = tuple((c[0][order], c[1][order]) if isinstance(c, tuple)
               else c[order] for c in contribs)
    scol = tuple(c[order] for c in cols)

    first = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    diff = jnp.zeros(n, dtype=jnp.bool_)
    for k in sk:
        diff = diff | (k != jnp.roll(k, 1))
    new_group = sv & (first | diff)
    gid = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    num_groups = jnp.where(n > 0, gid[-1] + 1, 0)
    gid = jnp.where(sv, gid, n)

    states = _reduce_all(sc, kinds, identities, widths, gid, n)
    gkeys = []
    for k in sk:
        out = jnp.zeros(n, dtype=k.dtype)
        gkeys.append(out.at[gid].set(k, mode="drop"))
    gvalid = jnp.arange(n, dtype=jnp.int32) < num_groups
    states = [_where_valid(gvalid, s, ident)
              for s, ident in zip(states, identities)]
    return tuple(gkeys), tuple(states), gvalid, scol, sv, new_group


class CollectAggregationBuilder:
    """Keeps all input rows; one sorted pass at finish (see module doc)."""

    compact_table = True

    def __init__(self, key_types: Sequence[Type], key_dicts, calls:
                 Sequence[AggregateCall], page_capacity: int,
                 max_groups: int = 1 << 20, from_intermediate: bool = False):
        if from_intermediate:
            raise NotImplementedError(
                "collect aggregates are single-phase (splittable=False)")
        self.user_key_types = list(key_types)
        from ..types import BOOLEAN
        self.key_types = [x for t in key_types for x in (t, BOOLEAN)]
        self.key_dicts = list(key_dicts)
        self.calls = list(calls)
        self.from_intermediate = False
        self.max_groups = max_groups
        self._pages: List[Page] = []
        self._host_pages: List = []
        self._bytes = 0

    def set_channels(self, key_channels):
        self._key_channels = tuple(key_channels)
        return self

    def share_kernels(self, donor) -> None:
        pass  # the sort kernel is module-level jitted (shared by shape)

    def add_page(self, page: Page) -> None:
        self._pages.append(page)
        self._bytes += sum(b.data.nbytes for b in page.blocks)

    # ---- spill protocol (device HBM -> host RAM) -------------------------
    def memory_bytes(self) -> int:
        return self._bytes

    def spill(self) -> None:
        for p in self._pages:
            self._host_pages.append(jax.device_get(p))
        self._pages = []
        self._bytes = 0

    # ----------------------------------------------------------------------

    def _concat_page(self) -> Optional[Page]:
        pages = self._host_pages + self._pages
        self._host_pages, self._pages = [], []
        if not pages:
            return None
        total = sum(p.capacity for p in pages)
        cap = _pow2(total)
        nblocks = len(pages[0].blocks)
        blocks = []
        for i in range(nblocks):
            data = jnp.concatenate([jnp.asarray(p.blocks[i].data)
                                    for p in pages])
            if cap > total:
                data = jnp.concatenate(
                    [data, jnp.zeros(cap - total, dtype=data.dtype)])
            nulls = None
            if any(p.blocks[i].nulls is not None for p in pages):
                nulls = jnp.concatenate([jnp.asarray(p.blocks[i].null_mask())
                                         for p in pages])
                if cap > total:
                    nulls = jnp.concatenate(
                        [nulls, jnp.zeros(cap - total, dtype=jnp.bool_)])
            b0 = pages[0].blocks[i]
            blocks.append(Block(b0.type, data, nulls, b0.dictionary))
        mask = jnp.concatenate([jnp.asarray(p.mask) for p in pages])
        if cap > total:
            mask = jnp.concatenate(
                [mask, jnp.zeros(cap - total, dtype=jnp.bool_)])
        return Page(tuple(blocks), mask)

    @staticmethod
    def _decode(vals: np.ndarray, nulls: Optional[np.ndarray], t: Type,
                d: Optional[Dictionary]):
        """numpy slice -> python values (the to_pylist recipe)."""
        if d is not None:
            out = list(d.lookup(vals.astype(np.int64)))
        else:
            out = [t.to_python(v) for v in vals]
        if nulls is not None:
            out = [None if n else v for v, n in zip(out, nulls)]
        return out

    def _collect_columns(self, page: Page):
        """Per collect call: (arrays to permute, metadata for host decode)."""
        cols = []
        meta = []  # (call, mode, slots: [(type, dict, has_nulls)...])
        for call in self.calls:
            name = call.function.name
            if name not in COLLECT_NAMES:
                meta.append(None)
                continue
            part = page.mask
            if call.mask_channel is not None:
                mc = page.blocks[call.mask_channel]
                mcv = mc.data.astype(jnp.bool_)
                if mc.nulls is not None:
                    mcv = mcv & ~mc.nulls
                part = part & mcv
            # map keys / histogram values never include NULL entries
            skip_null_args = {"map_agg": (0,), "histogram": (0,),
                              "array_agg": ()}[name]
            for ai in skip_null_args:
                b = page.blocks[call.input_channels[ai]]
                if b.nulls is not None:
                    part = part & ~b.nulls
            slot_info = []
            arrs = [part]
            for ch in call.input_channels:
                b = page.blocks[ch]
                arrs.append(b.data)
                has_n = b.nulls is not None
                if has_n:
                    arrs.append(b.nulls)
                slot_info.append((b.type, b.dictionary, has_n))
            cols.extend(arrs)
            mode = "array" if name == "array_agg" else "map"
            meta.append((call, mode, slot_info, len(arrs)))
        return cols, meta

    def finish(self):
        page = self._concat_page()
        from ..types import BOOLEAN
        if page is None:
            if not self.user_key_types:
                # global collect over empty input: one all-NULL group
                return self._global_empty()
            z = tuple(jnp.zeros(0, dtype=t.np_dtype) for t in self.key_types)
            states = []
            for call in self.calls:
                if call.function.name in COLLECT_NAMES:
                    states.append(jnp.zeros(0, dtype=np.int32))
                    continue
                for col in call.function.state:
                    shape = (0, col.width) if col.width > 1 else (0,)
                    states.append(jnp.zeros(shape, dtype=np.dtype(col.dtype)))
            return z, tuple(states), jnp.zeros(0, dtype=jnp.bool_)

        keys = _null_safe_keys(page, self._key_channels) \
            if self._key_channels else \
            (jnp.zeros(page.capacity, dtype=jnp.int32),
             jnp.zeros(page.capacity, dtype=jnp.bool_))
        cap = page.capacity

        # ONE sorted pass: algebraic states + permuted collect columns share
        # the same lexsort (the permutation is the expensive kernel here)
        algebraic = [c for c in self.calls
                     if c.function.name not in COLLECT_NAMES]
        contribs = _call_contributions(algebraic, page, False)
        kinds = tuple(col.reduce for c in algebraic
                      for col in c.function.state)
        idents = tuple(col.identity for c in algebraic
                       for col in c.function.state)
        widths = _state_widths(algebraic)
        cols, meta = self._collect_columns(page)
        gkeys, states, gvalid, sc, sv, new_group = _collect_combined(
            keys, page.mask, tuple(contribs), tuple(cols), kinds, idents,
            widths)
        alg_states = {}
        it = iter(states)
        for c in algebraic:
            alg_states[id(c)] = [next(it) for _ in c.function.state]

        # host materialization: boundaries are the ragged offsets
        n_live = int(np.asarray(sv).sum())
        starts = np.flatnonzero(np.asarray(new_group))
        num_groups = len(starts)
        ends = np.append(starts[1:], n_live)

        collect_handles: List[np.ndarray] = []
        col_cursor = 0
        for call, m in zip(self.calls, meta):
            if m is None:
                continue
            _call, mode, slot_info, n_arrs = m
            arrs = [np.asarray(sc[col_cursor + k]) for k in range(n_arrs)]
            col_cursor += n_arrs
            part = arrs[0]
            slots = []
            ai = 1
            for (t, d, has_n) in slot_info:
                vals = arrs[ai]
                ai += 1
                nulls = arrs[ai] if has_n else None
                if has_n:
                    ai += 1
                slots.append((t, d, vals, nulls))
            store: ArrayValues = call.function.output_dict
            handles = np.full(max(num_groups, 1), -1, dtype=np.int32)
            for g in range(num_groups):
                lo, hi = starts[g], ends[g]
                keep = np.flatnonzero(part[lo:hi]) + lo
                if len(keep) == 0:
                    continue
                decoded = [self._decode(vals[keep],
                                        nulls[keep] if nulls is not None
                                        else None, t, d)
                           for (t, d, vals, nulls) in slots]
                if call.function.name == "array_agg":
                    entry = tuple(decoded[0])
                elif call.function.name == "map_agg":
                    seen = {}
                    for k_, v_ in zip(decoded[0], decoded[1]):
                        if k_ not in seen:
                            seen[k_] = v_
                    entry = tuple(seen.items())
                else:  # histogram
                    from collections import Counter
                    entry = tuple(Counter(decoded[0]).items())
                handles[g] = store.extend([entry])[0]
            collect_handles.append(handles)

        if not self.user_key_types:
            # global: exactly one group (handles[0]; empty input never gets
            # here — _global_empty covers it)
            out_states = []
            it = iter(collect_handles)
            for call, m in zip(self.calls, meta):
                if m is None:
                    out_states.extend(s[:1] for s in alg_states[id(call)])
                else:
                    out_states.append(jnp.asarray(next(it)[:1]))
            return (), tuple(out_states), jnp.ones(1, dtype=jnp.bool_)

        # grouped: collect handles (host order = sorted group order) align
        # with gkeys/gvalid from sort_group_reduce (same sort -> same order)
        out_states = []
        it = iter(collect_handles)
        for call, m in zip(self.calls, meta):
            if m is None:
                out_states.extend(alg_states[id(call)])
            else:
                h = next(it)
                full = np.full(cap, -1, dtype=np.int32)
                full[:min(len(h), cap)] = h[:cap]
                out_states.append(jnp.asarray(full))
        return gkeys, tuple(out_states), gvalid

    def _global_empty(self):
        states = []
        for call in self.calls:
            if call.function.name in COLLECT_NAMES:
                states.append(jnp.full(1, -1, dtype=np.int32))
            else:
                for col in call.function.state:
                    w = col.width
                    arr = jnp.full((1, w) if w > 1 else (1,), col.identity,
                                   dtype=np.dtype(col.dtype))
                    states.append(arr)
        return (), tuple(states), jnp.ones(1, dtype=jnp.bool_)
