"""Table scan operator: connector page source -> device pages.

Analogue of operator/TableScanOperator.java and the fused
ScanFilterAndProjectOperator.java:55. The host-side generator/connector produces
numpy pages; this operator uploads them to the device and runs the fused
filter+project processor so the very first device kernel already prunes.

TPU-first design of the host→HBM boundary (the streaming-scan wall):
- connectors may emit NARROW dtypes (see tpch connector `_narrow_array`) — the
  scan widens back to each block's declared type ON DEVICE, inside the same
  jitted program as the filter/projections, so the narrow form only exists on
  the wire;
- the staged scan pipeline (ops/scan_pipeline.py) walks the page source ahead
  of the driver: split-parallel readers decode row ranges concurrently,
  chunks re-batch into canonical device-shaped pages, and a dedicated upload
  stage issues async `jax.device_put`s under a bytes-bounded budget — the
  role `isBlocked` futures play in the reference's ScanFilterAndProject
  laziness (operator/Driver.java:347-434 overlap of IO and compute), deepened
  into a real pipeline.
"""
from __future__ import annotations

import threading
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp

from ..block import Block, Page
from ..spi.connector import ConnectorPageSource
from ..types import Type
from .filter_project import PageProcessor
from .operator import Operator, OperatorContext, OperatorFactory, timed
from .scan_pipeline import ScanPipeline, page_nbytes


class _ResidentPageCache:
    """Bounded LRU of UPLOADED device pages per page-source cache token.

    The warm-scan analogue of the reference's LocalQueryRunner benchmarks
    (pages live in memory across queries): a source that declares itself
    deterministic+immutable (ConnectorPageSource.cache_token) has its device
    pages kept resident, so repeat scans skip host generation AND the
    host→HBM upload entirely. Eviction drops whole streams LRU-first; freeing
    the last reference releases the HBM."""

    def __init__(self, max_bytes: int = 6 << 30):
        self.max_bytes = max_bytes
        self._pages = {}
        self._order: list = []
        self._bytes = 0
        self._lock = threading.Lock()

    # one page-size formula engine-wide: cache eviction and the scan
    # pipeline's byte-budget backpressure must never disagree
    _page_bytes = staticmethod(page_nbytes)

    def get(self, token):
        with self._lock:
            hit = self._pages.get(token)
            if hit is not None:
                self._order.remove(token)
                self._order.append(token)
            return hit

    def put(self, token, pages) -> None:
        size = sum(self._page_bytes(p) for p in pages)
        if size > self.max_bytes:
            return
        with self._lock:
            if token in self._pages:
                return
            while self._bytes + size > self.max_bytes and self._order:
                old = self._order.pop(0)
                self._bytes -= sum(self._page_bytes(p)
                                   for p in self._pages.pop(old))
            self._pages[token] = list(pages)
            self._order.append(token)
            self._bytes += size

    def clear(self) -> None:
        with self._lock:
            self._pages.clear()
            self._order.clear()
            self._bytes = 0


RESIDENT_CACHE = _ResidentPageCache()

from ..utils.metrics import METRICS as _METRICS  # noqa: E402

_METRICS.set_gauge("scan.resident_cache_bytes",
                   lambda: RESIDENT_CACHE._bytes)
_METRICS.set_gauge("scan.resident_cache_streams",
                   lambda: len(RESIDENT_CACHE._pages))


def _widen_page(page: Page) -> Page:
    """Device-side upcast of narrow wire blocks to their declared dtypes."""
    blocks = []
    for b in page.blocks:
        want = jnp.dtype(b.type.np_dtype)
        data = b.data if b.data.dtype == want else b.data.astype(want)
        blocks.append(Block(b.type, data, b.nulls, b.dictionary))
    return Page(tuple(blocks), page.mask.astype(jnp.bool_))


# module-level singleton: page types/dictionaries are pytree aux data, so one
# jit object handles every schema (retracing per treedef, never per query)
_widen_jit = jax.jit(_widen_page)


class TableScanOperator(Operator):
    def __init__(self, context: OperatorContext, source: ConnectorPageSource,
                 types: List[Type], processor: Optional[PageProcessor] = None,
                 device=None, ready=None, process_fn=None, prefetch: bool = True,
                 scan_options: Optional[dict] = None):
        super().__init__(context)
        self.source = source
        self._types = types
        self.processor = processor
        self.device = device
        self._process_fn = process_fn  # shared jitted widen(+filter/project)
        self._ready = ready  # None = always ready; else poll before reading
        self._done = False
        self._prefetch_enabled = prefetch
        # session-resolved pipeline knobs (exec/local_planner): reader pool
        # size, re-batch target rows, in-flight byte bound, rebatch on/off
        self._scan_options = scan_options or {}
        self._pipeline: Optional[ScanPipeline] = None
        self._pipeline_stats: Optional[dict] = None
        self._iter: Optional[Iterator[Page]] = None
        # device-resident replay: a deterministic source's uploaded pages are
        # cached across queries (see _ResidentPageCache); keyed by target
        # device too — worker w must never replay pages resident on another
        # worker's chip
        token = getattr(source, "cache_token", None)
        self._cache_token = None if token is None else (token, device)
        self._replay: Optional[Iterator[Page]] = None
        self._collected: Optional[List[Page]] = None
        self._collected_bytes = 0
        if self._cache_token is not None:
            hit = RESIDENT_CACHE.get(self._cache_token)
            if hit is not None:
                self._replay = iter(hit)
            else:
                self._collected = []

    def is_blocked(self):
        """A replay scan (union buffer) blocks until its producers finish —
        under the task executor, pipeline order no longer implies completion
        order, so the dependency must be an explicit blocked state."""
        if self._ready is None:
            return None
        if self._ready():
            self._ready = None
            return None
        return self._ready

    @property
    def output_types(self) -> List[Type]:
        return self.processor.output_types if self.processor else self._types

    def needs_input(self) -> bool:
        return False  # source operator

    def add_input(self, page: Page) -> None:
        raise RuntimeError("table scan takes no input")

    def _next_uploaded(self) -> Optional[Page]:
        if self._replay is not None:
            return next(self._replay, None)
        if self._prefetch_enabled:
            if self._pipeline is None:
                # None/0 thread/byte knobs fall through to ScanPipeline's
                # engine defaults; target_rows has NO default — without a
                # planner-resolved page capacity the pipeline runs the
                # passthrough path (source page shapes, no split fan-out)
                opts = self._scan_options
                self._pipeline = ScanPipeline(
                    self.source, self.device,
                    reader_threads=opts.get("reader_threads"),
                    target_rows=opts.get("target_rows"),
                    prefetch_bytes=opts.get("prefetch_bytes"),
                    rebatch=bool(opts.get("rebatch", True)),
                    # per-query fairness slot on the shared scan pool (None
                    # = dedicated threads, the shared_pools=False oracle)
                    pool_key=opts.get("pool_key"),
                    # prefetch bytes are USER memory of the owning query:
                    # staged + uploaded-unconsumed pages compete with
                    # operator state in the query's pool
                    memory=self.context.memory.user
                    .new_local_memory_context("scan_prefetch"))
            page = self._pipeline.next()
        else:
            if self._iter is None:
                self._iter = iter(self.source)
            try:
                page = next(self._iter)
            except StopIteration:
                page = None
            if page is not None:
                page = jax.tree.map(
                    lambda a: jax.device_put(a, self.device), page)
        if self._collected is not None:
            if page is None:
                # stream exhausted without error: install for future scans
                RESIDENT_CACHE.put(self._cache_token, self._collected)
                self._collected = None
            else:
                # bound collection AS WE GO: a stream too big for the cache
                # must not pin its pages live until exhaustion — abandoning
                # restores pure streaming (prefetch depth bounds memory)
                self._collected_bytes += _ResidentPageCache._page_bytes(page)
                if self._collected_bytes > RESIDENT_CACHE.max_bytes // 2:
                    self._collected = None
                else:
                    self._collected.append(page)
        return page

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._done:
            return None
        page = self._next_uploaded()
        if page is None:
            self._done = True
            self.source.close()
            return None
        self.context.record_input(page, page.capacity)
        if self._process_fn is not None:
            page = self._process_fn(page)
        elif self.processor is not None:
            page = self.processor(_widen_page(page))
        else:
            page = _widen_page(page)
        self.context.record_output(page, page.capacity)
        return page

    def is_finished(self) -> bool:
        return self._done or self._finishing

    def pipeline_stats(self) -> Optional[dict]:
        """Per-stage busy/stall seconds of this scan's pipeline (None when
        the scan replayed resident pages or ran the serial path). Survives
        close() so the runner can roll it into QueryResult.stats."""
        if self._pipeline is not None:
            return self._pipeline.stats()
        return self._pipeline_stats

    def close(self) -> None:
        if self._pipeline is not None:
            self._pipeline_stats = self._pipeline.stats()
            # stops every stage and JOINS the threads (bounded) — a producer
            # mid jax.device_put must never race interpreter teardown
            self._pipeline.close()
            self._pipeline = None
        super().close()


class TableScanOperatorFactory(OperatorFactory):
    """`page_sources` is either a list (every worker scans those sources — the
    single-worker / replay case) or a callable worker -> source list (the
    distributed case: worker-scoped splits or exchange-output pages). Each
    create_operator(w) call consumes the next unclaimed source of worker w, so
    several drivers of one worker can split a multi-source scan."""

    def __init__(self, operator_id: int, page_sources, types: List[Type],
                 processor: Optional[PageProcessor] = None, ready=None,
                 prefetch: bool = True):
        super().__init__(operator_id, "TableScan")
        # worker -> target device (set by the planner in distributed mode so
        # worker w's pages live on mesh device w and downstream fragment
        # chains stay device-resident; None = default device)
        self.devices = None
        # scan-pipeline knobs resolved from the session by the planner
        # (None = ScanPipeline defaults for directly-constructed factories)
        self.scan_options = None
        if callable(page_sources):
            self._sources_fn = page_sources
        else:
            srcs = list(page_sources)
            self._sources_fn = lambda w: list(srcs)
        self._types = types
        self._processor = processor
        self._ready = ready  # worker -> poll-able "producers finished?"
        self._remaining = {}
        self._prefetch = prefetch
        # one shared jit for widen+filter+project: a single kernel per page,
        # shared across all drivers/workers of this factory — and, via the
        # global kernel cache, across repeated queries with the same processor
        # fingerprint (one compile per distinct scan kernel, ever)
        if processor is not None:
            from ..utils import kernel_cache as kc

            self._process_fn = kc.get_or_install(
                ("scan-fused", processor.cache_key),
                lambda: jax.jit(
                    lambda p: processor._process(_widen_page(p))))
        else:
            self._process_fn = _widen_jit

    def set_parallelism(self, n: int) -> None:
        """Re-deal each worker's sources into `n` groups so `n` drivers can
        each scan a share (intra-pipeline driver parallelism: the reference
        feeds N Drivers from split assignment, SqlTaskExecution.java:1013)."""
        inner = self._sources_fn

        def dealt(w: int):
            from ..exec.local_planner import _ConcatPageSource

            srcs = []
            for s in inner(w):
                srcs.extend(s.sources if isinstance(s, _ConcatPageSource)
                            else [s])
            groups = [[srcs[i] for i in range(g, len(srcs), n)]
                      for g in range(n)]
            return [_ConcatPageSource(g) for g in groups]

        self._sources_fn = dealt

    def create_operator(self, worker: int = 0) -> Operator:
        if worker not in self._remaining:
            self._remaining[worker] = list(self._sources_fn(worker))
        src = self._remaining[worker].pop(0)
        device = None
        if self.devices:
            device = self.devices[worker % len(self.devices)]
        return TableScanOperator(self.context(worker), src, self._types,
                                 self._processor, device=device,
                                 ready=self._ready(worker) if self._ready else None,
                                 process_fn=self._process_fn,
                                 prefetch=self._prefetch,
                                 scan_options=self.scan_options)
