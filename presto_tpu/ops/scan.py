"""Table scan operator: connector page source -> device pages.

Analogue of operator/TableScanOperator.java and the fused
ScanFilterAndProjectOperator.java:55. The host-side generator/connector produces numpy
pages; this operator uploads them to the device (`jax.device_put`), optionally through
a fused filter+project processor so the very first device kernel already prunes —
the host->HBM transfer is the analogue of the reference's page-source read, and
fusion here minimizes the bytes that ever hit later pipeline stages.
"""
from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp

from ..block import Page
from ..spi.connector import ConnectorPageSource
from ..types import Type
from .filter_project import PageProcessor
from .operator import Operator, OperatorContext, OperatorFactory, timed


class TableScanOperator(Operator):
    def __init__(self, context: OperatorContext, source: ConnectorPageSource,
                 types: List[Type], processor: Optional[PageProcessor] = None,
                 device=None, ready=None):
        super().__init__(context)
        self.source = source
        self._iter: Optional[Iterator[Page]] = None
        self._types = types
        self.processor = processor
        self.device = device
        self._ready = ready  # None = always ready; else poll before reading
        self._done = False

    def is_blocked(self):
        """A replay scan (union buffer) blocks until its producers finish —
        under the task executor, pipeline order no longer implies completion
        order, so the dependency must be an explicit blocked state."""
        if self._ready is None:
            return None
        if self._ready():
            self._ready = None
            return None
        return self._ready

    @property
    def output_types(self) -> List[Type]:
        return self.processor.output_types if self.processor else self._types

    def needs_input(self) -> bool:
        return False  # source operator

    def add_input(self, page: Page) -> None:
        raise RuntimeError("table scan takes no input")

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._done:
            return None
        if self._iter is None:
            self._iter = iter(self.source)
        try:
            page = next(self._iter)
        except StopIteration:
            self._done = True
            self.source.close()
            return None
        # upload: host numpy blocks -> device arrays (async under XLA)
        page = jax.tree.map(lambda a: jax.device_put(a, self.device), page)
        self.context.record_input(page, page.capacity)
        if self.processor is not None:
            page = self.processor(page)
        self.context.record_output(page, page.capacity)
        return page

    def is_finished(self) -> bool:
        return self._done or self._finishing


class TableScanOperatorFactory(OperatorFactory):
    """`page_sources` is either a list (every worker scans those sources — the
    single-worker / replay case) or a callable worker -> source list (the
    distributed case: worker-scoped splits or exchange-output pages). Each
    create_operator(w) call consumes the next unclaimed source of worker w, so
    several drivers of one worker can split a multi-source scan."""

    def __init__(self, operator_id: int, page_sources, types: List[Type],
                 processor: Optional[PageProcessor] = None, ready=None):
        super().__init__(operator_id, "TableScan")
        if callable(page_sources):
            self._sources_fn = page_sources
        else:
            srcs = list(page_sources)
            self._sources_fn = lambda w: list(srcs)
        self._types = types
        self._processor = processor
        self._ready = ready  # worker -> poll-able "producers finished?"
        self._remaining = {}

    def create_operator(self, worker: int = 0) -> Operator:
        if worker not in self._remaining:
            self._remaining[worker] = list(self._sources_fn(worker))
        src = self._remaining[worker].pop(0)
        return TableScanOperator(self.context(worker), src, self._types,
                                 self._processor,
                                 ready=self._ready(worker) if self._ready else None)
