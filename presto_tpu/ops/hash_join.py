"""Hash join on TPU: dense-domain and sort-merge lookup kernels.

Analogue of the reference join stack: HashBuilderOperator.java (build),
PagesIndex.java:74 + PagesHash.java:34 (open-addressed table over row addresses),
LookupJoinOperator.java:53 + JoinProbe (probe), LookupJoinPageBuilder (output),
PartitionedLookupSourceFactory (sharing the table across probe drivers).

TPU re-design: per-row open addressing is scatter-chasing and serial, so the lookup
structure is one of:

1. DENSE — build keys scattered into a dense int32 row-index table over the key
   domain [min,max]; probing is ONE gather. Every TPC-H dimension join (custkey,
   orderkey, partkey, suppkey) is a dense-PK join, so this is the common fast path —
   think of it as the TPU's answer to the reference's BigintGroupByHash-style
   specialization.
2. SORTED — build rows sorted by 64-bit key; probe via vectorized binary search
   (jnp.searchsorted over the sorted key array). Handles duplicate build keys via
   [lo,hi) ranges and arbitrary key domains; multi-column keys go through a 64-bit
   mix with post-match verification on the true key columns (collisions only mask
   rows, never corrupt results).
3. PALLAS — a masked open-addressing table built and probed by the Pallas
   kernels in ops/pallas_hash.py (the reference's PagesHash shape, fixed-trip
   linear probing). Selected by the `hash_kernels` session property for
   unique single-key INNER/LEFT builds; anything else — duplicate keys,
   multi-key, FULL joins, an oversized or overflowing table — falls back to
   SORTED at build time (the differential oracle), never errs.

Join row expansion (output cardinality > input) is the two-pass count-then-emit the
reference's LookupJoinPageBuilder does with position lists: cumsum of match counts,
then per-output-slot inverse search. The unique-build path (declared by the planner
for PK joins) skips all of that and emits exactly one output row per probe row.

The build result is shared through a LookupSourceFactory future: probe drivers block
on it exactly like LookupJoinOperator blocks on lendLookupSource in the reference.
"""
from __future__ import annotations

import dataclasses
import functools
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..block import Block, Dictionary, Page
from ..exec.spill import storage_type_for
from ..types import BIGINT, Type
from .operator import Operator, OperatorContext, OperatorFactory, timed

INNER, LEFT, RIGHT, FULL, SEMI, ANTI = "inner", "left", "right", "full", "semi", "anti"


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = x.astype(jnp.uint64)
    x = (x ^ (x >> 33)) * jnp.uint64(0xFF51AFD7ED558CCD)
    x = (x ^ (x >> 33)) * jnp.uint64(0xC4CEB9FE1A85EC53)
    return x ^ (x >> 33)


def combined_key(keys: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Multi-column equi-key -> one int64 (exact for single int key, mixed otherwise)."""
    if len(keys) == 1:
        return keys[0].astype(jnp.int64)
    acc = _mix64(keys[0].astype(jnp.int64))
    for k in keys[1:]:
        acc = _mix64(acc ^ (k.astype(jnp.int64).astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15)))
    return acc.astype(jnp.int64)


@dataclasses.dataclass
class LookupSource:
    kind: str                          # "dense" | "sorted" | "pallas"
    key_arrays: Tuple[jnp.ndarray, ...]  # true build key columns (compacted)
    payload: Tuple[jnp.ndarray, ...]   # build output columns (compacted)
    payload_meta: List[Tuple[Type, Optional[Dictionary]]]
    build_count: jnp.ndarray           # scalar int32 live rows
    unique: bool
    # dense:
    table: Optional[jnp.ndarray] = None   # (domain,) int32 row idx, -1 empty
    base: int = 0
    # sorted:
    sorted_key: Optional[jnp.ndarray] = None  # (n,) int64 combined keys, invalid rows +inf
    sorted_row: Optional[jnp.ndarray] = None  # (n,) int32 original row index
    # pallas (ops/pallas_hash.py open-addressing table):
    ph_keys: Optional[jnp.ndarray] = None  # (slots,) int64 stored keys
    ph_rows: Optional[jnp.ndarray] = None  # (slots,) int32 row idx, -1 empty
    ph_trips: int = 0                      # STATIC probe trip count (pow2)
    # exact multi-key packing (offsets/shifts/widths per key column): when the
    # build key ranges fit 63 bits, the combined key is a bijective pack — no
    # mixed-hash collisions, so every multi-key path gets the exact fast paths
    pack_offsets: Optional[jnp.ndarray] = None
    pack_shifts: Optional[jnp.ndarray] = None
    pack_widths: Optional[jnp.ndarray] = None
    # per-payload-column null masks (None entries = column has no nulls):
    payload_nulls: Tuple = ()
    # whether any live build row had a NULL key (drives null-aware NOT IN semantics)
    has_null_key: bool = False
    # FULL-join side buffer: build rows whose key was NULL never match but must
    # still appear unmatched in the output (tracked separately because matching
    # structures exclude them)
    null_key_payload: Optional[Tuple] = None
    null_key_nulls: Tuple = ()
    null_key_count: int = 0

    @property
    def exact_keys(self) -> bool:
        """True when sorted_key equality implies true key equality: single
        INTEGER key, or a bijectively packed multi-key. Un-packable
        multi-key mixes (ranges beyond 63 bits) AND float single keys
        (combined_key's astype(int64) truncates 1.2 and 1.5 to the same
        sorted key) must range-scan + verify candidates instead of trusting
        the one searchsorted position."""
        if len(self.key_arrays) > 1:
            return self.pack_offsets is not None
        if self.key_arrays and not (
                np.issubdtype(np.dtype(self.key_arrays[0].dtype), np.integer)
                or np.dtype(self.key_arrays[0].dtype) == np.bool_):
            return False
        return True

    def combine_probe(self, probe_keys) -> jnp.ndarray:
        """Probe keys -> the build's combined-key space (packed when exact;
        out-of-range probes map to a negative sentinel that matches nothing)."""
        if self.pack_offsets is None:
            return combined_key(probe_keys)
        return _pack_key(tuple(probe_keys), self.pack_offsets,
                         self.pack_shifts, self.pack_widths)


class LookupSourceFactory:
    """PartitionedLookupSourceFactory analogue: a future the probes block on.

    One slot per worker task — each worker's build pipeline publishes its own
    lookup source and only that worker's probe drivers consume it (the reference
    scopes the factory to a task; here the factory is shared across workers for
    kernel reuse, so the handoff is worker-keyed)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slots = {}

    def _slot(self, worker: int):
        with self._lock:
            slot = self._slots.get(worker)
            if slot is None:
                slot = self._slots[worker] = [threading.Event(), None]
            return slot

    def set(self, source: LookupSource, worker: int = 0) -> None:
        slot = self._slot(worker)
        slot[1] = source
        slot[0].set()

    def done(self, worker: int = 0) -> bool:
        return self._slot(worker)[0].is_set()

    def get(self, worker: int = 0) -> LookupSource:
        return self._slot(worker)[1]


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------

class JoinBuildOperator(Operator):
    """HashBuilderOperator analogue (sink side of the build pipeline)."""

    def __init__(self, context: OperatorContext, factory: "JoinBuildOperatorFactory"):
        super().__init__(context)
        self.f = factory
        self._pages: List[Page] = []       # device-resident
        self._host_pages: List[Page] = []  # spilled to host RAM (numpy)
        self._disk_runs: List = []         # spilled to disk (exec/spill.py runs)
        self._null_key_pages: List[Page] = []  # FULL join: unmatched-by-construction
        self._saw_null_key = None  # device bool accumulator, synced once at build

    @property
    def output_types(self) -> List[Type]:
        return []

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        for c in self.f.key_channels:
            if page.blocks[c].nulls is not None:
                seen = jnp.any(page.blocks[c].nulls & page.mask)
                self._saw_null_key = seen if self._saw_null_key is None \
                    else (self._saw_null_key | seen)
        self._pages.append(_compact_for_build(page, tuple(self.f.key_channels),
                                              tuple(self.f.payload_channels)))
        if self.f.track_unmatched and \
                any(page.blocks[c].nulls is not None
                    for c in self.f.key_channels):
            # FULL join: keep NULL-key build rows aside — they never match but
            # must surface as unmatched rows in the output. No device sync
            # here: the rows are filtered by mask once at _build.
            nk = jnp.zeros_like(page.mask)
            for c in self.f.key_channels:
                if page.blocks[c].nulls is not None:
                    nk = nk | page.blocks[c].nulls
            nk = nk & page.mask
            sel = page.select_channels(list(self.f.payload_channels))
            self._null_key_pages.append(sel.with_mask(nk))
        self.context.update_revocable(self.revocable_bytes(),
                                      self.start_memory_revoke)

    # spill protocol: one revoke walks the whole ladder (HashBuilderOperator
    # spill states :155-180 analogue). Rung 1 offloads accumulated device
    # pages to host RAM; rung 2 (when the query has a disk tier attached)
    # compacts host pages into PCOL runs via exec/spill.py — _build re-admits
    # disk runs to host and host pages to device before the fused build.
    # Revocable = device pages + disk-eligible host pages; host pages whose
    # dtypes have no pcol storage type stay in RAM (disk is an optimisation
    # rung, never a correctness requirement) and stop counting as revocable.
    def revocable_bytes(self) -> int:
        total = 0
        for p in self._pages + self._null_key_pages:
            if isinstance(p.mask, np.ndarray):
                continue  # already host-resident (revoked earlier)
            rows = p.capacity
            total += rows  # mask
            for b in p.blocks:
                total += rows * np.dtype(b.data.dtype).itemsize
                if b.nulls is not None:
                    total += rows
        if self.context.spill is not None:
            for p in self._host_pages:
                if _page_disk_eligible(p):
                    total += _host_page_bytes(p)
        return total

    def start_memory_revoke(self) -> None:
        self._host_pages.extend(jax.device_get(p) for p in self._pages)
        self._pages = []
        self._null_key_pages = [p if isinstance(p.mask, np.ndarray)
                                else jax.device_get(p)
                                for p in self._null_key_pages]
        if self.context.spill is not None:
            self._spill_host_to_disk()
        self.context.revocable_memory.set_bytes(self.revocable_bytes())

    def _spill_host_to_disk(self) -> None:
        """Rung 2: host pages -> compacted on-disk PCOL runs. Dictionary
        blocks write their code arrays; the Dictionary objects (small,
        shared) ride along in run.meta so the read side rebuilds bit-exact
        Blocks. Ineligible pages are kept in host RAM."""
        mgr = self.context.spill
        keep: List[Page] = []
        for p in self._host_pages:
            if not _page_disk_eligible(p):
                keep.append(p)
                continue
            live = np.flatnonzero(np.asarray(p.mask))
            if len(live) == 0:
                continue  # nothing to rebuild — drop the page
            names, cols, specs = [], [], []
            for i, b in enumerate(p.blocks):
                names.append(f"c{i}")
                cols.append(np.ascontiguousarray(np.asarray(b.data)[live]))
                if b.nulls is not None:
                    names.append(f"n{i}")
                    cols.append(np.ascontiguousarray(
                        np.asarray(b.nulls)[live]))
                specs.append((b.type, b.dictionary, b.nulls is not None))
            self._disk_runs.append(mgr.write_columns(
                names, cols, kind="join", meta={"blocks": specs}))
        self._host_pages = keep

    def get_output(self) -> Optional[Page]:
        return None

    def finish(self) -> None:
        if self._finishing:
            return
        super().finish()
        # partitioned parallel build (PartitionedLookupSourceFactory
        # analogue): N build drivers per worker ingest concurrently; the LAST
        # one to finish merges every driver's collected pages and runs the
        # single fused device build (one sort kernel over the union — on TPU
        # the chip parallelizes the sort, so the drivers' job is overlapping
        # host generation/upload/page prep, which is where build wall goes)
        self.f._builder_done(self)
        self.context.revocable_memory.set_bytes(0)

    def _build(self) -> LookupSource:
        kc = len(self.f.key_channels)
        if self._disk_runs:  # re-admit disk runs first (disk -> host RAM)
            runs, self._disk_runs = self._disk_runs, []
            mgr = self.context.spill
            for run in runs:
                self._host_pages.append(_page_from_run(mgr, run))
                mgr.release(run)
        if self._host_pages:  # re-admit spilled pages (host -> device upload)
            self._pages = self._host_pages + self._pages
            self._host_pages = []
        if not self._pages:
            empty = tuple(jnp.zeros(1, dtype=jnp.int64) for _ in range(kc))
            empty_payload = tuple(jnp.zeros(1, dtype=t.np_dtype)
                                  for (t, _) in self.f.payload_meta)
            return LookupSource(
                kind="sorted", key_arrays=empty, payload=empty_payload,
                payload_meta=self.f.payload_meta, build_count=jnp.asarray(0, jnp.int32),
                unique=True,
                sorted_key=jnp.full(1, np.iinfo(np.int64).max, dtype=jnp.int64),
                sorted_row=jnp.zeros(1, dtype=jnp.int32),
                payload_nulls=tuple(None for _ in self.f.payload_meta))
        # one fused kernel: concat across pages + count + (dense table | key
        # sort). On the device this is ONE dispatch instead of one eager
        # concatenate per column plus a host count sync — the TPU build wall
        # is dispatch round-trips, not FLOPs (operator/PagesHash.java:34's
        # role, re-shaped for a remote accelerator).
        null_cols = tuple(i for i in range(len(self.f.payload_channels))
                          if any(p.blocks[kc + i].nulls is not None
                                 for p in self._pages))
        # pad the page count to its pow2 bucket with a zero-row dummy so the
        # fused build kernel's trace signature is bounded by O(log pages)
        # distinct counts (remote compiles cost seconds each)
        pages = list(self._pages)
        want = 1 << max(0, (len(pages) - 1).bit_length())
        if want > len(pages):
            # numpy zeros, not jnp: an eager jnp.zeros dispatch compiles a
            # throwaway kernel per dtype; np arrays device_put at the jit call
            p0 = pages[0]
            zb = tuple(Block(b.type,
                             np.zeros((0,), dtype=b.data.dtype),
                             np.zeros((0,), dtype=np.bool_)
                             if b.nulls is not None else None,
                             b.dictionary)
                       for b in p0.blocks)
            zp = Page(zb, np.zeros((0,), dtype=np.bool_))
            pages.extend([zp] * (want - len(pages)))
        pages = tuple(pages)
        if self.f.strategy == "dense" and kc == 1:
            keys, payload, pnulls, mask, n_dev, table = _fused_build_dense(
                pages, kc, null_cols, self.f.dense_min,
                int(self.f.dense_max - self.f.dense_min + 1))
            src = LookupSource(
                kind="dense", key_arrays=keys, payload=payload,
                payload_meta=self.f.payload_meta,
                build_count=n_dev, unique=self.f.unique,
                table=table, base=self.f.dense_min)
        elif self.f.strategy == "pallas" and kc == 1:
            src, pnulls = self._build_pallas(pages, kc, null_cols)
        elif kc == 1:
            keys, payload, pnulls, mask, n_dev, sorted_key, sorted_row = \
                _fused_build_sorted(pages, kc, null_cols)
            src = LookupSource(
                kind="sorted", key_arrays=keys, payload=payload,
                payload_meta=self.f.payload_meta,
                build_count=n_dev, unique=self.f.unique,
                sorted_key=sorted_key, sorted_row=sorted_row)
        else:
            # multi-key: the bijective packing plan needs host min/max
            keys, payload, pnulls, mask, n_dev = _concat_parts(
                pages, kc, null_cols)
            src = _build_sorted(tuple(keys), tuple(payload), mask,
                                n_dev,
                                self.f.payload_meta, self.f.unique)
        src.payload_nulls = tuple(pnulls)
        src.has_null_key = bool(self._saw_null_key) if self._saw_null_key is not None else False
        if self._null_key_pages:
            nmask = np.concatenate([np.asarray(p.mask)
                                    for p in self._null_key_pages])
            keep = np.flatnonzero(nmask)
            cols, nils = [], []
            for i in range(len(self.f.payload_channels)):
                col = np.concatenate([np.asarray(p.blocks[i].data)
                                      for p in self._null_key_pages])
                cols.append(col[keep])
                if any(p.blocks[i].nulls is not None
                       for p in self._null_key_pages):
                    nm = np.concatenate([np.asarray(p.blocks[i].null_mask())
                                         for p in self._null_key_pages])
                    nils.append(nm[keep])
                else:
                    nils.append(None)
            src.null_key_payload = tuple(cols)
            src.null_key_nulls = tuple(nils)
            src.null_key_count = len(keep)
        return src

    def _build_pallas(self, pages, kc: int, null_cols):
        """Open-addressing build (ops/pallas_hash.py). ONE host sync per
        build reads the kernel's [overflow, max_run, distinct] stats — the
        price buys the static probe trip count; an oversized table, an
        insert overflow or an excessive probe bound falls back to the sorted
        build (row-identical by the differential contract, never an error)."""
        from ..utils.metrics import METRICS
        from . import pallas_hash as ph

        keys, payload, pnulls, mask, n_dev = _concat_parts(
            pages, kc, null_cols)
        n = int(keys[0].shape[0])
        slots = ph.table_slots(n)
        # float keys are ineligible: the table stores astype(int64) values
        # and the probe has NO true-key verify (the sorted path's
        # searchsorted also truncates, but its `bv == pk` re-check on the
        # original arrays rejects the false matches this would create)
        if not (np.issubdtype(np.dtype(keys[0].dtype), np.integer)
                or np.dtype(keys[0].dtype) == np.bool_):
            slots = None
        if slots is not None:
            insert = ph.insert_table_jit(1, n, slots)
            (slot_keys,), slot_rows, _gid, stats = insert(
                (keys[0],), mask)
            overflow, max_run, _ng = [int(x) for x in np.asarray(stats)]
            trips = ph.probe_trips_for(max_run)
            if not overflow and trips <= ph.PROBE_TRIPS_CAP:
                METRICS.count("pallas.join_builds")
                src = LookupSource(
                    kind="pallas", key_arrays=keys, payload=payload,
                    payload_meta=self.f.payload_meta, build_count=n_dev,
                    unique=self.f.unique, ph_keys=slot_keys,
                    ph_rows=slot_rows, ph_trips=trips)
                return src, pnulls
        METRICS.count("pallas.join_fallbacks")
        sorted_key, sorted_row = _sorted_kernel_ck(combined_key(keys), mask)
        return LookupSource(
            kind="sorted", key_arrays=keys, payload=payload,
            payload_meta=self.f.payload_meta, build_count=n_dev,
            unique=self.f.unique, sorted_key=sorted_key,
            sorted_row=sorted_row), pnulls

    def is_finished(self) -> bool:
        return self._finishing


def _page_disk_eligible(page: Page) -> bool:
    """Can this host-resident page round-trip through a pcol spill run?
    Every block's storage array must be 1-D with a mapped storage type."""
    for b in page.blocks:
        a = np.asarray(b.data)
        if a.ndim != 1 or storage_type_for(a.dtype) is None:
            return False
    return True


def _host_page_bytes(page: Page) -> int:
    rows = page.capacity
    total = rows  # mask
    for b in page.blocks:
        total += rows * np.dtype(b.data.dtype).itemsize
        if b.nulls is not None:
            total += rows
    return total


def _page_from_run(mgr, run) -> Page:
    """Rebuild a compacted host page from a spill run written by
    JoinBuildOperator._spill_host_to_disk (all-true mask; null masks were
    stored as bool columns, dictionaries rode along in run.meta)."""
    cols = mgr.read_columns(run)
    blocks, i = [], 0
    for (btype, bdict, has_nulls) in run.meta["blocks"]:
        data = cols[i][0]
        i += 1
        nulls = None
        if has_nulls:
            nulls = cols[i][0]
            i += 1
        blocks.append(Block(btype, data, nulls, bdict))
    return Page(tuple(blocks), np.ones(run.rows, dtype=bool))


def _compact_for_build(page: Page, key_channels: Tuple[int, ...],
                       payload_channels: Tuple[int, ...]) -> Page:
    sel = page.select_channels(list(key_channels) + list(payload_channels))
    # null keys never join: mask them out before compaction
    mask = sel.mask
    for i in range(len(key_channels)):
        if sel.blocks[i].nulls is not None:
            mask = mask & ~sel.blocks[i].nulls
    return _compact_jit(sel.with_mask(mask))


_compact_jit = jax.jit(lambda p: p.compact())


def _concat_parts_impl(pages, kc: int, null_cols):
    """Concat compacted build pages into flat key/payload/nulls/mask arrays."""
    keys = tuple(jnp.concatenate([p.blocks[i].data for p in pages])
                 for i in range(kc))
    npayload = len(pages[0].blocks) - kc
    payload = tuple(jnp.concatenate([p.blocks[kc + i].data for p in pages])
                    for i in range(npayload))
    pnulls = tuple(
        jnp.concatenate([p.blocks[kc + i].null_mask() for p in pages])
        if i in null_cols else None
        for i in range(npayload))
    mask = jnp.concatenate([p.mask for p in pages])
    n = jnp.sum(mask.astype(jnp.int32))
    return keys, payload, pnulls, mask, n


_concat_parts = functools.partial(jax.jit, static_argnames=(
    "kc", "null_cols"))(_concat_parts_impl)


@functools.partial(jax.jit, static_argnames=("kc", "null_cols", "base",
                                             "domain"))
def _fused_build_dense(pages, kc, null_cols, base, domain):
    keys, payload, pnulls, mask, n = _concat_parts_impl(pages, kc, null_cols)
    key = keys[0]
    idx = (key.astype(jnp.int64) - base).astype(jnp.int32)
    idx = jnp.where(mask, idx, domain)  # dropped
    table = jnp.full(domain, -1, dtype=jnp.int32)
    rows = jnp.arange(key.shape[0], dtype=jnp.int32)
    table = table.at[idx].set(rows, mode="drop")
    return keys, payload, pnulls, mask, n, table


@functools.partial(jax.jit, static_argnames=("kc", "null_cols"))
def _fused_build_sorted(pages, kc, null_cols):
    keys, payload, pnulls, mask, n = _concat_parts_impl(pages, kc, null_cols)
    ck = combined_key(keys)
    big = jnp.int64(np.iinfo(np.int64).max)
    ck = jnp.where(mask, ck, big)
    order = jnp.argsort(ck)
    return (keys, payload, pnulls, mask, n,
            ck[order], order.astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("domain",))
def _dense_kernel(key, payload, mask, base, domain):
    idx = (key.astype(jnp.int64) - base).astype(jnp.int32)
    idx = jnp.where(mask, idx, domain)  # dropped
    table = jnp.full(domain, -1, dtype=jnp.int32)
    rows = jnp.arange(key.shape[0], dtype=jnp.int32)
    table = table.at[idx].set(rows, mode="drop")
    return table


def _build_dense(key, payload, mask, n, kmin, kmax, payload_meta, unique) -> LookupSource:
    domain = int(kmax - kmin + 1)
    table = _dense_kernel(key, payload, mask, kmin, domain)
    return LookupSource(kind="dense", key_arrays=(key,), payload=payload,
                        payload_meta=payload_meta,
                        build_count=jnp.asarray(n, jnp.int32), unique=unique,
                        table=table, base=kmin)


@jax.jit
def _sorted_kernel_ck(ck, mask):
    big = jnp.int64(np.iinfo(np.int64).max)
    ck = jnp.where(mask, ck, big)
    order = jnp.argsort(ck)
    return ck[order], order.astype(jnp.int32)


@jax.jit
def _pack_key(keys, offsets, shifts, widths):
    """Bijective multi-key pack; out-of-range values map to a negative
    sentinel (never equal to any packed build key, which is >= 0)."""
    acc = jnp.zeros(keys[0].shape[0], dtype=jnp.int64)
    oob = jnp.zeros(keys[0].shape[0], dtype=jnp.bool_)
    for i, k in enumerate(keys):
        v = k.astype(jnp.int64) - offsets[i]
        oob = oob | (v < 0) | (v >= (jnp.int64(1) << widths[i]))
        acc = acc | (jnp.clip(v, 0, None) << shifts[i])
    sentinel = jnp.int64(np.iinfo(np.int64).min // 2)
    return jnp.where(oob, sentinel, acc)


def _plan_packing(keys, mask):
    """Host-side packing plan: per-key offsets/shifts/widths, or None when the
    combined ranges exceed 62 bits. One device sync per build (the build
    already syncs its row count)."""
    offsets, widths = [], []
    lo64 = np.iinfo(np.int64)
    for k in keys:
        mn = int(jnp.min(jnp.where(mask, k, jnp.int64(lo64.max))))
        mx = int(jnp.max(jnp.where(mask, k, jnp.int64(lo64.min))))
        if mx < mn:  # no live rows
            mn, mx = 0, 0
        offsets.append(mn)
        widths.append(max((mx - mn).bit_length(), 1))
    if sum(widths) > 62:
        return None
    shifts, acc = [], 0
    for w in reversed(widths):
        shifts.append(acc)
        acc += w
    shifts = list(reversed(shifts))
    return (jnp.asarray(offsets, dtype=jnp.int64),
            jnp.asarray(shifts, dtype=jnp.int64),
            jnp.asarray(widths, dtype=jnp.int64))


def _build_sorted(keys, payload, mask, n, payload_meta, unique) -> LookupSource:
    pack = _plan_packing(keys, mask) if len(keys) > 1 else None
    ck = _pack_key(tuple(keys), *pack) if pack is not None \
        else combined_key(keys)
    sorted_key, sorted_row = _sorted_kernel_ck(ck, mask)
    return LookupSource(kind="sorted", key_arrays=keys, payload=payload,
                        payload_meta=payload_meta,
                        build_count=jnp.asarray(n, jnp.int32), unique=unique,
                        sorted_key=sorted_key, sorted_row=sorted_row,
                        pack_offsets=pack[0] if pack else None,
                        pack_shifts=pack[1] if pack else None,
                        pack_widths=pack[2] if pack else None)


class JoinBuildOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, key_channels: List[int],
                 payload_channels: List[int],
                 payload_meta: List[Tuple[Type, Optional[Dictionary]]],
                 strategy: str = "sorted", unique: bool = False,
                 dense_min: int = 0, dense_max: int = 0,
                 track_unmatched: bool = False):
        super().__init__(operator_id, "JoinBuild")
        # FULL joins need the NULL-key build rows preserved for unmatched output
        self.track_unmatched = track_unmatched
        if strategy not in ("dense", "sorted", "pallas"):
            raise ValueError(
                f"unknown join build strategy {strategy!r}; the planner "
                "selects it from the `hash_kernels` session property "
                "(sorted | pallas | auto)")
        if strategy == "dense" and not unique:
            # the dense table stores ONE row index per key slot — a duplicate build
            # key would silently keep only the last row; refuse at plan time
            raise ValueError(
                "dense join strategy requires unique build keys; use "
                "strategy='sorted' (the `hash_kernels=sorted` session "
                "default) for duplicate-key builds")
        if strategy == "dense" and len(key_channels) != 1:
            raise ValueError(
                "dense join strategy requires a single key channel; the "
                "`hash_kernels` session property only routes single-key "
                "builds off the sorted path")
        if strategy == "pallas" and (not unique or len(key_channels) != 1
                                     or track_unmatched):
            # the open-addressing table stores one row per key slot and has
            # no sorted_row ordering for the FULL-join unmatched epilogue;
            # the planner (and `hash_kernels=auto`) must fall back to
            # 'sorted' for duplicate-key / multi-key / FULL builds rather
            # than construct this
            raise ValueError(
                "pallas join strategy requires a unique single-key "
                "INNER/LEFT build; set the `hash_kernels` session property "
                "to 'auto' (or 'sorted') so ineligible builds fall back to "
                "the sorted strategy instead of raising")
        self.key_channels = key_channels
        self.payload_channels = payload_channels
        self.payload_meta = payload_meta
        self.strategy = strategy
        self.unique = unique
        self.dense_min = dense_min
        self.dense_max = dense_max
        self.lookup_factory = LookupSourceFactory()
        self._builders_lock = threading.Lock()
        self._created = {}   # worker -> [JoinBuildOperator]
        self._finished = {}  # worker -> count

    def create_operator(self, worker: int = 0) -> JoinBuildOperator:
        op = JoinBuildOperator(self.context(worker), self)
        with self._builders_lock:
            self._created.setdefault(worker, []).append(op)
        return op

    def _builder_done(self, op: JoinBuildOperator) -> None:
        """Called by each build driver's finish(). The last finisher for the
        worker merges every sibling's collected pages into its own state and
        publishes the lookup source (drivers are all created before execution
        starts, so the expected count is final before any finish)."""
        w = op.context.worker
        with self._builders_lock:
            self._finished[w] = self._finished.get(w, 0) + 1
            if self._finished[w] < len(self._created[w]):
                return
            siblings = [o for o in self._created[w] if o is not op]
        for o in siblings:
            op._pages.extend(o._pages)
            op._host_pages.extend(o._host_pages)
            op._disk_runs.extend(o._disk_runs)
            op._null_key_pages.extend(o._null_key_pages)
            if o._saw_null_key is not None:
                op._saw_null_key = o._saw_null_key \
                    if op._saw_null_key is None \
                    else (op._saw_null_key | o._saw_null_key)
            o._pages, o._host_pages, o._null_key_pages = [], [], []
            o._disk_runs = []
        self.lookup_factory.set(op._build(), w)
        op._pages = []  # consumed into the lookup source


# ---------------------------------------------------------------------------
# probe stage (pure): the page-local fast paths as ONE composable function
# ---------------------------------------------------------------------------
#
# The unique-build INNER/LEFT probe and the exact-key SEMI/ANTI probe are
# page-local (one output page per probe page, no host sync), so they can run
# as a single fused kernel — standalone (the operator below jits exactly this
# function) or inlined into a pipeline segment (ops/fused_segment.py). The
# lookup-source arrays arrive as jit ARGUMENTS, never trace constants, so a
# rebuilt build side (new query, same shapes) replays the compiled kernel.

@dataclasses.dataclass(frozen=True)
class ProbeStageConfig:
    """Static (hashable) config of a page-local probe stage. Everything the
    traced function branches on lives here; everything data lives in the aux
    pytree from :func:`probe_stage_aux`."""

    kind: str                              # "dense" | "sorted" | "pallas"
    join_type: str                         # INNER | LEFT | SEMI | ANTI
    probe_key_channels: Tuple[int, ...]
    probe_output_channels: Tuple[int, ...]
    build_output_channels: Tuple[int, ...]
    payload_meta: Tuple                    # ((type, dict), ...) per SELECTED build col
    null_aware: bool = False
    # pallas probes unroll a FIXED trip count (ops/pallas_hash.py): the
    # bound is static kernel config, so it lives here, not in the aux
    pallas_trips: int = 0


def probe_plan_fusible(join_type: str, key_channels, unique: bool,
                       filter_fn=None, semi_output_channel=None) -> bool:
    """Plan-time test: will every page of this probe take the page-local
    stage path? INNER/LEFT need a unique single-key build (one output row
    per probe row); SEMI/ANTI need exact keys (single key) and no join
    filter. FULL joins track visited build rows across pages and RIGHT is
    planner-flipped — neither is page-local."""
    if len(key_channels) != 1:
        return False  # multi-key exactness is a runtime (packing) property
    if join_type in (SEMI, ANTI):
        return filter_fn is None and semi_output_channel is None
    if join_type in (INNER, LEFT):
        return unique
    return False


def pallas_join_eligible(join_type: str, key_channels, unique: bool) -> bool:
    """Plan-time test shared by the local planner and the differential
    tests: may this join's build use the Pallas open-addressing strategy?
    Unique single-key INNER/LEFT only — duplicate-key, multi-key, FULL and
    semi builds keep the sorted strategy (the `hash_kernels=auto` fallback
    contract: ineligible shapes NEVER raise, they fall back)."""
    return (unique and len(key_channels) == 1
            and join_type in (INNER, LEFT))


def probe_stage_cfg(f: "LookupJoinOperatorFactory",
                    src: LookupSource) -> ProbeStageConfig:
    return ProbeStageConfig(
        kind=src.kind, join_type=f.join_type,
        probe_key_channels=tuple(f.probe_key_channels),
        probe_output_channels=tuple(f.probe_output_channels),
        build_output_channels=tuple(f.build_output_channels),
        payload_meta=tuple(_payload_meta_selected(src, f)),
        null_aware=f.null_aware,
        pallas_trips=src.ph_trips)


def probe_stage_aux(src: LookupSource):
    """Traced pytree of everything the stage reads from the build side.
    Host scalars stay numpy (an eager jnp.asarray would compile a throwaway
    convert kernel per query); they device_put at the jit call."""
    if src.kind == "dense":
        match = (src.table, np.asarray(src.base, np.int64))
    elif src.kind == "pallas":
        match = (src.ph_keys, src.ph_rows)
    else:
        match = (src.sorted_key, src.sorted_row, tuple(src.key_arrays))
    return (match, tuple(src.payload), tuple(src.payload_nulls),
            np.asarray(src.has_null_key))


def probe_stage_key(cfg: ProbeStageConfig) -> tuple:
    """Global kernel-cache identity (dictionary versions included: payload
    meta dictionaries ride into output blocks as static aux data)."""
    from ..utils import kernel_cache as kc

    return ("probe-stage", cfg.kind, cfg.join_type, cfg.probe_key_channels,
            cfg.probe_output_channels, cfg.build_output_channels,
            tuple((t.name, kc.dict_key(d)) for t, d in cfg.payload_meta),
            cfg.null_aware, cfg.pallas_trips)


def apply_probe_stage(page: Page, aux, cfg: ProbeStageConfig) -> Page:
    """Pure page -> page probe: match rows then emit, in one traceable body.

    Semantics identical to the operator's _match_rows + _emit_unique pair
    (the differential-tested contract): null probe keys never match; SEMI
    keeps matches, ANTI keeps non-matches (null-aware NOT IN empties the
    result under any NULL build key, via the has_null_key aux scalar); LEFT
    emits null build columns for unmatched probe rows."""
    match, payload, payload_nulls, has_null_key = aux
    probe_keys = [page.blocks[c].data for c in cfg.probe_key_channels]
    probe_mask = page.mask
    for c in cfg.probe_key_channels:
        if page.blocks[c].nulls is not None:
            probe_mask = probe_mask & ~page.blocks[c].nulls
    if cfg.kind == "dense":
        table, base = match
        row = probe_match_dense(table, base, probe_keys[0], probe_mask)
    elif cfg.kind == "pallas":
        ph_keys, ph_rows = match
        row = probe_match_pallas(ph_keys, ph_rows, probe_keys[0], probe_mask,
                                 cfg.pallas_trips)
    else:
        sorted_key, sorted_row, key_arrays = match
        row = probe_match_sorted(sorted_key, sorted_row,
                                 combined_key(tuple(probe_keys)),
                                 tuple(probe_keys), probe_mask, key_arrays)
    matched = row >= 0
    if cfg.join_type in (SEMI, ANTI):
        if cfg.join_type == SEMI:
            keep = page.mask & matched
        else:
            keep = page.mask & ~matched
            if cfg.null_aware:
                # NOT IN: NULL probe key -> UNKNOWN -> filtered; any NULL
                # build key makes every non-match UNKNOWN -> empty result
                keep = keep & probe_mask & ~has_null_key
        sel = page.select_channels(list(cfg.probe_output_channels))
        return Page(sel.blocks, keep)
    return unique_join_page(page, row, payload, payload_nulls,
                            cfg.probe_output_channels,
                            cfg.build_output_channels, cfg.payload_meta,
                            cfg.join_type == INNER,
                            cfg.join_type in (LEFT, FULL))


def probe_stage_kernel(cfg: ProbeStageConfig):
    """Jitted stage shared through the global kernel cache: identical-config
    probes across operators, workers and queries replay one compile (the
    hash_agg share_kernels pattern, generalized to the join probe)."""
    from ..utils import kernel_cache as kc

    return kc.get_or_install(
        probe_stage_key(cfg),
        lambda: jax.jit(apply_probe_stage, static_argnames=("cfg",)))


# ---------------------------------------------------------------------------
# probe
# ---------------------------------------------------------------------------

def probe_match_dense(source_table, base, probe_keys, probe_mask):
    """DENSE unique build: one gather -> build row per probe row (-1 = no
    match). Pure body — the standalone kernel below and the fused stage
    both call it."""
    domain = source_table.shape[0]
    idx = (probe_keys.astype(jnp.int64) - base).astype(jnp.int32)
    in_range = (idx >= 0) & (idx < domain) & probe_mask
    idx = jnp.where(in_range, idx, 0)
    row = jnp.where(in_range, source_table[idx], jnp.int32(-1))
    return row


_probe_match_unique = jax.jit(probe_match_dense)


def probe_match_pallas(ph_keys, ph_rows, probe_keys, probe_mask, trips: int):
    """PALLAS unique build: fixed-trip open-addressing scan (one Pallas
    kernel dispatch; ops/pallas_hash.py). Pure body — the standalone kernel
    and the fused stage both call it; `trips` is static config."""
    from .pallas_hash import probe_table

    return probe_table(ph_keys, ph_rows, probe_keys.astype(jnp.int64),
                       probe_mask, trips)


_probe_match_pallas = functools.partial(
    jax.jit, static_argnames=("trips",))(probe_match_pallas)


def probe_match_sorted(sorted_key, sorted_row, ck, probe_keys_list,
                       probe_mask, key_arrays):
    """SORTED unique build: binary search + verify (ck = the build's
    combined-key space, packed when exact). Pure body shared by the
    standalone kernel and the fused stage."""
    pos = jnp.searchsorted(sorted_key, ck)
    pos = jnp.clip(pos, 0, sorted_key.shape[0] - 1)
    hit = (sorted_key[pos] == ck) & probe_mask
    row = jnp.where(hit, sorted_row[pos], jnp.int32(-1))
    # verify true keys (hash collisions on multi-key mixes)
    for pk, bk in zip(probe_keys_list, key_arrays):
        bv = bk[jnp.where(row >= 0, row, 0)]
        row = jnp.where((row >= 0) & (bv == pk), row, jnp.int32(-1))
    return row


_probe_match_sorted_unique = jax.jit(probe_match_sorted)


class LookupJoinOperator(Operator):
    """Probe side. Unique-build fast path: one output row per probe row, no sync.
    General path: count-then-emit expansion with one scalar sync per probe page."""

    def __init__(self, context: OperatorContext, factory: "LookupJoinOperatorFactory"):
        super().__init__(context)
        self.f = factory
        self._outputs: List[Page] = []
        self._source: Optional[LookupSource] = None
        self._visited = None  # FULL: device bool per build row, OR-accumulated
        self._unmatched_emitted = False
        # page-local stage path (one fused kernel per page, shared via the
        # global kernel cache): resolved lazily from the live lookup source
        self._stage_cfg: Optional[ProbeStageConfig] = None
        self._stage_aux = None

    @property
    def output_types(self) -> List[Type]:
        return self.f.output_types

    def is_blocked(self):
        if self._source is not None:
            return None
        lf = self.f.lookup_factory
        w = self.context.worker
        if lf.done(w):
            self._source = lf.get(w)
            return None
        return lambda: lf.done(w)

    def needs_input(self) -> bool:
        return (not self._finishing and self._source is not None
                and len(self._outputs) < 4)

    @timed("add_input_ns")
    def add_input(self, page: Page) -> None:
        self.context.record_input(page, page.capacity)
        if self._source is None:
            w = self.context.worker
            assert self.f.lookup_factory.done(w), \
                "probe received input before build finished"
            self._source = self.f.lookup_factory.get(w)
        src = self._source
        probe_keys = [page.blocks[c].data for c in self.f.probe_key_channels]
        probe_mask = page.mask
        for c in self.f.probe_key_channels:
            if page.blocks[c].nulls is not None:
                probe_mask = probe_mask & ~page.blocks[c].nulls
        if self.f.join_type == RIGHT:
            raise NotImplementedError(
                "RIGHT joins are planned as flipped LEFT; the planner must not "
                "route them here")
        if self.f.join_type == FULL and self._visited is None:
            self._visited = jnp.zeros(src.key_arrays[0].shape[0],
                                      dtype=jnp.bool_)
        # unique fast path requires exact key equality through sorted_key/dense table;
        # multi-key hashes must range-scan + verify via the expansion path
        if self.f.join_type in (SEMI, ANTI):
            if self.f.filter_fn is None and src.exact_keys:
                if self._stage_eligible(src):
                    self._push(self._stage_call(src, page))
                else:
                    row = self._match_rows(src, probe_keys, probe_mask)
                    self._emit_unique(page, row, probe_mask)
            else:
                self._emit_semi_expanded(page, probe_keys, probe_mask)
        elif src.unique and (src.kind == "dense" or src.exact_keys):
            if self._stage_eligible(src):
                self._push(self._stage_call(src, page))
            else:
                row = self._match_rows(src, probe_keys, probe_mask)
                self._emit_unique(page, row, probe_mask)
        else:
            self._emit_expanded(page, probe_keys, probe_mask)

    def _stage_eligible(self, src: LookupSource) -> bool:
        """One-kernel page-local path — THE plan-time fusion predicate,
        evaluated against the live build, so the fused and standalone paths
        can never drift apart. exact_keys is the extra RUNTIME condition:
        a float single-key build (sorted-key equality != key equality) must
        take the range-scan + verify expansion path instead of trusting
        the stage's single-position probe."""
        return probe_plan_fusible(self.f.join_type,
                                  self.f.probe_key_channels, src.unique,
                                  self.f.filter_fn,
                                  self.f.semi_output_channel) \
            and src.exact_keys

    def _stage_call(self, src: LookupSource, page: Page) -> Page:
        if self._stage_cfg is None:
            self._stage_cfg = probe_stage_cfg(self.f, src)
            self._stage_aux = probe_stage_aux(src)
            self._stage_kernel = probe_stage_kernel(self._stage_cfg)
        return self._stage_kernel(page, self._stage_aux, cfg=self._stage_cfg)

    def _match_rows(self, src, probe_keys, probe_mask):
        if src.kind == "dense":
            return _probe_match_unique(src.table, src.base, probe_keys[0], probe_mask)
        if src.kind == "pallas":
            return _probe_match_pallas(src.ph_keys, src.ph_rows,
                                       probe_keys[0], probe_mask,
                                       trips=src.ph_trips)
        return _probe_match_sorted_unique(src.sorted_key, src.sorted_row,
                                          src.combine_probe(tuple(probe_keys)),
                                          tuple(probe_keys), probe_mask,
                                          src.key_arrays)

    def _emit_semi_expanded(self, page: Page, probe_keys, probe_mask) -> None:
        """SEMI/ANTI with a join filter or multi-key: range-scan every candidate
        match, verify true keys, evaluate the filter on the (probe,build) pair, and
        OR-reduce per probe row. The SemiJoinOperator-with-filter analogue
        (reference: LookupJoinOperator + JoinFilterFunctionCompiler)."""
        src = self._source
        ck = src.combine_probe(tuple(probe_keys))
        lo, emit, _match, total_dev = _range_kernel(
            src.sorted_key, ck, probe_mask, page.mask, False)
        total = int(total_dev)
        cap = page.capacity
        offsets = jnp.cumsum(emit)
        any_match = jnp.zeros(cap, dtype=jnp.bool_)
        if self.f._semi_kernel is None:
            # jitted once per filter CONFIG (a detached holder: the cached
            # closure must pin only the compiled filter, never the factory and
            # its lookup sources/build tables), shared by every worker's probe
            # operators — and across queries when the planner supplied a
            # filter fingerprint
            f = self.f
            cfg = _SemiFilterKernel(f.filter_fn, f.filter_probe_channels,
                                    f.filter_build_channels)
            if f.filter_key is not None:
                from ..utils import kernel_cache as kc

                self.f._semi_kernel = kc.get_or_install(
                    ("join-semi", f.filter_key,
                     tuple(f.filter_probe_channels),
                     tuple(f.filter_build_channels)),
                    lambda: jax.jit(cfg.chunk))
            else:
                # no planner fingerprint for the ad-hoc filter fn: a
                # per-factory compile IS the contract here (the kernel is
                # memoized on the factory and reused across its chunks)
                self.f._semi_kernel = jax.jit(cfg.chunk)  # prestocheck: ignore[cache-key-hygiene]
        for c in range(max(0, -(-total // cap))):
            any_match = self.f._semi_kernel(
                page, tuple(probe_keys), lo, offsets, src.sorted_row,
                tuple(src.key_arrays), tuple(src.payload),
                tuple(src.payload_nulls), jnp.asarray(c * cap),
                jnp.asarray(total), any_match)
        if self.f.join_type == SEMI:
            keep = page.mask & any_match
        else:
            keep = page.mask & ~any_match
            if self.f.null_aware:
                keep = keep & probe_mask
                if src.has_null_key:
                    keep = jnp.zeros_like(keep)
        sel = page.select_channels(self.f.probe_output_channels)
        self._push(Page(sel.blocks, keep))

    def _emit_unique(self, page: Page, row, probe_mask) -> None:
        src = self._source
        jt = self.f.join_type
        matched = row >= 0
        if jt == FULL:
            self._visited = _mark_rows(self._visited, row, page.mask)
        if jt == SEMI or jt == ANTI:
            if self.f.semi_output_channel is not None:
                # mark column output (SemiJoinOperator semantics): keep all rows,
                # append the membership flag after the selected probe channels
                from ..types import BOOLEAN
                sel = page.select_channels(self.f.probe_output_channels)
                blocks = list(sel.blocks) + [Block(BOOLEAN, matched)]
                self._push(Page(tuple(blocks), page.mask))
            else:
                if jt == SEMI:
                    keep = matched
                else:
                    keep = ~matched & page.mask
                    if self.f.null_aware:
                        # NOT IN: NULL probe key -> UNKNOWN -> filtered; any NULL
                        # build key makes every non-match UNKNOWN -> empty result
                        keep = keep & probe_mask
                        if src.has_null_key:
                            keep = jnp.zeros_like(keep)
                sel = page.select_channels(self.f.probe_output_channels)
                self._push(Page(sel.blocks, page.mask & keep))
            return
        self._push(_emit_unique_kernel(
            page, row, tuple(src.payload), tuple(src.payload_nulls),
            tuple(self.f.probe_output_channels),
            tuple(self.f.build_output_channels),
            tuple(_payload_meta_selected(src, self.f)),
            jt == INNER, jt in (LEFT, FULL)))

    def _emit_expanded(self, page: Page, probe_keys, probe_mask) -> None:
        src = self._source
        jt = self.f.join_type
        if jt not in (INNER, LEFT, FULL):
            raise NotImplementedError(f"{jt} join via expansion")
        left = jt in (LEFT, FULL)
        if left and not src.exact_keys:
            # a mixed-hash collision would mask a probe row's only match slots and
            # silently drop the row; LEFT semantics need exact combined keys
            raise NotImplementedError(
                "multi-key LEFT join on a non-unique build needs exact-key "
                "verification with null-row fallback (single-key LEFT is exact)")
        ck = src.combine_probe(tuple(probe_keys))
        lo, emit, match_counts, total = _range_kernel(
            src.sorted_key, ck, probe_mask, page.mask, left)
        if jt == FULL:
            # exact single-key ranges (guaranteed above): every build row in a
            # live probe row's [lo, lo+match) range is a true match
            self._visited = _mark_ranges(self._visited, src.sorted_row, lo,
                                         lo + match_counts,
                                         probe_mask & page.mask)
        total = int(total)  # host sync: output cardinality for this page
        cap = page.capacity
        n_chunks = max(1, -(-total // cap)) if total > 0 else 0
        offsets = jnp.cumsum(emit)
        for c in range(n_chunks):
            out = _expand_kernel(page, tuple(probe_keys), lo, offsets,
                                 match_counts, src.sorted_row,
                                 tuple(src.key_arrays), tuple(src.payload),
                                 tuple(src.payload_nulls),
                                 tuple(self.f.probe_output_channels),
                                 tuple(self.f.build_output_channels),
                                 c * cap, total, left,
                                 tuple((t, d) for (t, d) in
                                       _payload_meta_selected(src, self.f)))
            self._push(out)

    def _push(self, page: Page) -> None:
        self._outputs.append(page)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._outputs:
            out = self._outputs.pop(0)
            self.context.record_output(out, out.capacity)
            return out
        return None

    def finish(self) -> None:
        if self.f.join_type == FULL and not self._unmatched_emitted:
            self._unmatched_emitted = True
            self._emit_unmatched_build()
        super().finish()

    def _emit_unmatched_build(self) -> None:
        """FULL join epilogue: build rows no probe row visited (plus NULL-key
        build rows, unmatched by construction) emit with null probe columns."""
        lf = self.f.lookup_factory
        w = self.context.worker
        if self._source is None:
            if not lf.done(w):
                return  # no probe input ever arrived and build never finished
            self._source = lf.get(w)
        src = self._source
        total_build = int(src.build_count)
        rows = np.zeros(0, dtype=np.int64)
        if total_build > 0:
            # live build rows are NOT a prefix of the concatenated page arrays
            # (pages are capacity-padded); the sort kernel puts the n live rows
            # first in sorted order, so sorted_row[:n] IS the live-row index set
            live = np.asarray(src.sorted_row)[:total_build]
            if self._visited is not None:
                vis = np.asarray(self._visited)
                rows = live[~vis[live]]
            else:
                rows = live
        n_un = len(rows)
        n_null = src.null_key_count
        if n_un + n_null == 0:
            return
        cap = max(1 << 10, 1 << (n_un + n_null - 1).bit_length()) \
            if n_un + n_null else 1 << 10
        cap = min(cap, 1 << 16)
        payload_np = [np.asarray(a) for a in src.payload]
        nulls_np = [np.asarray(x) if x is not None else None
                    for x in src.payload_nulls]
        # assemble [unvisited live rows] + [null-key side buffer] per column
        cols = []
        for bi, (t, d) in zip(self.f.build_output_channels,
                              _payload_meta_selected(src, self.f)):
            parts = [payload_np[bi][rows]] if n_un else []
            nparts = []
            bn = nulls_np[bi] if bi < len(nulls_np) else None
            nparts.append((bn[rows] if bn is not None else
                           np.zeros(n_un, dtype=bool)) if n_un else
                          np.zeros(0, dtype=bool))
            if n_null:
                parts.append(src.null_key_payload[bi])
                nk_n = src.null_key_nulls[bi]
                nparts.append(nk_n if nk_n is not None
                              else np.zeros(n_null, dtype=bool))
            data = np.concatenate(parts) if parts else np.zeros(0)
            nul = np.concatenate(nparts)
            cols.append((t, d, data, nul))
        total = n_un + n_null
        for lo in range(0, total, cap):
            hi = min(lo + cap, total)
            pad = cap - (hi - lo)
            blocks = []
            # probe columns: all NULL
            for (t, d) in self.f.probe_output_meta:
                z = np.zeros(cap, dtype=t.np_dtype)
                blocks.append(Block(t, z, np.ones(cap, dtype=bool), d))
            for (t, d, data, nul) in cols:
                seg = np.concatenate([data[lo:hi],
                                      np.zeros(pad, dtype=data.dtype)]) \
                    if pad else data[lo:hi]
                nseg = np.concatenate([nul[lo:hi], np.zeros(pad, dtype=bool)]) \
                    if pad else nul[lo:hi]
                blocks.append(Block(t, seg.astype(t.np_dtype, copy=False),
                                    nseg if nseg.any() else None, d))
            mask = np.arange(cap) < (hi - lo)
            self._push(Page(tuple(blocks), mask))

    def is_finished(self) -> bool:
        return self._finishing and not self._outputs


def _payload_meta_selected(src: LookupSource, f) -> List[Tuple[Type, Optional[Dictionary]]]:
    return [src.payload_meta[i] for i in f.build_output_channels]


def unique_join_page(page: Page, row, payload, payload_nulls,
                     probe_channels, build_channels, meta,
                     inner: bool, left_outer: bool) -> Page:
    """Unique-build join output: probe-channel passthrough plus a gather per
    build column. Pure body — the standalone kernel below runs it as ONE
    fused dispatch (eagerly this was ~15 separate dispatches per page);
    the fused segment inlines it into its whole-chain kernel."""
    matched = row >= 0
    out_mask = page.mask & (matched if inner else jnp.ones_like(matched))
    safe_row = jnp.where(matched, row, 0)
    blocks = [page.blocks[c] for c in probe_channels]
    for bi, (t, d) in zip(build_channels, meta):
        arr = payload[bi][safe_row]
        bn = payload_nulls[bi] if bi < len(payload_nulls) else None
        nulls = bn[safe_row] if bn is not None else None
        if left_outer:
            unmatched = ~matched  # unmatched probe rows -> null build columns
            nulls = unmatched if nulls is None else (nulls | unmatched)
        blocks.append(Block(t, arr, nulls, d))
    return Page(tuple(blocks), out_mask)


_emit_unique_kernel = functools.partial(
    jax.jit, static_argnames=("probe_channels", "build_channels", "meta",
                              "inner", "left_outer"))(unique_join_page)


@jax.jit
def _mark_rows(visited, row, mask):
    """OR build rows matched by this probe page into the visited set."""
    idx = jnp.where((row >= 0) & mask, row, visited.shape[0])
    return visited.at[idx].set(True, mode="drop")


@jax.jit
def _mark_ranges(visited, sorted_row, lo, hi, probe_mask):
    """Visited-marking for range matches: coverage via a difference array —
    O(n) regardless of match multiplicity."""
    n = sorted_row.shape[0]
    add = jnp.where(probe_mask, 1, 0).astype(jnp.int32)
    delta = jnp.zeros(n + 1, dtype=jnp.int32)
    delta = delta.at[jnp.where(probe_mask, lo, n)].add(add, mode="drop")
    delta = delta.at[jnp.where(probe_mask, hi, n)].add(-add, mode="drop")
    covered = jnp.cumsum(delta[:-1]) > 0
    return visited.at[sorted_row].max(covered)


@functools.partial(jax.jit, static_argnames=("left",))
def _range_kernel(sorted_key, probe_ck, probe_mask, emit_mask, left=False):
    """Match ranges per probe row. Returns (lo, emit_counts, match_counts, total).
    LEFT joins emit one row for match-less live probe rows (null build side)."""
    lo = jnp.searchsorted(sorted_key, probe_ck, side="left")
    hi = jnp.searchsorted(sorted_key, probe_ck, side="right")
    lo = jnp.where(probe_mask, lo, 0)
    hi = jnp.where(probe_mask, hi, 0)
    match = (hi - lo).astype(jnp.int32)
    if left:
        emit = jnp.where(emit_mask, jnp.maximum(match, 1), 0).astype(jnp.int32)
    else:
        emit = match
    return lo.astype(jnp.int32), emit, match, jnp.sum(emit)


@functools.partial(jax.jit, static_argnames=("probe_channels", "build_channels",
                                             "left", "payload_meta"))
def _expand_kernel(page: Page, probe_keys, lo, offsets, match_counts, sorted_row,
                   key_arrays, payload, payload_nulls, probe_channels,
                   build_channels, out_base, total, left, payload_meta):
    """Emit output rows [out_base, out_base+cap) of the expanded join. For LEFT,
    an emit slot beyond a probe row's match count is its null-build row."""
    cap = page.mask.shape[0]
    j = jnp.arange(cap, dtype=jnp.int32) + out_base
    live = j < total
    # probe row for output slot j: first i with offsets[i] > j
    pi = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    pi = jnp.clip(pi, 0, cap - 1)
    prev = jnp.where(pi > 0, offsets[jnp.maximum(pi - 1, 0)], 0)
    k = j - prev
    is_match = k < match_counts[pi]
    spos = lo[pi] + k
    spos = jnp.clip(spos, 0, sorted_row.shape[0] - 1)
    brow = jnp.where(is_match, sorted_row[spos], 0)
    # verify true keys (collision safety on multi-key mixes)
    ok = live
    for pkc, bk in zip(range(len(probe_keys)), key_arrays):
        pv = probe_keys[pkc][pi]
        bv = bk[brow]
        ok = ok & (~is_match | (bv == pv)) if left else ok & (bv == pv)
    blocks = []
    for c in probe_channels:
        b = page.blocks[c]
        nulls = b.nulls[pi] if b.nulls is not None else None
        blocks.append(Block(b.type, b.data[pi], nulls, b.dictionary))
    for bi, (t, d) in zip(build_channels, payload_meta):
        bn = payload_nulls[bi] if bi < len(payload_nulls) else None
        nulls = bn[brow] if bn is not None else None
        if left:
            nulls = ~is_match if nulls is None else (nulls | ~is_match)
        blocks.append(Block(t, payload[bi][brow], nulls, d))
    return Page(tuple(blocks), ok)


class LookupJoinOperatorFactory(OperatorFactory):
    def __init__(self, operator_id: int, lookup_factory: LookupSourceFactory,
                 probe_key_channels: List[int], probe_output_channels: List[int],
                 probe_output_meta: List[Tuple[Type, Optional[Dictionary]]],
                 build_output_channels: List[int],
                 build_output_meta: List[Tuple[Type, Optional[Dictionary]]],
                 join_type: str = INNER, semi_output_channel: Optional[int] = None,
                 null_aware: bool = False, filter_fn=None,
                 filter_probe_channels: Optional[List[int]] = None,
                 filter_build_channels: Optional[List[int]] = None,
                 filter_key: Optional[tuple] = None,
                 unique_build: bool = False):
        super().__init__(operator_id, f"LookupJoin({join_type})")
        # plan-time build-side uniqueness claim (JoinBuildOperatorFactory's
        # `unique`): the segment compiler fuses INNER/LEFT probes only when
        # the build guarantees one output row per probe row
        self.unique_build = unique_build
        # global kernel-cache identity of the compiled join filter (expression
        # + layout fingerprint from the local planner); None -> per-factory jit
        self.filter_key = filter_key
        # join filter: compiled expression over [filter_probe_channels... page
        # channels, filter_build_channels... payload columns] evaluated per
        # candidate (probe,build) pair — JoinFilterFunctionCompiler analogue
        self.filter_fn = filter_fn
        self.filter_probe_channels = filter_probe_channels or []
        self.filter_build_channels = filter_build_channels or []
        self._semi_kernel = None  # lazily jitted, shared across workers
        self.lookup_factory = lookup_factory
        self.probe_key_channels = probe_key_channels
        self.probe_output_channels = probe_output_channels
        self.probe_output_meta = list(probe_output_meta)
        self.build_output_meta = list(build_output_meta)
        self.build_output_channels = build_output_channels
        self.join_type = join_type
        self.semi_output_channel = semi_output_channel
        # null_aware = SQL IN/NOT IN semantics: a NULL probe key (or any NULL build
        # key on NOT IN) compares UNKNOWN, so the row is filtered. Default False =
        # EXISTS/NOT EXISTS semantics where a null key simply never matches.
        self.null_aware = null_aware
        self.output_types = [t for (t, _) in probe_output_meta] + \
                            [t for (t, _) in build_output_meta]
        if semi_output_channel is not None:
            from ..types import BOOLEAN
            # mark-column mode appends the membership flag as the LAST channel
            self.output_types = [t for (t, _) in probe_output_meta] + [BOOLEAN]

    def create_operator(self, worker: int = 0) -> LookupJoinOperator:
        return LookupJoinOperator(self.context(worker), self)


class _SemiFilterKernel:
    """Join-filter config holder for the cached semi/anti probe kernel.

    Deliberately detached from the operator factory: the kernel cache keeps
    the jitted bound method alive for the process lifetime, and a factory
    would drag its LookupSourceFactory (the build-side hash tables in HBM)
    along with it."""

    def __init__(self, filter_fn, filter_probe_channels, filter_build_channels):
        self.filter_fn = filter_fn
        self.filter_probe_channels = list(filter_probe_channels)
        self.filter_build_channels = list(filter_build_channels)

    def chunk(self, page, probe_keys, lo, offsets, sorted_row, key_arrays,
              payload, payload_nulls, out_base, total, any_match):
        """One output chunk of the verified semi/anti probe: range-positions
        -> candidate build rows -> exact key check -> filter -> OR per probe."""
        cap = page.mask.shape[0]
        j = jnp.arange(cap, dtype=jnp.int32) + out_base
        live = j < total
        pi = jnp.clip(jnp.searchsorted(offsets, j, side="right").astype(jnp.int32),
                      0, cap - 1)
        prev = jnp.where(pi > 0, offsets[jnp.maximum(pi - 1, 0)], 0)
        spos = jnp.clip(lo[pi] + (j - prev), 0, sorted_row.shape[0] - 1)
        brow = sorted_row[spos]
        ok = live
        for pk, bk in zip(probe_keys, key_arrays):
            ok = ok & (bk[brow] == pk[pi])
        if self.filter_fn is not None:
            datas, nulls = [], []
            for pc in self.filter_probe_channels:
                b = page.blocks[pc]
                datas.append(b.data[pi])
                nulls.append(b.nulls[pi] if b.nulls is not None else None)
            for bc in self.filter_build_channels:
                datas.append(payload[bc][brow])
                bn = payload_nulls[bc] if bc < len(payload_nulls) else None
                nulls.append(bn[brow] if bn is not None else None)
            fd, fnu = self.filter_fn(tuple(datas), tuple(nulls))
            ok = ok & fd
            if fnu is not None:
                ok = ok & ~fnu
        return any_match.at[pi].max(ok)
