"""Fused pipeline segments: one device dispatch per page through a chain of
page-local operators.

The driver (exec/driver.py, the Driver.processInternal analogue) moves each
page through N separate jitted dispatches with a host round-trip at every
operator boundary. For chains of PAGE-LOCAL operators — filter/project, the
unique/exact join probe, the per-page partial of a hash aggregation, a TopN
buffer merge — those boundaries are pure overhead: every stage is a pure
``page -> page`` (or ``page -> contribution``) function, so the whole chain
can trace into ONE jitted kernel. XLA then fuses across the old operator
boundaries (a join's gathered payload column feeding only a SUM never
materializes), and per-page host work drops to a single dispatch. This is
the per-operator kernel-launch fusion "Accelerating Presto with GPUs"
(PAPERS.md) identifies as the first structural win, applied to the engine's
jitted-operator design.

Shape of the thing:

- The segment compiler (exec/local_planner.LocalExecutionPlanner, knob
  ``segment_fusion``) groups maximal runs of fusible operator factories into
  one :class:`FusedSegmentOperatorFactory`. Mid stages are
  ``FilterProjectOperatorFactory`` (PageProcessor._process) and plan-time
  page-local ``LookupJoinOperatorFactory`` probes
  (hash_join.apply_probe_stage); an optional TERMINAL stage absorbs a
  ``HashAggregationOperatorFactory`` (the builder's per-page partial) or a
  ``TopNOperatorFactory`` (the buffer merge). Blocking operators, join
  builds, exchanges, sorts and expansion-path probes are fusion barriers.
- Join lookup-source arrays and aggregation/TopN accumulator state thread
  through the fused function as JIT ARGUMENTS, never trace constants — a
  rebuilt build side or a growing accumulator replays the compiled kernel.
- Compiled segments live in the global ``utils/kernel_cache`` keyed on every
  stage's config fingerprint plus the input layout's dictionary versions
  (the hash_agg ``share_kernels`` pattern, generalized): workers, drivers
  and repeated queries share one compile per distinct segment.
- The unfused path (``segment_fusion = False``) keeps the exact per-operator
  pipeline and serves as the differential-testing oracle
  (tests/test_fused_segment.py asserts row-identical output).

Per-segment dispatch and compile counts surface in
``QueryResult.stats["segments"]`` and as ``segments.*`` counters on
``/v1/metrics``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

import jax

from ..block import Page
from ..utils import kernel_cache as kc
from ..utils import trace
from ..utils.metrics import METRICS
from .filter_project import FilterProjectOperatorFactory
from .hash_agg import (DirectAggregationBuilder, GlobalAggregationBuilder,
                       GroupedAggregationBuilder,
                       HashAggregationOperatorFactory, _builder_key)
from .hash_join import (LookupJoinOperatorFactory, apply_probe_stage,
                        probe_plan_fusible, probe_stage_aux, probe_stage_cfg,
                        probe_stage_key)
from .operator import Operator, OperatorContext, OperatorFactory, timed
from .topn import TopNOperatorFactory, topn_merge_stage


def mid_stage_fusible(f) -> bool:
    """Plan-time: can `f` run as a page-local mid stage of a segment?"""
    if isinstance(f, FilterProjectOperatorFactory):
        return True
    if isinstance(f, LookupJoinOperatorFactory):
        return probe_plan_fusible(f.join_type, f.probe_key_channels,
                                  f.unique_build, f.filter_fn,
                                  f.semi_output_channel)
    return False


def terminal_stage_fusible(f) -> bool:
    """Plan-time: can `f` terminate a segment (per-page contribution)?"""
    if isinstance(f, HashAggregationOperatorFactory):
        from .collect_agg import COLLECT_NAMES
        # collect builders keep raw rows (no per-page partial); ragged
        # handles cannot thread through the fused kernel
        return not any(c.function.name in COLLECT_NAMES for c in f.calls)
    return isinstance(f, TopNOperatorFactory)


class FusedSegmentOperatorFactory(OperatorFactory):
    """One factory per fused segment; holds the member factories in chain
    order plus the segment-level dispatch/compile counters the runner rolls
    into ``QueryResult.stats["segments"]``."""

    def __init__(self, operator_id: int, mid_factories: List,
                 terminal_factory=None,
                 output_types: Optional[List] = None,
                 output_dicts: Optional[List] = None):
        members = list(mid_factories) + (
            [terminal_factory] if terminal_factory is not None else [])
        names = "+".join(m.name for m in members)
        super().__init__(operator_id, f"FusedSegment[{names}]")
        self.mid_factories = list(mid_factories)
        self.terminal_factory = terminal_factory
        self.member_names = [m.name for m in members]
        self.output_types = list(output_types or [])
        self.output_dicts = list(output_dicts or [])
        self._lock = threading.Lock()
        self.pages = 0      # fused dispatches (one per input page)
        self.compiles = 0   # kernel-cache misses this factory triggered

    def create_operator(self, worker: int = 0) -> "FusedSegmentOperator":
        tf = self.terminal_factory
        if tf is not None:
            # forward the query's memory wiring: the terminal's builder is
            # the segment's only revocable state
            tf.memory_ctx = self.memory_ctx
            tf.revoke_check = self.revoke_check
            tf.spill_manager = self.spill_manager
        return FusedSegmentOperator(self.context(worker), self, worker)

    def note_pages(self, n: int) -> None:
        with self._lock:
            self.pages += n
        METRICS.count_many({"dispatches": n}, prefix="segments.")

    def note_compile(self) -> None:
        with self._lock:
            self.compiles += 1
        METRICS.count("segments.compiles")
        trace.instant(trace.SEGMENT, f"compile {self.name}")

    def describe(self) -> dict:
        with self._lock:
            return {"operators": list(self.member_names),
                    "dispatches": self.pages, "compiles": self.compiles}


class _AggTerminal:
    """Terminal adapter around a real HashAggregationOperator: the fused
    kernel computes the per-page contribution; this absorbs it into the
    inner operator's builder (state, spill and result building unchanged)."""

    def __init__(self, factory: HashAggregationOperatorFactory, worker: int):
        self.op = factory.create_operator(worker)
        self.builder = self.op.builder
        if isinstance(self.builder, GroupedAggregationBuilder):
            self.mode = "grouped"
        elif isinstance(self.builder, DirectAggregationBuilder):
            self.mode = "direct"
        else:
            assert isinstance(self.builder, GlobalAggregationBuilder), \
                type(self.builder)
            self.mode = "global"

    def variant(self):
        """Changes when the builder's adaptive per-page strategy flips
        (partial -> raw defer): the operator recomposes its fused kernel."""
        if self.mode == "grouped" and self.builder.defer_raw():
            return "raw"
        return "partial"

    def cache_key(self, input_dicts) -> tuple:
        tag = {"grouped": "sort", "direct": "direct",
               "global": "global"}[self.mode]
        return _builder_key(tag, self.builder,
                            input_dicts=tuple(input_dicts)) + (self.variant(),)

    def stage_plan(self):
        b = self.builder
        if self.mode == "grouped":
            if b.defer_raw():
                return ("agg_raw", b._page_raw)
            return ("agg_partial", b._page_partial)
        if self.mode == "direct":
            return ("agg_state", lambda page, st: b._accumulate(page, *st))
        return ("agg_state", lambda page, st: b._accumulate(page, st))

    def state(self):
        if self.mode == "grouped":
            return ()
        return self.builder.init_state()

    def out_groups(self, capacity: int) -> int:
        if self.mode == "grouped" and not self.builder.defer_raw():
            return self.builder.page_out_groups(capacity)
        return 0

    def absorb(self, result, capacity: int, out_groups: int) -> bool:
        b = self.builder
        if self.mode == "grouped":
            if b.defer_raw() and out_groups == 0:
                b.absorb_raw(result, capacity)
                ok = True
            else:
                ok = b.absorb_partial(result, capacity, out_groups)
        else:
            b.absorb_state(result)
            ok = True
        mem = getattr(b, "memory_bytes", None)
        if mem is not None:
            self.op.context.update_revocable(mem(),
                                             self.op.start_memory_revoke)
        return ok


class _TopNTerminal:
    """Terminal adapter around a real TopNOperator: the fused kernel merges
    the page into the N-row buffer, threaded through as a jit argument."""

    mode = "topn"

    def __init__(self, factory: TopNOperatorFactory, worker: int):
        self.op = factory.create_operator(worker)

    def variant(self):
        return "topn"

    def cache_key(self, input_dicts) -> tuple:
        f = self.op
        return ("topn", tuple(f.orders), f.n,
                tuple(t.name for t in f.output_types),
                tuple(kc.dict_key(d) for d in input_dicts))

    def stage_plan(self):
        orders, n = self.op.orders, self.op.n
        return ("topn", lambda page, st: topn_merge_stage(page, st, orders, n))

    def state(self):
        return self.op._buffer  # None before the first page (one retrace)

    def out_groups(self, capacity: int) -> int:
        return 0

    def absorb(self, result, capacity: int, out_groups: int) -> bool:
        self.op._buffer = result
        return True


def _compose(mid_plan, terminal_plan):
    """-> f(page, auxes, state, out_groups): the whole segment, traceable."""

    def run_mid(page, auxes):
        ai = 0
        for kind, obj in mid_plan:
            if kind == "proc":
                page = obj._process(page)
            else:  # probe
                page = apply_probe_stage(page, auxes[ai], obj)
                ai += 1
        return page

    tkind = terminal_plan[0]

    def fn(page, auxes, state, out_groups):
        page = run_mid(page, auxes)
        if tkind == "none":
            return page
        if tkind == "agg_partial":
            return terminal_plan[1](page, out_groups)
        if tkind == "agg_raw":
            return terminal_plan[1](page)
        return terminal_plan[1](page, state)  # agg_state | topn

    return fn


class FusedSegmentOperator(Operator):
    """Runs the whole segment chain as one jitted dispatch per input page."""

    def __init__(self, context: OperatorContext,
                 factory: FusedSegmentOperatorFactory, worker: int):
        super().__init__(context)
        self.f = factory
        self.worker = worker
        # per-stage runtime slots, chain order (probe stages resolve their
        # lookup source through is_blocked, exactly like LookupJoinOperator)
        self._stages = [{"factory": mf, "source": None, "aux": None}
                        for mf in factory.mid_factories]
        self._terminal = None
        tf = factory.terminal_factory
        if isinstance(tf, HashAggregationOperatorFactory):
            self._terminal = _AggTerminal(tf, worker)
        elif isinstance(tf, TopNOperatorFactory):
            self._terminal = _TopNTerminal(tf, worker)
        self._pending: Optional[Page] = None
        self._fused = None
        self._in_key = None
        self._tvariant = None
        self._pages = 0

    @property
    def output_types(self) -> List:
        return self.f.output_types

    # ------------------------------------------------------------- blocking

    def is_blocked(self):
        for st in self._stages:
            mf = st["factory"]
            if not isinstance(mf, LookupJoinOperatorFactory) or \
                    st["source"] is not None:
                continue
            lf = mf.lookup_factory
            w = self.worker
            if lf.done(w):
                st["source"] = lf.get(w)
                continue
            return lambda: lf.done(w)
        return None

    # ------------------------------------------------------------ execution

    def needs_input(self) -> bool:
        if self._finishing:
            return False
        if self._terminal is None:
            return self._pending is None
        return True

    def _install(self, page: Page, in_key) -> None:
        """(Re)compose + fetch the segment kernel for the live input layout.
        Mirrors PageProcessor.__call__'s rebuild-on-layout-drift: dictionary
        versions are part of the key, so an INSERT-extended dictionary can
        never replay a stale kernel."""
        from .expressions import InputLayout

        self._in_key = in_key
        cur_types = [b.type for b in page.blocks]
        cur_dicts = [b.dictionary for b in page.blocks]
        mid_plan = []
        keys = []
        for st in self._stages:
            mf = st["factory"]
            if isinstance(mf, FilterProjectOperatorFactory):
                proc = mf.processor
                live = kc.layout_key(cur_types, cur_dicts)
                if proc._layout_key != live:
                    proc._build(InputLayout(cur_types, cur_dicts))
                mid_plan.append(("proc", proc))
                keys.append(proc.cache_key)
                cur_types = list(proc.output_types_)
                cur_dicts = list(proc.output_dicts)
            else:
                src = st["source"]
                assert src is not None, \
                    "probe stage traced before its build finished"
                assert src.exact_keys, "fused probe needs exact keys"
                cfg = probe_stage_cfg(mf, src)
                st["aux"] = probe_stage_aux(src)
                mid_plan.append(("probe", cfg))
                keys.append(probe_stage_key(cfg))
                cur_types = [cur_types[c] for c in cfg.probe_output_channels] \
                    + [t for t, _ in cfg.payload_meta]
                cur_dicts = [cur_dicts[c] for c in cfg.probe_output_channels] \
                    + [d for _, d in cfg.payload_meta]
        if self._terminal is None:
            terminal_plan = ("none",)
            tkey = ("none",)
            self._tvariant = None
        else:
            terminal_plan = self._terminal.stage_plan()
            tkey = self._terminal.cache_key(cur_dicts)
            self._tvariant = self._terminal.variant()
        key = ("fused-segment", in_key, tuple(keys), tkey)

        def make():
            self.f.note_compile()
            return jax.jit(_compose(mid_plan, terminal_plan),
                           static_argnames=("out_groups",))

        self._fused = kc.get_or_install(key, make)

    def add_input(self, page: Page) -> None:
        # timed by hand instead of @timed: ONE clock pair feeds the stats
        # accumulator, the per-page dispatch histogram AND the trace span
        # (the decorator would add a second measurement of the same window
        # and a duplicate `operator` span per page)
        t0 = time.perf_counter_ns()
        self.context.record_input(page, page.capacity)
        in_key = kc.layout_key([b.type for b in page.blocks],
                               [b.dictionary for b in page.blocks])
        t = self._terminal
        if self._fused is None or in_key != self._in_key or \
                (t is not None and t.variant() != self._tvariant):
            self._install(page, in_key)
        auxes = tuple(st["aux"] for st in self._stages
                      if st["aux"] is not None)
        self._pages += 1
        try:
            if t is None:
                self._pending = self._fused(page, auxes, None, out_groups=0)
                return
            og = t.out_groups(page.capacity)
            result = self._fused(page, auxes, t.state(), out_groups=og)
            if not t.absorb(result, page.capacity, og):
                # the builder's shrunken partial table overflowed on this
                # page and reset to full size: recompute at the new size
                og = t.out_groups(page.capacity)
                ok = t.absorb(
                    self._fused(page, auxes, t.state(), out_groups=og),
                    page.capacity, og)
                assert ok, "full-size partial cannot overflow"
        finally:
            # per-page dispatch latency: one histogram observation per page
            # (pages are large, so this is per-dispatch, not per-row) plus a
            # flight-recorder span when a trace is live
            dt = time.perf_counter_ns() - t0
            stats = self.context.stats
            stats.add_input_ns += dt
            METRICS.histogram("segments.page_dispatch_s", dt / 1e9)
            trace.record(trace.SEGMENT, self.f.name, t0, dt,
                         {"rows": page.capacity}
                         if trace.active() is not None else None)

    @timed("get_output_ns")
    def get_output(self) -> Optional[Page]:
        if self._terminal is None:
            out, self._pending = self._pending, None
        else:
            out = self._terminal.op.get_output()
        if out is not None:
            self.context.record_output(out, out.capacity)
        return out

    def finish(self) -> None:
        super().finish()
        if self._terminal is not None:
            self._terminal.op.finish()

    def is_finished(self) -> bool:
        if self._terminal is not None:
            return self._terminal.op.is_finished()
        return self._finishing and self._pending is None

    def close(self) -> None:
        if self._pages:
            self.f.note_pages(self._pages)
            self._pages = 0
        if self._terminal is not None:
            self._terminal.op.close()
        super().close()
