"""Record decoder library: raw message bytes -> typed columns.

Analogue of presto-record-decoder (RowDecoder/FieldDecoder SPI used by the
kafka/redis-class connectors): a table DESCRIPTION names the message format
and maps message fields to SQL columns; the decoder turns a batch of raw
messages into per-column numpy arrays + null masks.

TPU-shaped contract: decoders are BATCH functions (list of messages in,
column arrays out) so the host decode loop stays amortizable and the scan
uploads whole columns, never per-row values. Undecodable fields are NULL,
never an error — a poison message must not kill the query (the reference's
decoder sets null and optionally surfaces `_message_corrupt`).

Formats:
- ``json``: one JSON object per message; field ``mapping`` is a ``/``
  separated path into nested objects.
- ``csv``: delimiter-separated text; ``mapping`` is the 0-based field index.
- ``raw``: the whole message as one value (varchar or bytes-as-varchar).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..types import DecimalType, Type, is_string  # noqa: F401 (Type in hints)


@dataclasses.dataclass(frozen=True)
class DecoderField:
    """One column of a decoded message (DecoderColumnHandle analogue)."""
    name: str
    type: Type
    mapping: str = ""          # json path | csv index | ignored for raw
    # strptime-style format for date/timestamp text fields; None = ISO8601
    # date ("%Y-%m-%d") / epoch-millis integer for timestamps
    date_format: Optional[str] = None


class RowDecoder:
    """decode(messages) -> {field name: (values ndarray, nulls ndarray|None)}.

    String-typed fields return dtype=object arrays of python str (the scan
    dictionary-encodes them); numeric fields return the type's np dtype."""

    def __init__(self, fields: Sequence[DecoderField]):
        self.fields = list(fields)

    def decode(self, messages: Sequence[bytes]) -> Dict[str, tuple]:
        raise NotImplementedError

    # ------------------------------------------------------------- helpers

    def _columns(self, rows: List[List[object]]) -> Dict[str, tuple]:
        """rows[i][j] = python value of field j in message i (None = null)."""
        out = {}
        n = len(rows)
        for j, f in enumerate(self.fields):
            vals = [r[j] for r in rows]
            out[f.name] = _to_typed(f, vals, n)
        return out


def _to_typed(f: DecoderField, vals: List[object], n: int) -> tuple:
    if is_string(f.type):
        nulls = np.fromiter((v is None for v in vals), dtype=np.bool_,
                            count=n) if any(v is None for v in vals) else None
        arr = np.array(["" if v is None else str(v) for v in vals],
                       dtype=object)
        return arr, nulls
    dt = f.type.np_dtype
    arr = np.zeros(n, dtype=dt)
    null_list = [v is None for v in vals]
    for i, v in enumerate(vals):
        if v is None:
            continue
        try:
            arr[i] = v
        except (OverflowError, ValueError):
            # value outside the column dtype's range: null-on-poison, the
            # same contract as an undecodable field
            null_list[i] = True
    nulls = np.asarray(null_list, dtype=np.bool_) if any(null_list) else None
    return arr, nulls


def _coerce(f: DecoderField, v) -> object:
    """Python message value -> engine substrate value, None when undecodable
    (the null-on-poison contract)."""
    try:
        if v is None:
            return None
        t = f.type
        if is_string(t):
            return v if isinstance(v, str) else str(v)
        if t.name == "boolean":
            if isinstance(v, str):
                if v.lower() in ("true", "1"):
                    return True
                if v.lower() in ("false", "0"):
                    return False
                return None
            return bool(v)
        if t.name == "date":
            import datetime
            if isinstance(v, str):
                fmt = f.date_format or "%Y-%m-%d"
                d = datetime.datetime.strptime(v.strip(), fmt).date()
                return (d - datetime.date(1970, 1, 1)).days
            return int(v)
        if t.name == "timestamp":
            import datetime
            if isinstance(v, str):
                if f.date_format:
                    dt = datetime.datetime.strptime(v.strip(), f.date_format)
                else:
                    dt = datetime.datetime.fromisoformat(v.strip())
                # aware timestamps (RFC3339 'Z'/offset, the producer norm)
                # need an aware epoch; naive ones a naive epoch
                if dt.tzinfo is not None:
                    epoch = datetime.datetime(
                        1970, 1, 1, tzinfo=datetime.timezone.utc)
                else:
                    epoch = datetime.datetime(1970, 1, 1)
                return int((dt - epoch).total_seconds() * 1000)
            return int(v)
        if isinstance(t, DecimalType):
            from decimal import Decimal
            return int(round(Decimal(str(v)).scaleb(t.scale)))
        if t.name in ("double", "real"):
            return float(v)
        return int(v)
    except (ValueError, TypeError, ArithmeticError):
        return None


class JsonRowDecoder(RowDecoder):
    """One JSON object per message; mapping is a /-separated path
    (reference: decoder/json/JsonRowDecoder.java)."""

    def decode(self, messages: Sequence[bytes]) -> Dict[str, tuple]:
        paths = [tuple(p for p in f.mapping.split("/") if p) or (f.name,)
                 for f in self.fields]
        rows: List[List[object]] = []
        for m in messages:
            try:
                obj = json.loads(m.decode("utf-8") if isinstance(m, bytes)
                                 else m)
            except (ValueError, UnicodeDecodeError):
                rows.append([None] * len(self.fields))
                continue
            row = []
            for f, path in zip(self.fields, paths):
                v = obj
                for seg in path:
                    if isinstance(v, dict):
                        v = v.get(seg)
                    else:
                        v = None
                        break
                row.append(_coerce(f, v))
            rows.append(row)
        return self._columns(rows)


class CsvRowDecoder(RowDecoder):
    """Delimiter-separated text; mapping is the 0-based field index
    (reference: decoder/csv/CsvRowDecoder.java)."""

    def __init__(self, fields: Sequence[DecoderField], delimiter: str = ","):
        super().__init__(fields)
        self.delimiter = delimiter
        for f in fields:
            try:
                int(f.mapping)
            except ValueError:
                raise ValueError(
                    f"csv field {f.name!r}: mapping must be a 0-based "
                    f"field index, got {f.mapping!r}")

    def decode(self, messages: Sequence[bytes]) -> Dict[str, tuple]:
        idx = [int(f.mapping) for f in self.fields]
        rows: List[List[object]] = []
        for m in messages:
            try:
                text = m.decode("utf-8") if isinstance(m, bytes) else m
            except UnicodeDecodeError:
                rows.append([None] * len(self.fields))
                continue
            parts = text.rstrip("\r\n").split(self.delimiter)
            row = []
            for f, i in zip(self.fields, idx):
                v = parts[i] if 0 <= i < len(parts) else None
                if v == "" and not is_string(f.type):
                    v = None
                row.append(_coerce(f, v))
            rows.append(row)
        return self._columns(rows)


class RawRowDecoder(RowDecoder):
    """The whole message as one value (reference: decoder/raw/RawRowDecoder
    narrowed to the text case; binary slicing is not represented on the
    engine's substrate)."""

    def __init__(self, fields: Sequence[DecoderField]):
        super().__init__(fields)
        if len(fields) != 1 or not is_string(fields[0].type):
            raise ValueError("raw decoder takes exactly one varchar field")

    def decode(self, messages: Sequence[bytes]) -> Dict[str, tuple]:
        rows = []
        for m in messages:
            try:
                rows.append([m.decode("utf-8") if isinstance(m, bytes)
                             else str(m)])
            except UnicodeDecodeError:
                rows.append([None])
        return self._columns(rows)


_DECODERS = {"json": JsonRowDecoder, "csv": CsvRowDecoder,
             "raw": RawRowDecoder}


def create_row_decoder(data_format: str, fields: Sequence[DecoderField],
                       **options) -> RowDecoder:
    """DispatchingRowDecoderFactory analogue."""
    cls = _DECODERS.get(data_format)
    if cls is None:
        raise ValueError(f"unknown message format {data_format!r} "
                         f"(supported: {sorted(_DECODERS)})")
    return cls(fields, **options)


def register_row_decoder(name: str, factory) -> None:
    """Plugin hook for additional formats."""
    _DECODERS[name] = factory
