"""Connector SPI — the plugin boundary.

Analogue of presto-spi (spi/Plugin.java:31, spi/connector/Connector.java:27,
spi/connector/ConnectorMetadata.java:59, spi/ConnectorSplitManager,
spi/ConnectorPageSource.java:20, spi/ConnectorPageSinkProvider,
spi/connector/ConnectorNodePartitioningProvider).

Contract, TPU-flavored: a page source yields `Page` batches of a FIXED capacity chosen
by the engine (so downstream jitted kernels compile once per schema), with the tail
batch padded + masked. Connectors that know their data layout can expose bucketing via
`ConnectorNodePartitioningProvider` so co-partitioned joins skip the mesh exchange,
exactly like the reference's bucketed hive tables.
"""
from __future__ import annotations

import abc
import dataclasses
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from ..block import Dictionary, Page
from ..types import Type


@dataclasses.dataclass(frozen=True)
class ColumnMetadata:
    name: str
    type: Type
    hidden: bool = False
    # Static string dictionary for varchar columns. On TPU, dictionaries are plan-time
    # metadata: the expression compiler resolves string predicates against them into
    # integer compares (the role DictionaryBlock plays at runtime in the reference,
    # spi/block/DictionaryBlock.java).
    dictionary: Optional[Dictionary] = None


@dataclasses.dataclass(frozen=True)
class ColumnHandle:
    """Connector-opaque column reference (spi/ColumnHandle)."""
    connector_id: str
    name: str
    type: Type
    ordinal: int = -1


@dataclasses.dataclass(frozen=True)
class SchemaTableName:
    schema: str
    table: str

    def __str__(self):
        return f"{self.schema}.{self.table}"


@dataclasses.dataclass(frozen=True)
class TableHandle:
    """spi/ConnectorTableHandle + engine-level metadata/TableHandle rolled together."""
    connector_id: str
    schema_table: SchemaTableName
    extra: Tuple = ()  # connector payload (e.g. tpch scale factor)


@dataclasses.dataclass(frozen=True)
class TableMetadata:
    name: SchemaTableName
    columns: Tuple[ColumnMetadata, ...]

    def column(self, name: str) -> ColumnMetadata:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)


@dataclasses.dataclass
class ColumnStatistics:
    """spi/statistics/ColumnStatistics — feeds the CBO."""
    distinct_count: Optional[float] = None
    null_fraction: float = 0.0
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    avg_bytes: Optional[float] = None


@dataclasses.dataclass
class TableStatistics:
    """spi/statistics/TableStatistics."""
    row_count: Optional[float] = None
    columns: Dict[str, ColumnStatistics] = dataclasses.field(default_factory=dict)

    @staticmethod
    def empty() -> "TableStatistics":
        return TableStatistics()


@dataclasses.dataclass(frozen=True)
class Split:
    """spi/ConnectorSplit: a schedulable unit of data. `addresses` drive split-affinity
    placement (SOURCE_DISTRIBUTION); `bucket` drives grouped/lifespan execution."""
    connector_id: str
    payload: Tuple
    addresses: Tuple[str, ...] = ()
    remotely_accessible: bool = True
    bucket: Optional[int] = None


class ConnectorPageSource(abc.ABC):
    """spi/ConnectorPageSource.java:20 — a stream of fixed-capacity masked pages."""

    # True = reads may block INDEFINITELY on progress the engine does not
    # control (remote tasks over HTTP, another coordinator, a live stream's
    # future records). The scan pipeline must not step such a source on the
    # shared worker pool — a read that cannot honor the bounded-step
    # contract would wedge a pool worker and starve every other query's
    # stages (including, circularly, the upstream producers this read is
    # waiting for). Local file/generator reads are pure compute: False.
    external_wait = False

    @abc.abstractmethod
    def __iter__(self) -> Iterator[Page]:
        ...

    def close(self) -> None:
        pass

    def completed_bytes(self) -> int:
        return 0

    def split_readers(self, target_rows: int):
        """Optional scan-pipeline decomposition: a list of zero-arg callables,
        each returning an iterable of `ops.scan_pipeline.HostChunk`s for one
        independently-readable row range, in stream order. The streaming scan
        reads them concurrently on a shared reader pool and re-batches the
        chunks into device-shaped pages (order-preserving). None = this
        source only supports serial page iteration."""
        return None

    @property
    def cache_token(self) -> Optional[tuple]:
        """Hashable identity of a DETERMINISTIC, IMMUTABLE page stream, or None.

        A non-None token lets the scan keep the uploaded device pages resident
        and replay them for later scans with the same token (the reference's
        LocalQueryRunner benchmark pattern: repeated queries read in-memory
        pages, not the generator). Mutable sources (memory connector tables,
        files that can change) must return None."""
        return None


class FixedPageSource(ConnectorPageSource):
    def __init__(self, pages: Sequence[Page]):
        self._pages = list(pages)

    def __iter__(self):
        return iter(self._pages)


class ConnectorPageSink(abc.ABC):
    """spi/ConnectorPageSink — write path for INSERT/CTAS."""

    @abc.abstractmethod
    def append_page(self, page: Page) -> None:
        ...

    def finish(self) -> Any:
        return None

    def abort(self) -> None:
        pass


@dataclasses.dataclass
class Constraint:
    """Pushed-down predicate summary (spi/Constraint + TupleDomain, simplified to
    per-column [min,max] / in-set domains, which covers TPC pruning)."""
    domains: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @staticmethod
    def all() -> "Constraint":
        return Constraint()


class ConnectorMetadata(abc.ABC):
    """spi/connector/ConnectorMetadata.java:59 (narrowed to the engine's needs)."""

    @abc.abstractmethod
    def list_schemas(self) -> List[str]:
        ...

    @abc.abstractmethod
    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        ...

    @abc.abstractmethod
    def get_table_handle(self, name: SchemaTableName) -> Optional[TableHandle]:
        ...

    @abc.abstractmethod
    def get_table_metadata(self, table: TableHandle) -> TableMetadata:
        ...

    def get_column_handles(self, table: TableHandle) -> Dict[str, ColumnHandle]:
        meta = self.get_table_metadata(table)
        return {c.name: ColumnHandle(table.connector_id, c.name, c.type, i)
                for i, c in enumerate(meta.columns)}

    def get_table_statistics(self, table: TableHandle,
                             constraint: Constraint) -> TableStatistics:
        return TableStatistics.empty()

    def get_unique_column_sets(self, table: TableHandle) -> List[Tuple[str, ...]]:
        """Column sets that uniquely identify a row (primary/unique keys). Lets the
        planner pick unique-build join kernels (the reference infers the same from
        spi/statistics distinct counts in DetermineJoinDistributionType)."""
        return []

    # write path (optional)
    def begin_insert(self, table: TableHandle):
        raise NotImplementedError(f"{type(self).__name__} does not support inserts")

    def finish_insert(self, handle, fragments) -> None:
        pass

    def create_table(self, metadata: TableMetadata,
                     properties: Optional[Dict[str, Any]] = None) -> None:
        """`properties` are the CTAS WITH(...) table properties (the
        reference's ConnectorMetadata table-property flow, e.g. hive
        partitioned_by). Connectors that define none must reject any."""
        raise NotImplementedError(f"{type(self).__name__} does not support CREATE TABLE")

    def drop_table(self, table: TableHandle) -> None:
        raise NotImplementedError(f"{type(self).__name__} does not support DROP TABLE")


class ConnectorSplitManager(abc.ABC):
    """spi/connector/ConnectorSplitManager."""

    @abc.abstractmethod
    def get_splits(self, table: TableHandle, constraint: Constraint,
                   desired_splits: int) -> List[Split]:
        ...


class ConnectorPageSourceProvider(abc.ABC):
    """spi/connector/ConnectorPageSourceProvider."""

    @abc.abstractmethod
    def create_page_source(self, split: Split, columns: Sequence[ColumnHandle],
                           page_capacity: int,
                           constraint: Constraint = Constraint.all()) -> ConnectorPageSource:
        ...


class ConnectorPageSinkProvider(abc.ABC):
    @abc.abstractmethod
    def create_page_sink(self, insert_handle) -> ConnectorPageSink:
        ...


class ConnectorNodePartitioningProvider:
    """spi/connector/ConnectorNodePartitioningProvider — connector bucketing.

    bucket_count(table) -> Optional[int]; bucket_of(split) -> bucket id. When present the
    engine can run grouped (lifespan) execution and skip re-exchanges for co-bucketed
    joins (operator/StageExecutionDescriptor.java:33)."""

    def bucket_count(self, table: TableHandle) -> Optional[int]:
        return None

    def bucket_columns(self, table: TableHandle) -> Optional[Tuple[str, ...]]:
        """Ordered column names the bucket hash is computed over, or None.
        Grouped execution requires them to verify join/grouping alignment."""
        return None


class Connector(abc.ABC):
    """spi/connector/Connector.java:27 — bundle of services for one catalog."""

    @abc.abstractmethod
    def metadata(self) -> ConnectorMetadata:
        ...

    @abc.abstractmethod
    def split_manager(self) -> ConnectorSplitManager:
        ...

    @abc.abstractmethod
    def page_source_provider(self) -> ConnectorPageSourceProvider:
        ...

    def page_sink_provider(self) -> Optional[ConnectorPageSinkProvider]:
        return None

    def node_partitioning_provider(self) -> ConnectorNodePartitioningProvider:
        return ConnectorNodePartitioningProvider()

    def session_properties(self) -> Dict[str, Any]:
        return {}

    def shutdown(self) -> None:
        pass


class ConnectorFactory(abc.ABC):
    """spi/connector/ConnectorFactory."""

    @property
    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def create(self, catalog_name: str, config: Dict[str, str]) -> Connector:
        ...


class Plugin:
    """spi/Plugin.java:31 — factories a plugin contributes. Subclass and override."""

    def connector_factories(self) -> List[ConnectorFactory]:
        return []

    def functions(self) -> List:
        return []

    def types(self) -> List[Type]:
        return []

    def event_listener_factories(self) -> List:
        return []
