"""Event listener SPI + query monitor.

Analogue of spi/eventlistener/ (EventListener.java, QueryCreatedEvent,
QueryCompletedEvent) and event/QueryMonitor.java:79,119,181: plugins register
listeners; the query manager emits created/completed events with timing,
state, row counts, and failure info. Listener exceptions are isolated — a
broken listener never fails a query (the reference wraps dispatch the same
way)."""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str = ""
    source: str = ""
    # client-supplied correlation id (X-Presto-Trace-Token), threaded
    # through events and /v1/query so external tracing can stitch a
    # request to the engine's execution (QueryMonitor's trace token)
    trace_token: str = ""
    create_time: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str = "FINISHED"            # FINISHED | FAILED | CANCELED
    user: str = ""
    trace_token: str = ""
    row_count: int = 0
    wall_seconds: float = 0.0
    error: Optional[Dict] = None
    end_time: float = dataclasses.field(default_factory=time.time)


class EventListener:
    """Base SPI class: override any subset (spi/eventlistener/EventListener.java)."""

    def query_created(self, event: QueryCreatedEvent) -> None:
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass


class QueryMonitor:
    """Dispatches lifecycle events to registered listeners (QueryMonitor.java)."""

    def __init__(self, listeners: Optional[List[EventListener]] = None):
        self.listeners: List[EventListener] = list(listeners or [])

    def add_listener(self, listener: EventListener) -> None:
        self.listeners.append(listener)

    def _dispatch(self, method: str, event) -> None:
        for lst in self.listeners:
            try:
                getattr(lst, method)(event)
            except Exception:  # noqa: BLE001 - listeners must never fail queries
                pass

    def query_created(self, event: QueryCreatedEvent) -> None:
        self._dispatch("query_created", event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._dispatch("query_completed", event)
