"""Collective exchange kernels: the ICI data plane.

Analogue of the reference's shuffle stack — producer side
operator/PartitionedOutputOperator.java:297,380-440 (row->partition, serialize,
enqueue) + buffer classes, consumer side operator/ExchangeClient.java pulling over
HTTP with LZ4 pages (execution/buffer/PagesSerde.java:39).

TPU re-design: there is no serialization, no HTTP, no LZ4 — a partitioned exchange is
ONE collective inside the SPMD program:

    repartition = sort rows by target partition + lax.all_to_all over the mesh axis
    broadcast   = lax.all_gather
    single      = all_gather then mask to worker 0

Pages stay fixed-capacity: each worker sends exactly `cap` row slots to every other
worker (count-carrying, tail-masked), so the collective has a static shape — the
price is padding bandwidth, the win is a single fused XLA program with the collective
overlapped against compute (what the reference approximates with async HTTP +
isBlocked futures).

These functions are pure and designed to be called INSIDE shard_map; they are the
building blocks the distributed planner stitches into stage programs.
"""
from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.hash_join import _mix64
from .mesh import WORKER_AXIS


def partition_ids(key: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Row -> target partition (PartitionFunction.getPartition analogue): mix then mod
    so dense keys spread (HashGenerationOptimizer's raw-hash + modulo). Uses the SAME
    mix as the join kernels' combined_key so exchange routing and build hashing can
    never diverge."""
    x = _mix64(key)
    return (x % jnp.uint64(n_parts)).astype(jnp.int32)


def repartition(arrays: Sequence[jnp.ndarray], mask: jnp.ndarray, key: jnp.ndarray,
                n_parts: int, out_cap_per_peer: int,
                axis_name: str = WORKER_AXIS):
    """All-to-all repartition of a row batch by key hash. Call inside shard_map.

    Each worker sends up to `out_cap_per_peer` rows to each peer (overflow rows are
    DROPPED and reported via the returned drop count — callers size capacity so this
    is a correctness assertion, the moral equivalent of the reference's buffer
    backpressure). Returns (arrays', mask', dropped) where arrays'/mask' hold the rows
    whose key hashes to THIS worker, shape (n_parts * out_cap_per_peer,).
    """
    pid = jnp.where(mask, partition_ids(key, n_parts), n_parts)
    return repartition_by_pid(arrays, mask, pid, n_parts, out_cap_per_peer,
                              axis_name)


def range_partition_ids(range_key: jnp.ndarray, splitters: jnp.ndarray,
                        mask: jnp.ndarray, n_parts: int) -> jnp.ndarray:
    """Row -> target partition by VALUE RANGE: worker w receives keys in
    (splitters[w-1], splitters[w]] — the distributed-ORDER-BY routing where
    worker order equals global order (MergeOperator's re-design; see
    sql/planner/plan.py MERGE)."""
    pid = jnp.searchsorted(splitters, range_key, side="left").astype(jnp.int32)
    return jnp.where(mask, jnp.clip(pid, 0, n_parts - 1), n_parts)


def repartition_by_pid(arrays: Sequence[jnp.ndarray], mask: jnp.ndarray,
                       pid: jnp.ndarray, n_parts: int, out_cap_per_peer: int,
                       axis_name: str = WORKER_AXIS):
    """Route rows to the peers named by `pid` (n_parts = masked-off). Shared
    tail of hash REPARTITION and range MERGE exchanges."""
    n = mask.shape[0]
    # stable sort rows by partition; within-partition order preserved
    order = jnp.argsort(pid, stable=True)
    pid_s = pid[order]
    # slot of each row within its partition
    pos_in_part = jnp.arange(n, dtype=jnp.int32) - jnp.searchsorted(
        pid_s, pid_s, side="left").astype(jnp.int32)
    keep = (pid_s < n_parts) & (pos_in_part < out_cap_per_peer)
    dropped = jnp.sum((pid_s < n_parts) & ~keep)
    outs, recv_mask = _route_kept(arrays, order, pid_s, pos_in_part, keep,
                                  n_parts, out_cap_per_peer, axis_name)
    return outs, recv_mask, dropped


def repartition_by_pid_with_carry(arrays: Sequence[jnp.ndarray],
                                  mask: jnp.ndarray, pid: jnp.ndarray,
                                  n_parts: int, out_cap_per_peer: int,
                                  axis_name: str = WORKER_AXIS):
    """Carry-over variant for the STREAMING exchange: overflow rows (the ones
    `repartition_by_pid` would drop when a peer's slice of this chunk exceeds
    `out_cap_per_peer`) are returned compacted to the front of same-shape
    carry buffers instead, staying resident on this worker for the pump to
    re-feed into the next chunk. Skewed keys are therefore correct by
    construction — capacity only bounds per-dispatch volume, never rows.

    Returns (recv_arrays, recv_mask, carry_arrays, carry_mask)."""
    n = mask.shape[0]
    order = jnp.argsort(pid, stable=True)
    pid_s = pid[order]
    pos_in_part = jnp.arange(n, dtype=jnp.int32) - jnp.searchsorted(
        pid_s, pid_s, side="left").astype(jnp.int32)
    live = pid_s < n_parts
    keep = live & (pos_in_part < out_cap_per_peer)
    overflow = live & ~keep
    outs, recv_mask = _route_kept(arrays, order, pid_s, pos_in_part, keep,
                                  n_parts, out_cap_per_peer, axis_name)
    # compact the overflow rows to the front of (n,) carry buffers
    cpos = jnp.cumsum(overflow.astype(jnp.int32)) - 1
    ctgt = jnp.where(overflow, cpos, n)
    carry_mask = jnp.zeros(n, dtype=jnp.bool_).at[ctgt].set(overflow,
                                                            mode="drop")
    carry = [jnp.zeros(n, dtype=a.dtype).at[ctgt].set(a[order], mode="drop")
             for a in arrays]
    return outs, recv_mask, carry, carry_mask


def _route_kept(arrays, order, pid_s, pos_in_part, keep, n_parts: int,
                out_cap_per_peer: int, axis_name: str):
    """Scatter the kept (sorted-by-pid) rows into (n_parts, cap) send buffers
    and run the all_to_all; shared tail of the drop and carry repartitions."""
    # scatter into (n_parts, cap) send buffers
    tgt = jnp.where(keep, pid_s * out_cap_per_peer + pos_in_part,
                    n_parts * out_cap_per_peer)
    send_mask = jnp.zeros(n_parts * out_cap_per_peer, dtype=jnp.bool_
                          ).at[tgt].set(keep, mode="drop")
    outs = []
    for a in arrays:
        buf = jnp.zeros(n_parts * out_cap_per_peer, dtype=a.dtype
                        ).at[tgt].set(a[order], mode="drop")
        outs.append(buf.reshape(n_parts, out_cap_per_peer))
    send_mask = send_mask.reshape(n_parts, out_cap_per_peer)
    # the collective: peer p receives every worker's partition-p slice
    recv = [lax.all_to_all(b, axis_name, split_axis=0, concat_axis=0, tiled=False)
            for b in outs]
    recv_mask = lax.all_to_all(send_mask, axis_name, split_axis=0, concat_axis=0,
                               tiled=False)
    outs = [r.reshape(n_parts * out_cap_per_peer) for r in recv]
    return outs, recv_mask.reshape(n_parts * out_cap_per_peer)


def broadcast_gather(arrays: Sequence[jnp.ndarray], mask: jnp.ndarray,
                     axis_name: str = WORKER_AXIS):
    """FIXED_BROADCAST: replicate every worker's rows to all workers
    (BroadcastOutputBuffer + replicated join build analogue)."""
    outs = [lax.all_gather(a, axis_name, tiled=True) for a in arrays]
    m = lax.all_gather(mask, axis_name, tiled=True)
    return outs, m


def gather_to_single(arrays: Sequence[jnp.ndarray], mask: jnp.ndarray,
                     axis_name: str = WORKER_AXIS):
    """SINGLE distribution: all rows on worker 0, masked off elsewhere
    (the coordinator-pull root exchange)."""
    outs, m = broadcast_gather(arrays, mask, axis_name)
    widx = lax.axis_index(axis_name)
    return outs, m & (widx == 0)


def psum_scalar(x: jnp.ndarray, axis_name: str = WORKER_AXIS) -> jnp.ndarray:
    return lax.psum(x, axis_name)
