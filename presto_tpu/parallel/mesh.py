"""Device mesh management.

The TPU equivalent of the reference's node inventory + discovery
(metadata/DiscoveryNodeManager.java:70, execution/scheduler/NodeScheduler.java:59):
"workers" are chips in a jax.sharding.Mesh. One mesh axis ("w") carries the engine's
inter-node parallelism; partitioned exchanges ride ICI collectives over it.

Multi-host: jax.distributed initializes process groups; the mesh spans all hosts'
devices and DCN handles cross-host legs of collectives — the control plane (split
assignment, task lifecycle) stays on the Python coordinator exactly like the
reference keeps HTTP for control while this design moves the data plane to XLA.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it in the experimental namespace
    from jax.experimental.shard_map import shard_map  # noqa: F401

WORKER_AXIS = "w"


@dataclasses.dataclass
class WorkerNode:
    """A schedulable worker = one chip (Node analogue, spi/Node)."""
    node_id: str
    device: jax.Device
    index: int

    @property
    def is_coordinator(self) -> bool:
        return self.index == 0


class MeshContext:
    """Holds the engine's mesh + sharding helpers."""

    def __init__(self, devices: Optional[List[jax.Device]] = None,
                 n_workers: Optional[int] = None):
        devs = devices if devices is not None else jax.devices()
        if n_workers is not None:
            devs = devs[:n_workers]
        self.devices = list(devs)
        self.mesh = Mesh(np.asarray(self.devices), (WORKER_AXIS,))
        self.nodes = [WorkerNode(f"worker-{i}", d, i) for i, d in enumerate(self.devices)]

    @property
    def n_workers(self) -> int:
        return len(self.devices)

    def sharded(self, *axes) -> NamedSharding:
        """NamedSharding with the leading dim over workers."""
        return NamedSharding(self.mesh, P(WORKER_AXIS, *axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def active_nodes(self) -> List[WorkerNode]:
        return self.nodes


_default_mesh: Optional[MeshContext] = None


def default_mesh() -> MeshContext:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = MeshContext()
    return _default_mesh


def set_default_mesh(ctx: MeshContext) -> None:
    global _default_mesh
    _default_mesh = ctx
