"""DistributedQueryRunner: planner-driven SQL execution over the device mesh.

The multi-chip analogue of presto-tests DistributedQueryRunner.java:77 — but
where the reference boots N HTTP servers, here "workers" are mesh devices:

  parse -> analyze/plan -> optimize -> AddExchanges -> PlanFragmenter
  -> per fragment (bottom-up): drive each worker's operator pipeline over its
     shard (worker-scoped splits or exchange-output pages)
  -> route the fragment's output through ONE shard_map collective over the ICI
     mesh (all_to_all repartition / all_gather broadcast / gather-to-root)

The data plane between fragments is the real XLA collective — the engine's
answer to the reference's HTTP+LZ4 shuffle (PartitionedOutputOperator.java:380,
ExchangeClient.java). Worker tasks within a fragment currently run sequentially
on the host control thread (the task-executor rev threads them); the collective
itself always runs as one SPMD program over all workers.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Block, Dictionary, Page
from ..exec.local_planner import LocalExecutionPlanner
from ..metadata import CatalogManager, Session
from ..runner import LocalQueryRunner, QueryResult
from ..sql import tree as t
from ..sql.planner.add_exchanges import add_exchanges
from ..sql.planner.fragmenter import (Fragment, SINGLE_PART, SOURCE_PART,
                                      SubPlan, fragment_plan)
from ..sql.planner.optimizer import optimize
from ..sql.planner.plan import (BROADCAST, GATHER, OutputNode, REPARTITION,
                                RemoteSourceNode, plan_to_text)
from ..sql.planner.planner import LogicalPlanner
from ..types import Type
from .mesh import MeshContext, WORKER_AXIS

# (pages for each worker, shared column dictionaries)
RemoteInput = Tuple[List[Page], List[Optional[Dictionary]]]


class DistributedQueryRunner:
    """In-process multi-worker engine over a jax.sharding.Mesh."""

    def __init__(self, mesh: Optional[MeshContext] = None,
                 session: Optional[Session] = None,
                 catalogs: Optional[CatalogManager] = None,
                 page_capacity: int = 1 << 14):
        self.local = LocalQueryRunner(session, catalogs, page_capacity)
        self.mesh = mesh if mesh is not None else MeshContext()

    @property
    def metadata(self):
        return self.local.metadata

    @property
    def session(self):
        return self.local.session

    # ------------------------------------------------------------------ api

    def plan_sql(self, sql: str) -> SubPlan:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            raise ValueError(f"cannot plan {type(stmt).__name__}")
        return self.plan_statement(stmt)

    def plan_statement(self, stmt: t.Query) -> SubPlan:
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, planner.symbols)
        return fragment_plan(plan)

    def explain(self, sql: str) -> str:
        sub = self.plan_sql(sql)
        parts = []
        for f in sub.fragments:
            head = f"Fragment {f.id} [{f.partitioning}]"
            if f.output_kind:
                keys = f" keys={[k.name for k in f.output_keys]}" \
                    if f.output_keys else ""
                head += f" output={f.output_kind}{keys}"
            parts.append(head + "\n" + plan_to_text(f.root, indent=1))
        return "\n".join(parts)

    def execute(self, sql: str) -> QueryResult:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            return self.local.execute(sql)  # EXPLAIN/SHOW et al stay local
        sub = self.plan_statement(stmt)
        return self._execute_subplan(sub)

    # ------------------------------------------------------------ execution

    def _execute_subplan(self, sub: SubPlan) -> QueryResult:
        W = self.mesh.n_workers
        # fid -> (per-worker routed pages, column dictionaries)
        routed_inputs: Dict[int, Tuple[List[List[Page]],
                                       List[Optional[Dictionary]]]] = {}
        for frag in sub.fragments:
            is_root = frag is sub.root_fragment
            if is_root:
                root = OutputNode(frag.root, sub.column_names,
                                  sub.output_symbols)
            else:
                syms = frag.root.outputs()
                root = OutputNode(frag.root, [s.name for s in syms], syms)
            workers = [0] if frag.partitioning == SINGLE_PART else list(range(W))
            per_worker: List[List[Page]] = [[] for _ in range(W)]
            out_types: List[Type] = []
            out_dicts: List[Optional[Dictionary]] = []
            for w in workers:
                remote = {fid: (pages[w], dicts)
                          for fid, (pages, dicts) in routed_inputs.items()}
                lp = LocalExecutionPlanner(
                    self.metadata, self.session,
                    worker=(w, W) if frag.partitioning == SOURCE_PART else None,
                    remote_pages=remote)
                ep = lp.plan(root)
                for d in ep.create_drivers():
                    d.run_to_completion()
                out_types, out_dicts = ep.output_types, ep.output_dicts
                if is_root:
                    return QueryResult(ep.sink.rows(), sub.column_names)
                per_worker[w] = [p for c in ep.sink.consumers for p in c.pages]
            key_idx = None
            if frag.output_kind == REPARTITION:
                names = [s.name for s in frag.root.outputs()]
                key_idx = [names.index(k.name) for k in frag.output_keys]
            routed = run_exchange(self.mesh, frag.output_kind, key_idx,
                                  per_worker, out_types, out_dicts)
            routed_inputs[frag.id] = (routed, out_dicts)
        raise AssertionError("root fragment must terminate execution")


# ---------------------------------------------------------------------------
# the exchange bridge: per-worker page lists -> one collective -> per-worker
# page lists (the engine's entire shuffle data plane)
# ---------------------------------------------------------------------------

def _flatten_worker(pages: List[Page], types: Sequence[Type],
                    length: int) -> Tuple[List[np.ndarray], List[np.ndarray],
                                          np.ndarray]:
    """Concat + pad this worker's pages to `length` rows per column."""
    ncols = len(types)
    datas: List[np.ndarray] = []
    nulls: List[np.ndarray] = []
    for c in range(ncols):
        dt = np.dtype(types[c].np_dtype)
        parts = [np.asarray(p.blocks[c].data) for p in pages]
        col = np.concatenate(parts) if parts else np.zeros(0, dtype=dt)
        col = col.astype(dt, copy=False)
        nparts = [np.asarray(p.blocks[c].nulls) if p.blocks[c].nulls is not None
                  else np.zeros(p.capacity, dtype=bool) for p in pages]
        nm = np.concatenate(nparts) if nparts else np.zeros(0, dtype=bool)
        pad = length - len(col)
        if pad:
            col = np.concatenate([col, np.zeros(pad, dtype=dt)])
            nm = np.concatenate([nm, np.zeros(pad, dtype=bool)])
        datas.append(col)
        nulls.append(nm)
    mparts = [np.asarray(p.mask) for p in pages]
    mask = np.concatenate(mparts) if mparts else np.zeros(0, dtype=bool)
    if length - len(mask):
        mask = np.concatenate([mask, np.zeros(length - len(mask), dtype=bool)])
    return datas, nulls, mask


def run_exchange(mesh: MeshContext, kind: str, key_idx: Optional[List[int]],
                 per_worker_pages: List[List[Page]], types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]]) -> List[List[Page]]:
    """Route every worker's output pages to their consumers with ONE shard_map
    collective over the mesh (REPARTITION=all_to_all, BROADCAST=all_gather,
    GATHER=all_gather masked to worker 0)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax import shard_map

    from ..ops.hash_join import combined_key
    from .exchange import broadcast_gather, gather_to_single, repartition

    W = mesh.n_workers
    ncols = len(types)
    L = max([sum(p.capacity for p in pages) for pages in per_worker_pages] + [1])

    # stack to (W*L,) global arrays, leading axis sharded over workers
    g_datas, g_nulls, g_mask = [], [], []
    flat = [_flatten_worker(pages, types, L) for pages in per_worker_pages]
    for c in range(ncols):
        g_datas.append(np.concatenate([f[0][c] for f in flat]))
        g_nulls.append(np.concatenate([f[1][c] for f in flat]))
    g_mask = np.concatenate([f[2] for f in flat])

    sharding = NamedSharding(mesh.mesh, P(WORKER_AXIS))
    dev_arrays = [jax.device_put(a, sharding) for a in g_datas + g_nulls]
    dev_mask = jax.device_put(g_mask, sharding)

    def stage(arrays, mask):
        if kind == REPARTITION:
            keys = [jnp.where(arrays[ncols + i], 0, arrays[i]).astype(jnp.int64)
                    for i in key_idx]
            out, m, _dropped = repartition(list(arrays), mask,
                                           combined_key(keys), W, L)
            return tuple(out), m
        if kind == BROADCAST:
            out, m = broadcast_gather(list(arrays), mask)
            return tuple(out), m
        if kind == GATHER:
            out, m = gather_to_single(list(arrays), mask)
            return tuple(out), m
        raise AssertionError(kind)

    smapped = shard_map(
        stage, mesh=mesh.mesh,
        in_specs=(tuple(P(WORKER_AXIS) for _ in dev_arrays), P(WORKER_AXIS)),
        out_specs=(tuple(P(WORKER_AXIS) for _ in dev_arrays), P(WORKER_AXIS)))
    out_arrays, out_mask = jax.jit(smapped)(tuple(dev_arrays), dev_mask)

    # split back into one page per worker
    out_np = [np.asarray(a) for a in out_arrays]
    mask_np = np.asarray(out_mask)
    out_len = len(mask_np) // W
    routed: List[List[Page]] = []
    for w in range(W):
        lo, hi = w * out_len, (w + 1) * out_len
        m = mask_np[lo:hi]
        if not m.any():
            routed.append([])
            continue
        blocks = []
        for c in range(ncols):
            nm = out_np[ncols + c][lo:hi]
            blocks.append(Block(types[c], out_np[c][lo:hi],
                                nm if nm.any() else None, dicts[c]))
        routed.append([Page(tuple(blocks), m)])
    return routed
