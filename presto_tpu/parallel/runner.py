"""DistributedQueryRunner: planner-driven SQL execution over the device mesh.

The multi-chip analogue of presto-tests DistributedQueryRunner.java:77 — but
where the reference boots N HTTP servers, here "workers" are mesh devices:

  parse -> analyze/plan -> optimize -> AddExchanges -> PlanFragmenter
  -> per fragment (bottom-up): drive each worker's operator pipeline over its
     shard (worker-scoped splits or exchange-output pages)
  -> route the fragment's output through ONE shard_map collective over the ICI
     mesh (all_to_all repartition / all_gather broadcast / gather-to-root)

The data plane between fragments is the real XLA collective — the engine's
answer to the reference's HTTP+LZ4 shuffle (PartitionedOutputOperator.java:380,
ExchangeClient.java). Worker tasks within a fragment currently run sequentially
on the host control thread (the task-executor rev threads them); the collective
itself always runs as one SPMD program over all workers.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Block, Dictionary, Page
from ..exec.local_planner import LocalExecutionPlanner
from ..exec.task_executor import TaskExecutor
from ..metadata import CatalogManager, Session
from ..runner import LocalQueryRunner, QueryResult
from ..sql import tree as t
from ..sql.planner.add_exchanges import add_exchanges
from ..sql.planner.fragmenter import (Fragment, SINGLE_PART, SubPlan,
                                      fragment_plan)
from ..sql.planner.optimizer import optimize
from ..sql.planner.plan import (BROADCAST, GATHER, OutputNode, REPARTITION,
                                RemoteSourceNode, plan_to_text)
from ..sql.planner.planner import LogicalPlanner
from ..types import Type
from .mesh import MeshContext, WORKER_AXIS

# (pages for each worker, shared column dictionaries)
RemoteInput = Tuple[List[Page], List[Optional[Dictionary]]]


class DistributedQueryRunner:
    """In-process multi-worker engine over a jax.sharding.Mesh."""

    def __init__(self, mesh: Optional[MeshContext] = None,
                 session: Optional[Session] = None,
                 catalogs: Optional[CatalogManager] = None,
                 page_capacity: int = 1 << 14):
        self.local = LocalQueryRunner(session, catalogs, page_capacity)
        self.mesh = mesh if mesh is not None else MeshContext()

    @property
    def metadata(self):
        return self.local.metadata

    @property
    def session(self):
        return self.local.session

    # ------------------------------------------------------------------ api

    def plan_sql(self, sql: str) -> SubPlan:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            raise ValueError(f"cannot plan {type(stmt).__name__}")
        return self.plan_statement(stmt)

    def plan_statement(self, stmt: t.Query) -> SubPlan:
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, planner.symbols, self.metadata, self.session)
        return fragment_plan(plan)

    def explain(self, sql: str) -> str:
        sub = self.plan_sql(sql)
        parts = []
        for f in sub.fragments:
            head = f"Fragment {f.id} [{f.partitioning}]"
            if f.output_kind:
                keys = f" keys={[k.name for k in f.output_keys]}" \
                    if f.output_keys else ""
                head += f" output={f.output_kind}{keys}"
            parts.append(head + "\n" + plan_to_text(f.root, indent=1))
        return "\n".join(parts)

    def execute(self, sql: str) -> QueryResult:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            return self.local.execute(sql)  # EXPLAIN/SHOW et al stay local
        sub = self.plan_statement(stmt)
        return self._execute_subplan(sub)

    # ------------------------------------------------------------ execution

    def _execute_subplan(self, sub: SubPlan) -> QueryResult:
        W = self.mesh.n_workers
        frag_dicts: Dict[int, List[Optional[Dictionary]]] = {}
        routed: Dict[int, List[List[Page]]] = {}  # fid -> per-worker pages
        # ONE memory pool + query context for the whole query: every
        # fragment's operators draw on the same budget
        query_memory = self.local._query_memory()
        for frag in sub.fragments:
            is_root = frag is sub.root_fragment
            if is_root:
                root = OutputNode(frag.root, sub.column_names,
                                  sub.output_symbols)
            else:
                syms = frag.root.outputs()
                root = OutputNode(frag.root, [s.name for s in syms], syms)
            workers = [0] if frag.partitioning == SINGLE_PART else list(range(W))
            # plan ONCE per fragment: every worker shares the factories (and so
            # the jit-compiled kernels); only splits/exchange pages differ
            lp = LocalExecutionPlanner(self.metadata, self.session,
                                       n_workers=W, remote_dicts=frag_dicts)
            lp.attach_memory(*query_memory)
            ep = lp.plan(root)
            for fid, slot in ep.remote_slots.items():
                for w in range(W):
                    slot.set_pages(w, routed[fid][w])
            # all workers' drivers share one executor: worker tasks and their
            # build/probe pipelines time-slice across runner threads
            drivers = [d for w in workers for d in ep.create_drivers(w)]
            TaskExecutor(
                int(self.session.get("task_concurrency"))).execute(drivers)
            if is_root:
                return QueryResult(ep.sink.rows(), sub.column_names,
                                   ep.output_types)
            per_worker = [ep.sink.pages_for(w) for w in range(W)]
            key_idx = None
            if frag.output_kind == REPARTITION:
                names = [s.name for s in frag.root.outputs()]
                key_idx = [names.index(k.name) for k in frag.output_keys]
            routed[frag.id] = run_exchange(
                self.mesh, frag.output_kind, key_idx, per_worker,
                ep.output_types, ep.output_dicts,
                page_capacity=int(self.session.get("page_capacity")))
            frag_dicts[frag.id] = ep.output_dicts
        raise AssertionError("root fragment must terminate execution")


# ---------------------------------------------------------------------------
# the exchange bridge: per-worker page lists -> one collective -> per-worker
# page lists (the engine's entire shuffle data plane)
# ---------------------------------------------------------------------------

def _compact_worker(pages: List[Page], types: Sequence[Type]
                    ) -> Tuple[List[np.ndarray], List[np.ndarray], int]:
    """Concat this worker's pages and drop masked-off rows (host side).

    Compaction is what keeps exchange shapes bounded by LIVE row counts: an
    exchange's receive buffer is W x cap, so forwarding padding would multiply
    page capacity by W at every exchange hop."""
    ncols = len(types)
    mparts = [np.asarray(p.mask) for p in pages]
    mask = np.concatenate(mparts) if mparts else np.zeros(0, dtype=bool)
    keep = np.flatnonzero(mask)
    datas: List[np.ndarray] = []
    nulls: List[np.ndarray] = []
    for c in range(ncols):
        dt = np.dtype(types[c].np_dtype)
        parts = [np.asarray(p.blocks[c].data) for p in pages]
        col = np.concatenate(parts) if parts else np.zeros(0, dtype=dt)
        datas.append(col.astype(dt, copy=False)[keep])
        nparts = [np.asarray(p.blocks[c].nulls) if p.blocks[c].nulls is not None
                  else np.zeros(p.capacity, dtype=bool) for p in pages]
        nm = np.concatenate(nparts) if nparts else np.zeros(0, dtype=bool)
        nulls.append(nm[keep])
    return datas, nulls, len(keep)


def _pad_to(arr: np.ndarray, length: int) -> np.ndarray:
    pad = length - len(arr)
    if pad <= 0:
        return arr
    return np.concatenate([arr, np.zeros(pad, dtype=arr.dtype)])


@functools.lru_cache(maxsize=256)
def _exchange_program(mesh, kind: str, key_idx: Optional[Tuple[int, ...]],
                      ncols: int, W: int, L: int):
    """Build + jit the exchange collective ONCE per (mesh, kind, keys, shape)
    signature — repeated exchanges of the same shape reuse the compiled XLA
    program (the reference reuses its HTTP buffer machinery similarly)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    from ..ops.hash_join import combined_key
    from .exchange import broadcast_gather, gather_to_single, repartition

    def stage(arrays, mask):
        if kind == REPARTITION:
            keys = [jnp.where(arrays[ncols + i], 0, arrays[i]).astype(jnp.int64)
                    for i in key_idx]
            out, m, dropped = repartition(list(arrays), mask,
                                          combined_key(keys), W, L)
            return tuple(out), m, dropped.reshape(1)
        if kind == BROADCAST:
            out, m = broadcast_gather(list(arrays), mask)
        elif kind == GATHER:
            out, m = gather_to_single(list(arrays), mask)
        else:
            raise AssertionError(kind)
        return tuple(out), m, jnp.zeros(1, dtype=jnp.int32)

    n_arrays = 2 * ncols
    smapped = shard_map(
        stage, mesh=mesh,
        in_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)), P(WORKER_AXIS)),
        out_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                   P(WORKER_AXIS), P(WORKER_AXIS)))
    return jax.jit(smapped)


def run_exchange(mesh: MeshContext, kind: str, key_idx: Optional[List[int]],
                 per_worker_pages: List[List[Page]], types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int = 1 << 14) -> List[List[Page]]:
    """Route every worker's output pages to their consumers with ONE shard_map
    collective over the mesh (REPARTITION=all_to_all, BROADCAST=all_gather,
    GATHER=all_gather masked to worker 0)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    W = mesh.n_workers
    ncols = len(types)
    flat = [_compact_worker(pages, types) for pages in per_worker_pages]
    # bucket L (live rows of the fullest worker) to powers of two so repeated
    # exchanges of similar volume reuse one compiled collective
    L = max(max(f[2] for f in flat), 1)
    L = 1 << (L - 1).bit_length()

    # stack to (W*L,) global arrays, leading axis sharded over workers
    g_datas = [np.concatenate([_pad_to(f[0][c], L) for f in flat])
               for c in range(ncols)]
    g_nulls = [np.concatenate([_pad_to(f[1][c], L) for f in flat])
               for c in range(ncols)]
    g_mask = np.concatenate(
        [_pad_to(np.ones(f[2], dtype=bool), L) for f in flat])

    sharding = NamedSharding(mesh.mesh, P(WORKER_AXIS))
    dev_arrays = [jax.device_put(a, sharding) for a in g_datas + g_nulls]
    dev_mask = jax.device_put(g_mask, sharding)

    # jax.sharding.Mesh is hashable and value-equal: safe as the cache key
    program = _exchange_program(
        mesh.mesh, kind, tuple(key_idx) if key_idx is not None else None,
        ncols, W, L)
    out_arrays, out_mask, dropped = program(tuple(dev_arrays), dev_mask)
    n_dropped = int(np.asarray(dropped).sum())
    if n_dropped:
        # the send buffers are sized to the fullest worker's live rows, so a
        # drop means a sizing bug upstream — corrupt results must fail loudly
        # (the reference's OutputBuffer applies backpressure instead; see
        # parallel/exchange.py repartition docstring)
        raise RuntimeError(
            f"repartition exchange dropped {n_dropped} rows "
            f"(capacity {L} per peer, {W} workers)")

    # split back per worker, compact, and re-page at the standard page capacity
    # (standard-shaped pages let every downstream operator reuse the kernels it
    # already compiled for scan pages)
    out_np = [np.asarray(a) for a in out_arrays]
    mask_np = np.asarray(out_mask)
    out_len = len(mask_np) // W
    routed: List[List[Page]] = []
    for w in range(W):
        lo, hi = w * out_len, (w + 1) * out_len
        keep = np.flatnonzero(mask_np[lo:hi]) + lo
        if len(keep) == 0:
            routed.append([])
            continue
        cap = min(page_capacity, 1 << (max(len(keep), 1) - 1).bit_length())
        pages_out: List[Page] = []
        for p0 in range(0, len(keep), cap):
            sel = keep[p0:p0 + cap]
            blocks = []
            for c in range(ncols):
                nm = _pad_to(out_np[ncols + c][sel], cap)
                blocks.append(Block(types[c], _pad_to(out_np[c][sel], cap),
                                    nm if nm.any() else None, dicts[c]))
            pages_out.append(Page(tuple(blocks),
                                  _pad_to(np.ones(len(sel), dtype=bool), cap)))
        routed.append(pages_out)
    return routed
