"""DistributedQueryRunner: planner-driven SQL execution over the device mesh.

The multi-chip analogue of presto-tests DistributedQueryRunner.java:77 — but
where the reference boots N HTTP servers, here "workers" are mesh devices:

  parse -> analyze/plan -> optimize -> AddExchanges -> PlanFragmenter
  -> per fragment (bottom-up): drive each worker's operator pipeline over its
     shard (worker-scoped splits or exchange-output pages)
  -> route the fragment's output through shard_map collectives over the ICI
     mesh (all_to_all repartition / all_gather broadcast / gather-to-root)

The data plane between fragments is the real XLA collective — the engine's
answer to the reference's HTTP+LZ4 shuffle (PartitionedOutputOperator.java:380,
ExchangeClient.java). Two modes:

- STREAMING (default, `streaming_exchange=True`): every fragment's drivers
  run concurrently on ONE task executor; fragment boundaries are
  StreamingExchange instances (parallel/streaming_exchange.py) moving
  fixed-capacity chunks through one compiled collective per chunk while
  producers still run — the ExchangeClient pull-while-producing shape, with
  byte-bounded backpressure on both sides.
- BARRIER (`streaming_exchange=False`, the differential oracle): fragments
  execute bottom-up, each draining fully before `run_exchange` routes ALL of
  its output in one variable-shape collective — the pre-streaming data plane,
  kept bit-for-bit for A/B testing exactly like `segment_fusion=False`.

Within a fragment, EVERY worker's drivers are enqueued on one shared
TaskExecutor and time-slice across its runner threads (so 8 virtual workers
never host-serialize; build/probe pipelines of different workers overlap);
the collective itself always runs as one SPMD program over all workers.
"""
from __future__ import annotations

import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Block, Dictionary, Page
from ..exec.local_planner import LocalExecutionPlanner
from ..exec.shared_pools import next_query_key
from ..exec.task_executor import TaskExecutor
from ..metadata import CatalogManager, Session
from ..runner import LocalQueryRunner, QueryResult
from ..sql import tree as t
from ..sql.planner.add_exchanges import add_exchanges
from ..sql.planner.fragmenter import (Fragment, SINGLE_PART, SubPlan,
                                      fragment_plan)
from ..sql.planner.optimizer import optimize
from ..sql.planner.plan import (BROADCAST, GATHER, MERGE, OutputNode,
                                REPARTITION, RemoteSourceNode, plan_to_text)
from ..sql.planner.planner import LogicalPlanner
from ..types import Type
from ..utils import trace
from ..utils.metrics import METRICS
from .mesh import MeshContext, WORKER_AXIS
# shared exchange plumbing (one accounting + device-helper set for both data
# planes); EXCHANGE_STATS re-exported here because the multichip dryrun (and
# history) imports it from this module
from .streaming_exchange import (EXCHANGE_STATS, ExchangeSinkOperatorFactory,  # noqa: F401
                                 ExchangeStatsBook, StreamingExchange,
                                 _compact_pad_jit, _range_key_for,
                                 _zeros_shard, record_exchange_stat)

# (pages for each worker, shared column dictionaries)
RemoteInput = Tuple[List[Page], List[Optional[Dictionary]]]


class DistributedQueryRunner:
    """In-process multi-worker engine over a jax.sharding.Mesh."""

    def __init__(self, mesh: Optional[MeshContext] = None,
                 session: Optional[Session] = None,
                 catalogs: Optional[CatalogManager] = None,
                 page_capacity: int = 1 << 14):
        self.local = LocalQueryRunner(session, catalogs, page_capacity)
        self.mesh = mesh if mesh is not None else MeshContext()

    @property
    def metadata(self):
        return self.local.metadata

    @property
    def session(self):
        return self.local.session

    # ------------------------------------------------------------------ api

    def plan_sql(self, sql: str) -> SubPlan:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            raise ValueError(f"cannot plan {type(stmt).__name__}")
        return self.plan_statement(stmt)

    def plan_statement(self, stmt: t.Query) -> SubPlan:
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, planner.symbols, self.metadata, self.session,
                             n_workers=self.mesh.n_workers)
        return fragment_plan(plan)

    def explain(self, sql: str) -> str:
        sub = self.plan_sql(sql)
        parts = []
        for f in sub.fragments:
            head = f"Fragment {f.id} [{f.partitioning}]"
            if f.output_kind:
                keys = f" keys={[k.name for k in f.output_keys]}" \
                    if f.output_keys else ""
                head += f" output={f.output_kind}{keys}"
            parts.append(head + "\n" + plan_to_text(f.root, indent=1))
        return "\n".join(parts)

    def execute(self, sql: str) -> QueryResult:
        stmt = self.local.parser.parse(sql)
        if isinstance(stmt, t.Explain) and stmt.analyze and \
                isinstance(stmt.statement, t.Query):
            # distributed EXPLAIN ANALYZE: execute over the mesh and render
            # per-fragment per-operator stats rolled up across workers —
            # before this, ANALYZE silently profiled the single-node path
            return self._explain_analyze(stmt.statement)
        if not isinstance(stmt, t.Query):
            return self.local.execute(sql)  # EXPLAIN/SHOW et al stay local
        sub = self.plan_statement(stmt)
        return self._execute_subplan(sub)

    # ------------------------------------------------------------ execution

    def _execute_subplan(self, sub: SubPlan,
                         frag_drivers: Optional[Dict[int, List[list]]] = None
                         ) -> QueryResult:
        """`frag_drivers`, when given, collects each fragment's per-worker
        driver lists for EXPLAIN ANALYZE's stats roll-up."""
        import time as _time

        book = ExchangeStatsBook()

        def run() -> QueryResult:
            if bool(self.session.get("streaming_exchange", True)):
                return self._execute_streaming(sub, book, frag_drivers)
            return self._execute_barrier(sub, book, frag_drivers)

        t0 = _time.perf_counter()
        rec = trace.maybe_recorder(self.session)
        installed = rec is not None and trace.install(rec)
        try:
            # span only on THIS query's recorder: an untraced query running
            # concurrently with a traced one must not write a full-wall
            # lifecycle span into the other query's timeline
            if installed:
                with rec.span(trace.LIFECYCLE, "query"):
                    result = run()
            else:
                result = run()
        except BaseException as e:
            # black-box forensics: the failing query's coarse ring rides
            # the exception (QueryInfo.failure_trace_path upstream)
            if installed:
                trace.attach_failure(e, rec, self.session)
            raise
        finally:
            if installed:
                trace.uninstall(rec)
        METRICS.histogram("query.wall_s", _time.perf_counter() - t0)
        snap = book.snapshot()
        if snap:
            snap["mode"] = "streaming" \
                if bool(self.session.get("streaming_exchange", True)) \
                else "barrier"
            result.stats = dict(result.stats or {}, exchange=snap)
            METRICS.count_many(
                {k: v for k, v in snap.items()
                 if isinstance(v, (int, float))}, prefix="exchange.")
        if installed and not rec.coarse:
            result.trace_path = trace.export(rec, self.session)
        return result

    def _fragment_root(self, sub: SubPlan, frag: Fragment) -> OutputNode:
        if frag is sub.root_fragment:
            return OutputNode(frag.root, sub.column_names, sub.output_symbols)
        syms = frag.root.outputs()
        return OutputNode(frag.root, [s.name for s in syms], syms)

    def _routing_spec(self, frag: Fragment):
        """-> (key_idx, orderings) for the fragment's output exchange."""
        names = [s.name for s in frag.root.outputs()]
        key_idx = None
        orderings = None
        if frag.output_kind == REPARTITION:
            key_idx = [names.index(k.name) for k in frag.output_keys]
        elif frag.output_kind == MERGE:
            orderings = tuple(
                (names.index(o.symbol.name), o.descending, o.nulls_first)
                for o in frag.output_orderings)
        return key_idx, orderings

    def _execute_streaming(self, sub: SubPlan, book: ExchangeStatsBook,
                           frag_drivers: Optional[dict] = None) \
            -> QueryResult:
        """Plan every fragment, connect them with StreamingExchanges, then
        run ALL fragments' drivers in ONE task-executor pass: producer and
        consumer fragments time-slice on the same runner threads while the
        exchange pumps move chunks between them."""
        W = self.mesh.n_workers
        frag_dicts: Dict[int, List[Optional[Dictionary]]] = {}
        exchanges: Dict[int, StreamingExchange] = {}
        sink_facs: Dict[int, ExchangeSinkOperatorFactory] = {}
        mem_ctx, over_target, mem_release = self.local._query_memory()
        chunk_rows = int(self.session.get("exchange_chunk_rows") or 0)
        inflight = int(self.session.get("exchange_inflight_bytes") or 0)
        page_cap = int(self.session.get("page_capacity") or (1 << 14))
        # ONE shared-pool fairness slot per query: every fragment's scan
        # stages and every exchange pump of this query share it
        pool_key = next_query_key("mesh-q") \
            if bool(self.session.get("shared_pools", True)) else None
        drivers = []
        root_ep = None
        planned = []  # (fragment, local plan) — skew wiring scans consumers
        try:
            for frag in sub.fragments:
                is_root = frag is sub.root_fragment
                root = self._fragment_root(sub, frag)
                workers = [0] if frag.partitioning == SINGLE_PART \
                    else list(range(W))
                lp = LocalExecutionPlanner(self.metadata, self.session,
                                           n_workers=W,
                                           remote_dicts=frag_dicts,
                                           devices=self.mesh.devices,
                                           pool_key=pool_key)
                lp.attach_memory(mem_ctx, over_target)
                if is_root:
                    ep = lp.plan(root)
                else:
                    key_idx, orderings = self._routing_spec(frag)
                    holder: dict = {}

                    def sink_factory(types, dicts, _frag=frag, _key=key_idx,
                                     _ord=orderings, _holder=holder, _lp=lp):
                        ex = StreamingExchange(
                            self.mesh, _frag.id, _frag.output_kind, _key,
                            types, dicts, orderings=_ord,
                            chunk_rows=chunk_rows, inflight_bytes=inflight,
                            page_capacity=page_cap, book=book,
                            pool_key=pool_key,
                            # in-flight exchange bytes reserve as the
                            # query's user memory (unified accounting)
                            memory=mem_ctx.user.new_local_memory_context(
                                f"exchange_inflight_f{_frag.id}"))
                        fac = ExchangeSinkOperatorFactory(
                            next(_lp._ids), ex, types)
                        _holder["exchange"] = ex
                        _holder["factory"] = fac
                        return fac

                    ep = lp.plan(root, sink_factory=sink_factory)
                    exchanges[frag.id] = holder["exchange"]
                    sink_facs[frag.id] = holder["factory"]
                    frag_dicts[frag.id] = ep.output_dicts
                # consumer endpoints: attach the producers' streams (created
                # in fragment order, so every referenced exchange exists)
                for fid, slot in ep.remote_slots.items():
                    slot.stream = exchanges[fid]
                planned.append(ep)
                for w in workers:
                    worker_drivers = ep.create_drivers(w)
                    drivers.extend(worker_drivers)
                    if frag_drivers is not None:
                        # per-worker lists: driver ordering is deterministic
                        # per plan, so EXPLAIN ANALYZE's roll-up can line
                        # operator instances up across workers
                        frag_drivers.setdefault(frag.id, []).append(
                            worker_drivers)
                if is_root:
                    root_ep = ep
            # skew-aware routing: pair each INNER join's build-side and
            # probe-side REPARTITION exchanges BEFORE any pump runs (the
            # roles change the compiled routing program for the stream)
            if bool(self.session.get("skew_aware_exchange", True)):
                _wire_skew(planned, exchanges)
            # all drivers exist: producer counts are exact — start the pumps
            for fid, ex in exchanges.items():
                ex.start(sink_facs[fid].created)
            # live progress across ALL fragments' drivers (exec/progress.py;
            # no-op outside a protocol-layer query scope)
            from ..exec import progress as _progress
            from ..exec.explain import driver_stats as _dstats
            from ..runner import _pool_steps

            unregister = _progress.register(lambda: {
                "operators": _dstats(drivers),
                "memory_reserved_bytes": mem_ctx.total_bytes(),
                "pool_steps": _pool_steps(pool_key)})
            try:
                TaskExecutor(
                    int(self.session.get("task_concurrency"))
                ).execute(drivers)
            finally:
                unregister()
            return QueryResult(root_ep.sink.rows(), sub.column_names,
                               root_ep.output_types)
        finally:
            err = sys.exc_info()[1]
            for ex in exchanges.values():
                ex.close(error=err)
            if err is not None:
                for d in drivers:
                    try:
                        d.close()
                    except Exception:  # noqa: BLE001 - teardown best effort
                        pass
            # after every pipeline/exchange tore down: clear this query's
            # reservations from the process-shared pool
            mem_release()

    def _execute_barrier(self, sub: SubPlan, book: ExchangeStatsBook,
                         frag_drivers: Optional[dict] = None) \
            -> QueryResult:
        """The pre-streaming stage-barrier loop, kept as the differential
        oracle: each fragment drains fully, then ONE variable-shape
        collective routes all of its output."""
        # ONE memory pool + query context + task executor for the whole
        # query: every fragment's operators draw on the same budget and the
        # runner threads are reused across stages instead of rebuilt
        mem_ctx, over_target, mem_release = self.local._query_memory()
        executor = TaskExecutor(int(self.session.get("task_concurrency")),
                                persistent=True)
        try:
            return self._run_barrier_stages(sub, executor,
                                            (mem_ctx, over_target),
                                            book, frag_drivers)
        finally:
            executor.close()
            mem_release()

    def _run_barrier_stages(self, sub: SubPlan, executor: TaskExecutor,
                            query_memory, book: ExchangeStatsBook,
                            frag_drivers: Optional[dict] = None) \
            -> QueryResult:
        W = self.mesh.n_workers
        frag_dicts: Dict[int, List[Optional[Dictionary]]] = {}
        routed: Dict[int, List[List[Page]]] = {}  # fid -> per-worker pages
        # one shared-pool fairness slot per QUERY (not per fragment) — the
        # same invariant the streaming path and the cluster tier keep
        pool_key = next_query_key("mesh-q") \
            if bool(self.session.get("shared_pools", True)) else None
        for frag in sub.fragments:
            is_root = frag is sub.root_fragment
            root = self._fragment_root(sub, frag)
            workers = [0] if frag.partitioning == SINGLE_PART else list(range(W))
            # plan ONCE per fragment: every worker shares the factories (and so
            # the jit-compiled kernels); only splits/exchange pages differ
            lp = LocalExecutionPlanner(self.metadata, self.session,
                                       n_workers=W, remote_dicts=frag_dicts,
                                       devices=self.mesh.devices,
                                       pool_key=pool_key)
            lp.attach_memory(*query_memory)
            ep = lp.plan(root)
            for fid, slot in ep.remote_slots.items():
                for w in range(W):
                    slot.set_pages(w, routed[fid][w])
            # all workers' drivers share one executor: worker tasks and their
            # build/probe pipelines time-slice across runner threads
            per_worker_drivers = [ep.create_drivers(w) for w in workers]
            if frag_drivers is not None:
                frag_drivers[frag.id] = per_worker_drivers
            drivers = [d for wd in per_worker_drivers for d in wd]
            executor.execute(drivers)
            if is_root:
                return QueryResult(ep.sink.rows(), sub.column_names,
                                   ep.output_types)
            per_worker = [ep.sink.pages_for(w) for w in range(W)]
            key_idx, orderings = self._routing_spec(frag)
            routed[frag.id] = run_exchange(
                self.mesh, frag.output_kind, key_idx, per_worker,
                ep.output_types, ep.output_dicts,
                page_capacity=int(self.session.get("page_capacity")
                                  or (1 << 14)),
                orderings=orderings, book=book)
            frag_dicts[frag.id] = ep.output_dicts
        raise AssertionError("root fragment must terminate execution")

    # ------------------------------------------------- EXPLAIN ANALYZE

    def _explain_analyze(self, stmt: t.Query) -> QueryResult:
        """Execute over the mesh, then render per-fragment per-operator
        stats ROLLED UP across workers (rows / wall / blocked / peak-mem,
        via exec/explain.py — the same table the local runner prints),
        plus each fragment boundary's exchange chunk/carry counts."""
        import time as _time

        from ..exec.explain import driver_stats, rollup, table

        sub = self.plan_statement(stmt)
        frag_drivers: Dict[int, List[list]] = {}
        t0 = _time.perf_counter()
        result = self._execute_subplan(sub, frag_drivers)
        wall = _time.perf_counter() - t0
        ex = (result.stats or {}).get("exchange", {})
        per_exchange = {e.get("fragment"): e
                        for e in ex.get("per_exchange", [])}
        lines = [f"Query: {wall * 1000:.0f}ms wall, "
                 f"{len(sub.fragments)} fragments, "
                 f"{self.mesh.n_workers} workers, "
                 f"exchange={ex.get('mode', 'none')}", ""]
        for frag in sub.fragments:
            head = f"Fragment {frag.id} [{frag.partitioning}]"
            if frag.output_kind:
                head += f" output={frag.output_kind}"
            per_worker = frag_drivers.get(frag.id, [])
            head += f" workers={len(per_worker)}"
            lines.append(head)
            stats = [s for wd in per_worker for s in driver_stats(wd)]
            lines += table(rollup(stats), indent="  ")
            exch = per_exchange.get(frag.id)
            if exch:
                lines.append(
                    f"  exchange [{exch.get('kind')}]: "
                    f"chunks={exch.get('chunks', 0)} "
                    f"carry_rows={exch.get('carry_rows', 0)} "
                    f"rows_out={exch.get('rows_out', 0)} "
                    f"compiles={exch.get('compiles', 0)} "
                    f"overlap_s={exch.get('overlap_s', 0)}")
            lines.append("")
        return QueryResult([[line] for line in lines], ["Query Plan"],
                           stats=result.stats,
                           trace_path=result.trace_path)


# ---------------------------------------------------------------------------
# skew wiring: pair each INNER join's build/probe exchanges for heavy-hitter
# handling (parallel/streaming_exchange.py SkewCoordinator)
# ---------------------------------------------------------------------------

def _pipeline_members(chain) -> list:
    """Factory chain with fused segments expanded back to their members —
    the join build/probe factories the skew wiring looks for may sit inside
    a FusedSegmentOperatorFactory."""
    from ..ops.fused_segment import FusedSegmentOperatorFactory

    members = []
    for f in chain:
        if isinstance(f, FusedSegmentOperatorFactory):
            members.extend(f.mid_factories)
            if f.terminal_factory is not None:
                members.append(f.terminal_factory)
        else:
            members.append(f)
    return members


def _skew_pair_safe(build_members, probe_members, probe_join,
                    build_src, exchanges) -> bool:
    """Is spraying/replicating this join's hot keys invisible to everything
    else in the consumer fragment? Skew routing breaks the "all rows of key
    k on one partition" invariant that add_exchanges may have RELIED on
    when it elided downstream exchanges (a SINGLE-step aggregation on the
    join key, a second same-key partitioned join) — so the pair only wires
    when the build pipeline is exactly remote-source -> row-local* -> build,
    and everything downstream of the probe join is partition-AGNOSTIC:
    row-local operators, PARTIAL aggregations (re-exchanged by key later),
    TopN/sort/limit (order-based), sinks, and further joins only when their
    build side arrived by BROADCAST (location-independent by construction).
    Anything else keeps plain hash routing — correct, just concentrated."""
    from ..ops.coalesce import CoalesceOperatorFactory
    from ..ops.filter_project import FilterProjectOperatorFactory
    from ..ops.hash_agg import PARTIAL, HashAggregationOperatorFactory
    from ..ops.hash_join import LookupJoinOperatorFactory
    from ..ops.topn import (LimitOperatorFactory, OrderByOperatorFactory,
                            TopNOperatorFactory)
    from ..utils.testing import PageConsumerFactory

    row_local = (FilterProjectOperatorFactory, CoalesceOperatorFactory)
    if any(not isinstance(f, row_local) for f in build_members[:-1]):
        return False
    ji = probe_members.index(probe_join)
    if any(not isinstance(f, row_local) for f in probe_members[:ji]):
        return False
    for f in probe_members[ji + 1:]:
        if isinstance(f, row_local + (TopNOperatorFactory,
                                      OrderByOperatorFactory,
                                      LimitOperatorFactory,
                                      ExchangeSinkOperatorFactory,
                                      PageConsumerFactory)):
            continue
        if isinstance(f, HashAggregationOperatorFactory) and \
                f.step == PARTIAL:
            continue
        if isinstance(f, LookupJoinOperatorFactory):
            bfid = build_src.get(id(f.lookup_factory))
            bex = exchanges.get(bfid) if bfid is not None else None
            if bex is not None and bex.kind == BROADCAST:
                continue
            return False
        return False
    return True


def _wire_skew(planned, exchanges) -> None:
    """Scan every consumer fragment's pipelines for partitioned joins and
    pair the REPARTITION exchange feeding each JoinBuildOperatorFactory
    ("build" side) with the one feeding the matching LookupJoin probe
    ("probe" side) on one SkewCoordinator: both sample their first chunk,
    and a heavy-hitter key splits round-robin on its own side while the
    peer replicates it. INNER joins only — a replicated row would emit
    spurious unmatched rows under LEFT/FULL/semi semantics — and only
    unambiguous 1:1 pairs whose consumer fragment is provably partition-
    agnostic downstream of the join (:func:`_skew_pair_safe`)."""
    from ..ops.hash_join import INNER, JoinBuildOperatorFactory, \
        LookupJoinOperatorFactory
    from .streaming_exchange import SkewCoordinator

    build_src = {}   # id(lookup_factory) -> producer fragment id
    build_info = {}  # id(lookup_factory) -> build pipeline members
    probe_src = {}   # id(lf) -> (fid, join factory, members) | None
    for ep in planned:
        for chain in ep.pipelines:
            fid = getattr(getattr(chain[0], "slot", None),
                          "fragment_id", None)
            if fid is None:
                continue
            members = _pipeline_members(chain[1:])
            if members and isinstance(members[-1], JoinBuildOperatorFactory):
                build_src[id(members[-1].lookup_factory)] = fid
                build_info[id(members[-1].lookup_factory)] = members
            for f in members:
                if isinstance(f, LookupJoinOperatorFactory):
                    key = id(f.lookup_factory)
                    if key in probe_src:
                        probe_src[key] = None  # ambiguous: two probe feeds
                    else:
                        probe_src[key] = (fid, f, members)
    for key, bfid in build_src.items():
        pair = probe_src.get(key)
        if not pair:
            continue
        pfid, join_fac, probe_members = pair
        if join_fac.join_type != INNER or pfid == bfid:
            continue
        bex, pex = exchanges.get(bfid), exchanges.get(pfid)
        if bex is None or pex is None or \
                bex.kind != REPARTITION or pex.kind != REPARTITION or \
                bex._skew is not None or pex._skew is not None:
            continue
        if not _skew_pair_safe(build_info[key], probe_members, join_fac,
                               build_src, exchanges):
            continue
        coord = SkewCoordinator()
        bex.set_skew("build", coord)
        pex.set_skew("probe", coord)


# ---------------------------------------------------------------------------
# the barrier exchange bridge: per-worker page lists -> one collective ->
# per-worker page lists (the oracle data plane; the streaming plane lives in
# parallel/streaming_exchange.py and shares this module's device helpers)
# ---------------------------------------------------------------------------

# shape floor for exchange buffers: below this, padding is free but every
# distinct capacity would compile (and cache) another XLA collective
_MIN_EXCHANGE_CAP = 1 << 9


def _worker_device_columns(pages: List[Page], types: Sequence[Type],
                           book: Optional[ExchangeStatsBook] = None):
    """Concat+widen one worker's pages ON ITS DEVICE -> (datas, nulls, mask,
    live_count). Eager jnp ops follow the pages' committed device, so a worker
    whose pipeline ran on mesh device w compacts on device w."""
    import jax.numpy as jnp

    # host-sourced pages (numpy blocks — VALUES rows, or a regression that
    # re-materialized exchange output host-side) are what the multichip
    # dryrun's device-residency assertion exists to catch: count them
    for p in pages:
        if isinstance(p.mask, np.ndarray) or \
                any(isinstance(b.data, np.ndarray) for b in p.blocks):
            record_exchange_stat("host_uploads", 1, book)

    ncols = len(types)
    masks = [jnp.asarray(p.mask) for p in pages]
    mask = masks[0] if len(masks) == 1 else jnp.concatenate(masks)
    datas, nulls = [], []
    for c in range(ncols):
        dt = np.dtype(types[c].np_dtype)
        parts = [jnp.asarray(p.blocks[c].data).astype(dt) for p in pages]
        datas.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
        nparts = [jnp.asarray(p.blocks[c].nulls)
                  if p.blocks[c].nulls is not None
                  else jnp.zeros(p.capacity, dtype=jnp.bool_) for p in pages]
        nulls.append(nparts[0] if len(nparts) == 1 else jnp.concatenate(nparts))
    # live count stays a DEVICE scalar: the caller batches all workers'
    # counts into one host transfer instead of W serialized syncs
    return datas, nulls, mask, jnp.sum(mask.astype(jnp.int32))


def _exchange_program(mesh, kind: str, key_idx: Optional[Tuple[int, ...]],
                      ncols: int, W: int, L: int, out_cap: int,
                      range_dtype: Optional[str] = None):
    """-> (program, compiled_now). Build + jit the exchange collective ONCE
    per (mesh, kind, keys, shape) signature — repeated exchanges of the same
    shape reuse the compiled XLA program via the global LRU kernel cache
    (the reference reuses its HTTP buffer machinery similarly).
    `compiled_now` feeds the per-query compile counter race-free (a global
    cache-stats diff would misattribute compiles between concurrently
    executing queries).

    `out_cap` is the per-peer receive capacity. For REPARTITION the caller
    sizes it from the measured max (worker, peer) send count — sizing it to L
    (the worst case) would make every downstream page W/occupancy times
    padding, which on an 8-way mesh was a ~10x compute blowup."""
    from ..utils import kernel_cache as kc

    key = ("exchange-barrier", mesh, kind, key_idx, ncols, W, L, out_cap,
           range_dtype)
    return kc.get_or_build(
        key, lambda: _build_exchange_program(mesh, kind, key_idx, ncols, W,
                                             L, out_cap))


def _build_exchange_program(mesh, kind: str,
                            key_idx: Optional[Tuple[int, ...]],
                            ncols: int, W: int, L: int, out_cap: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.hash_join import combined_key
    from .exchange import (broadcast_gather, gather_to_single,
                           range_partition_ids, repartition,
                           repartition_by_pid)
    from .mesh import shard_map

    n_arrays = 2 * ncols

    if kind == MERGE:
        def merge_stage(arrays, mask, range_key, splitters):
            pid = range_partition_ids(range_key, splitters, mask, W)
            out, m, dropped = repartition_by_pid(
                list(arrays) + [range_key], mask, pid, W, out_cap)
            return tuple(out[:-1]), m, dropped.reshape(1)

        smapped = shard_map(
            merge_stage, mesh=mesh,
            in_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                      P(WORKER_AXIS), P(WORKER_AXIS), P()),
            out_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                       P(WORKER_AXIS), P(WORKER_AXIS)))
        return jax.jit(smapped)

    def stage(arrays, mask):
        if kind == REPARTITION:
            keys = [jnp.where(arrays[ncols + i], 0, arrays[i]).astype(jnp.int64)
                    for i in key_idx]
            out, m, dropped = repartition(list(arrays), mask,
                                          combined_key(keys), W, out_cap)
            return tuple(out), m, dropped.reshape(1)
        if kind == BROADCAST:
            out, m = broadcast_gather(list(arrays), mask)
        elif kind == GATHER:
            out, m = gather_to_single(list(arrays), mask)
        else:
            raise AssertionError(kind)
        return tuple(out), m, jnp.zeros(1, dtype=jnp.int32)

    smapped = shard_map(
        stage, mesh=mesh,
        in_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)), P(WORKER_AXIS)),
        out_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                   P(WORKER_AXIS), P(WORKER_AXIS)))
    return jax.jit(smapped)


def run_exchange(mesh: MeshContext, kind: str, key_idx: Optional[List[int]],
                 per_worker_pages: List[List[Page]], types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int = 1 << 14,
                 orderings=None,
                 book: Optional[ExchangeStatsBook] = None) -> List[List[Page]]:
    """Route every worker's output pages to their consumers with ONE shard_map
    collective over the mesh (REPARTITION=all_to_all, BROADCAST=all_gather,
    GATHER=all_gather masked to worker 0).

    DEVICE-RESIDENT end to end: each worker's pages compact on their own
    device, the global sharded array is assembled from those per-device
    shards (jax.make_array_from_single_device_arrays — no host gather), the
    collective runs, and the output shards are handed to the next fragment as
    device pages. The only host->device uploads are zero backfills for
    workers that produced nothing (counted in EXCHANGE_STATS). The reference
    never re-materializes pages host-side mid-query either — its data plane
    streams serialized pages process-to-process (ExchangeClient.java)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    W = mesh.n_workers
    ncols = len(types)
    record_exchange_stat("exchanges", 1, book)

    compacted = [None] * W
    for w, pages in enumerate(per_worker_pages):
        if pages:
            compacted[w] = _worker_device_columns(pages, types, book)
    # ONE batched host transfer for all workers' live counts (device_get on
    # the list issues every d2h together, not W serialized blocking syncs)
    live_devs = [c[3] for c in compacted if c is not None]
    live_np = iter(jax.device_get(live_devs))
    live = [int(next(live_np)) if compacted[w] is not None else 0
            for w in range(W)]
    # bucket L (live rows of the fullest worker) to powers of two — with a
    # floor — so repeated exchanges of similar volume reuse one compiled
    # collective; every distinct (L, out_cap) is a separate XLA program, and
    # distinct-program count is worth bounding (compile time, code memory)
    L = max(1 << (max(max(live), 1) - 1).bit_length(), _MIN_EXCHANGE_CAP)

    compact = _compact_pad_jit()
    shard_datas: List[List] = [None] * W  # per worker: ncols data arrays
    shard_nulls: List[List] = [None] * W
    shard_masks: List = [None] * W
    for w in range(W):
        dev = mesh.devices[w]
        if compacted[w] is None:
            # no output on this worker: cached constant zero shards
            shard_datas[w] = [_zeros_shard(dev, types[c].np_dtype, L, book)
                              for c in range(ncols)]
            shard_nulls[w] = [_zeros_shard(dev, bool, L, book)
                              for _ in range(ncols)]
            shard_masks[w] = _zeros_shard(dev, bool, L, book)
            continue
        datas, nulls, mask, _ = compacted[w]
        out_d, out_n, out_m = compact(tuple(datas), tuple(nulls), mask, L)
        # device_put to the worker's own device is a no-op when the pipeline
        # already ran there; otherwise a direct device-to-device move
        shard_datas[w] = [jax.device_put(a, dev) for a in out_d]
        shard_nulls[w] = [jax.device_put(a, dev) for a in out_n]
        shard_masks[w] = jax.device_put(out_m, dev)

    sharding = NamedSharding(mesh.mesh, P(WORKER_AXIS))

    def assemble(shards):
        return jax.make_array_from_single_device_arrays(
            (W * L,), sharding, shards)

    dev_arrays = [assemble([shard_datas[w][c] for w in range(W)])
                  for c in range(ncols)]
    dev_arrays += [assemble([shard_nulls[w][c] for w in range(W)])
                   for c in range(ncols)]
    dev_mask = assemble([shard_masks[w] for w in range(W)])

    # per-peer receive capacity: worst case (L) for gather/broadcast; for
    # REPARTITION/MERGE measure the true max (worker, peer) send count so
    # output pages are sized to the data, not to the theoretical skew bound
    out_cap = L
    range_keys = splitters = None
    if kind == REPARTITION:
        from ..ops.hash_join import combined_key
        from .exchange import partition_ids

        maxes = []
        for w in range(W):
            if compacted[w] is None:
                continue
            datas, nulls_w, mask, _ = compacted[w]
            keys = [jnp.where(nulls_w[i], 0, datas[i]).astype(jnp.int64)
                    for i in key_idx]
            pid = jnp.where(mask, partition_ids(combined_key(keys), W), W)
            counts = jax.ops.segment_sum(
                jnp.ones_like(pid), pid, num_segments=W + 1)[:W]
            maxes.append(jnp.max(counts))
        max_count = int(max(jax.device_get(maxes))) if maxes else 1
        out_cap = max(1 << (max(max_count, 1) - 1).bit_length(),
                      _MIN_EXCHANGE_CAP)
        out_cap = min(out_cap, L)
    elif kind == MERGE:
        # range routing for distributed ORDER BY: per-worker routing key on
        # each worker's device, splitters from pooled samples (control-plane
        # scalars — the reference samples the same way for bucketed sorts)
        from .exchange import range_partition_ids

        ch, desc, nf = orderings[0]
        range_keys = [None] * W
        samples = []
        for w in range(W):
            key_w = _range_key_for(
                jax.device_put(shard_datas[w][ch], mesh.devices[w]),
                shard_nulls[w][ch], types[ch], dicts[ch], desc, nf)
            range_keys[w] = jax.device_put(key_w, mesh.devices[w])
            lw = live[w]
            if lw:
                stride = max(1, lw // 128)
                samples.append(np.asarray(key_w[:lw:stride][:128]))
        pooled = np.sort(np.concatenate(samples)) if samples else \
            np.zeros(1, dtype=range_keys[0].dtype)
        splitters = np.asarray(
            [pooled[len(pooled) * i // W] for i in range(1, W)],
            dtype=pooled.dtype)
        maxes = []
        for w in range(W):
            if compacted[w] is None:
                continue
            pid = range_partition_ids(range_keys[w],
                                      jax.device_put(splitters,
                                                     mesh.devices[w]),
                                      shard_masks[w], W)
            counts = jax.ops.segment_sum(
                jnp.ones_like(pid), pid, num_segments=W + 1)[:W]
            maxes.append(jnp.max(counts))
        max_count = int(max(jax.device_get(maxes))) if maxes else 1
        out_cap = max(1 << (max(max_count, 1) - 1).bit_length(),
                      _MIN_EXCHANGE_CAP)
        out_cap = min(out_cap, L)

    # jax.sharding.Mesh is hashable and value-equal: safe as the cache key
    program, compiled_now = _exchange_program(
        mesh.mesh, kind, tuple(key_idx) if key_idx is not None else None,
        ncols, W, L, out_cap,
        str(range_keys[0].dtype) if kind == MERGE else None)
    if book is not None and compiled_now:
        book.bump("collective_compiles")
    from .streaming_exchange import COLLECTIVE_DISPATCH_LOCK
    with COLLECTIVE_DISPATCH_LOCK:
        if kind == MERGE:
            g_rangekey = assemble([range_keys[w] for w in range(W)])
            out_arrays, out_mask, dropped = program(
                tuple(dev_arrays), dev_mask, g_rangekey, splitters)
        else:
            out_arrays, out_mask, dropped = program(tuple(dev_arrays),
                                                    dev_mask)
    n_dropped = int(np.asarray(dropped).sum())
    if n_dropped:
        # the send buffers are sized to the fullest worker's live rows, so a
        # drop means a sizing bug upstream — corrupt results must fail loudly
        # (the streaming exchange carries overflow over to the next chunk
        # instead; see parallel/streaming_exchange.py)
        raise RuntimeError(
            f"repartition exchange dropped {n_dropped} rows "
            f"(capacity {L} per peer, {W} workers)")

    # hand each worker its output shard as DEVICE pages (no host round trip):
    # prefix-compact the shard on its device, then slice into STANDARD pow2
    # page capacities — downstream operators then reuse the kernels already
    # compiled for scan pages instead of tracing one variant per shard length
    # (capacity diversity compiles programs; program count is a real cost)
    out_len = out_mask.shape[0] // W
    # one host sync per column to decide null-mask presence (downstream
    # kernels skip null arithmetic entirely for all-non-null columns)
    null_cols = out_arrays[ncols:]
    has_nulls = np.asarray(jnp.stack([jnp.any(a) for a in null_cols])) \
        if ncols else np.zeros(0, dtype=bool)

    def shards_by_worker(arr):
        out = [None] * W
        for sh in arr.addressable_shards:
            start = sh.index[0].start or 0  # W=1: index is slice(None)
            out[start // out_len] = sh.data
        return out

    data_shards = [shards_by_worker(out_arrays[c]) for c in range(ncols)]
    nulls_shards = [shards_by_worker(null_cols[c]) for c in range(ncols)]
    mask_shards = shards_by_worker(out_mask)
    cap = min(max(page_capacity, _MIN_EXCHANGE_CAP), out_len)
    out_compact = []
    for w in range(W):
        out_compact.append(compact(
            tuple(data_shards[c][w] for c in range(ncols)),
            tuple(nulls_shards[c][w] for c in range(ncols)),
            mask_shards[w], out_len))
    out_live = jax.device_get(
        [jnp.sum(m.astype(jnp.int32)) for _, _, m in out_compact])
    routed: List[List[Page]] = []
    for w in range(W):
        out_d, out_n, out_m = out_compact[w]
        live_w = int(out_live[w])
        n_pages = max(1, -(-live_w // cap))
        pages: List[Page] = []
        for off in range(0, n_pages * cap, cap):
            blocks = []
            for c in range(ncols):
                nm = out_n[c][off:off + cap] if has_nulls[c] else None
                blocks.append(Block(types[c], out_d[c][off:off + cap],
                                    nm, dicts[c]))
            pages.append(Page(tuple(blocks), out_m[off:off + cap]))
        routed.append(pages if live_w else [])
    return routed
