"""DistributedQueryRunner: planner-driven SQL execution over the device mesh.

The multi-chip analogue of presto-tests DistributedQueryRunner.java:77 — but
where the reference boots N HTTP servers, here "workers" are mesh devices:

  parse -> analyze/plan -> optimize -> AddExchanges -> PlanFragmenter
  -> per fragment (bottom-up): drive each worker's operator pipeline over its
     shard (worker-scoped splits or exchange-output pages)
  -> route the fragment's output through ONE shard_map collective over the ICI
     mesh (all_to_all repartition / all_gather broadcast / gather-to-root)

The data plane between fragments is the real XLA collective — the engine's
answer to the reference's HTTP+LZ4 shuffle (PartitionedOutputOperator.java:380,
ExchangeClient.java). Within a fragment, EVERY worker's drivers are enqueued
on one shared TaskExecutor and time-slice across its runner threads (so 8
virtual workers never host-serialize; build/probe pipelines of different
workers overlap); the collective itself always runs as one SPMD program over
all workers.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..block import Block, Dictionary, Page
from ..exec.local_planner import LocalExecutionPlanner
from ..exec.task_executor import TaskExecutor
from ..metadata import CatalogManager, Session
from ..runner import LocalQueryRunner, QueryResult
from ..sql import tree as t
from ..sql.planner.add_exchanges import add_exchanges
from ..sql.planner.fragmenter import (Fragment, SINGLE_PART, SubPlan,
                                      fragment_plan)
from ..sql.planner.optimizer import optimize
from ..sql.planner.plan import (BROADCAST, GATHER, MERGE, OutputNode,
                                REPARTITION, RemoteSourceNode, plan_to_text)
from ..sql.planner.planner import LogicalPlanner
from ..types import Type
from .mesh import MeshContext, WORKER_AXIS

# (pages for each worker, shared column dictionaries)
RemoteInput = Tuple[List[Page], List[Optional[Dictionary]]]


class DistributedQueryRunner:
    """In-process multi-worker engine over a jax.sharding.Mesh."""

    def __init__(self, mesh: Optional[MeshContext] = None,
                 session: Optional[Session] = None,
                 catalogs: Optional[CatalogManager] = None,
                 page_capacity: int = 1 << 14):
        self.local = LocalQueryRunner(session, catalogs, page_capacity)
        self.mesh = mesh if mesh is not None else MeshContext()

    @property
    def metadata(self):
        return self.local.metadata

    @property
    def session(self):
        return self.local.session

    # ------------------------------------------------------------------ api

    def plan_sql(self, sql: str) -> SubPlan:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            raise ValueError(f"cannot plan {type(stmt).__name__}")
        return self.plan_statement(stmt)

    def plan_statement(self, stmt: t.Query) -> SubPlan:
        planner = LogicalPlanner(self.metadata, self.session)
        plan = planner.plan(stmt)
        plan = optimize(plan, self.metadata, self.session)
        plan = add_exchanges(plan, planner.symbols, self.metadata, self.session,
                             n_workers=self.mesh.n_workers)
        return fragment_plan(plan)

    def explain(self, sql: str) -> str:
        sub = self.plan_sql(sql)
        parts = []
        for f in sub.fragments:
            head = f"Fragment {f.id} [{f.partitioning}]"
            if f.output_kind:
                keys = f" keys={[k.name for k in f.output_keys]}" \
                    if f.output_keys else ""
                head += f" output={f.output_kind}{keys}"
            parts.append(head + "\n" + plan_to_text(f.root, indent=1))
        return "\n".join(parts)

    def execute(self, sql: str) -> QueryResult:
        stmt = self.local.parser.parse(sql)
        if not isinstance(stmt, t.Query):
            return self.local.execute(sql)  # EXPLAIN/SHOW et al stay local
        sub = self.plan_statement(stmt)
        return self._execute_subplan(sub)

    # ------------------------------------------------------------ execution

    def _execute_subplan(self, sub: SubPlan) -> QueryResult:
        W = self.mesh.n_workers
        frag_dicts: Dict[int, List[Optional[Dictionary]]] = {}
        routed: Dict[int, List[List[Page]]] = {}  # fid -> per-worker pages
        # ONE memory pool + query context for the whole query: every
        # fragment's operators draw on the same budget
        query_memory = self.local._query_memory()
        for frag in sub.fragments:
            is_root = frag is sub.root_fragment
            if is_root:
                root = OutputNode(frag.root, sub.column_names,
                                  sub.output_symbols)
            else:
                syms = frag.root.outputs()
                root = OutputNode(frag.root, [s.name for s in syms], syms)
            workers = [0] if frag.partitioning == SINGLE_PART else list(range(W))
            # plan ONCE per fragment: every worker shares the factories (and so
            # the jit-compiled kernels); only splits/exchange pages differ
            lp = LocalExecutionPlanner(self.metadata, self.session,
                                       n_workers=W, remote_dicts=frag_dicts,
                                       devices=self.mesh.devices)
            lp.attach_memory(*query_memory)
            ep = lp.plan(root)
            for fid, slot in ep.remote_slots.items():
                for w in range(W):
                    slot.set_pages(w, routed[fid][w])
            # all workers' drivers share one executor: worker tasks and their
            # build/probe pipelines time-slice across runner threads
            drivers = [d for w in workers for d in ep.create_drivers(w)]
            TaskExecutor(
                int(self.session.get("task_concurrency"))).execute(drivers)
            if is_root:
                return QueryResult(ep.sink.rows(), sub.column_names,
                                   ep.output_types)
            per_worker = [ep.sink.pages_for(w) for w in range(W)]
            key_idx = None
            orderings = None
            names = [s.name for s in frag.root.outputs()]
            if frag.output_kind == REPARTITION:
                key_idx = [names.index(k.name) for k in frag.output_keys]
            elif frag.output_kind == MERGE:
                orderings = tuple(
                    (names.index(o.symbol.name), o.descending, o.nulls_first)
                    for o in frag.output_orderings)
            routed[frag.id] = run_exchange(
                self.mesh, frag.output_kind, key_idx, per_worker,
                ep.output_types, ep.output_dicts,
                page_capacity=int(self.session.get("page_capacity")
                                  or (1 << 14)),
                orderings=orderings)
            frag_dicts[frag.id] = ep.output_dicts
        raise AssertionError("root fragment must terminate execution")


# ---------------------------------------------------------------------------
# the exchange bridge: per-worker page lists -> one collective -> per-worker
# page lists (the engine's entire shuffle data plane)
# ---------------------------------------------------------------------------

# observability for the multichip dryrun's "no host copies between fragments"
# check: host_uploads counts PAGE DATA crossing host->device in the exchange
# (must stay zero — fragment chains are device-resident); zero_backfills
# counts constant all-zero shards for workers that produced nothing, which
# are cached per (device, dtype, length) and uploaded at most once ever
EXCHANGE_STATS = {"host_uploads": 0, "zero_backfills": 0, "exchanges": 0}

_ZEROS_CACHE: dict = {}


def _zeros_shard(dev, dtype, L: int):
    """Cached all-zero device array (immutable, safely shared as a read-only
    collective input)."""
    import jax

    key = (dev, np.dtype(dtype).str, L)
    z = _ZEROS_CACHE.get(key)
    if z is None:
        EXCHANGE_STATS["zero_backfills"] += 1
        z = _ZEROS_CACHE[key] = jax.device_put(np.zeros(L, dtype=dtype), dev)
    return z

# shape floor for exchange buffers: below this, padding is free but every
# distinct capacity would compile (and cache) another XLA collective
_MIN_EXCHANGE_CAP = 1 << 9


@functools.lru_cache(maxsize=1)
def _compact_pad_jit():
    """(R,) columns + mask -> (L,) prefix-compacted columns + mask, on the
    inputs' device. The reference materializes selected positions the same
    way before serializing (PartitionedOutputOperator.java:380); here it is
    one fused scatter and the result never leaves the worker's chip."""
    import jax
    import jax.numpy as jnp

    def fn(datas, nulls, mask, L):
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        tgt = jnp.where(mask, pos, L)  # dead rows scatter out of bounds
        out_mask = jnp.zeros(L, dtype=jnp.bool_).at[tgt].set(mask, mode="drop")
        out_d = tuple(jnp.zeros(L, dtype=a.dtype).at[tgt].set(a, mode="drop")
                      for a in datas)
        out_n = tuple(jnp.zeros(L, dtype=jnp.bool_).at[tgt].set(n, mode="drop")
                      for n in nulls)
        return out_d, out_n, out_mask
    return jax.jit(fn, static_argnames=("L",))


def _worker_device_columns(pages: List[Page], types: Sequence[Type]):
    """Concat+widen one worker's pages ON ITS DEVICE -> (datas, nulls, mask,
    live_count). Eager jnp ops follow the pages' committed device, so a worker
    whose pipeline ran on mesh device w compacts on device w."""
    import jax.numpy as jnp

    # host-sourced pages (numpy blocks — VALUES rows, or a regression that
    # re-materialized exchange output host-side) are what the multichip
    # dryrun's device-residency assertion exists to catch: count them
    for p in pages:
        if isinstance(p.mask, np.ndarray) or \
                any(isinstance(b.data, np.ndarray) for b in p.blocks):
            EXCHANGE_STATS["host_uploads"] += 1

    ncols = len(types)
    masks = [jnp.asarray(p.mask) for p in pages]
    mask = masks[0] if len(masks) == 1 else jnp.concatenate(masks)
    datas, nulls = [], []
    for c in range(ncols):
        dt = np.dtype(types[c].np_dtype)
        parts = [jnp.asarray(p.blocks[c].data).astype(dt) for p in pages]
        datas.append(parts[0] if len(parts) == 1 else jnp.concatenate(parts))
        nparts = [jnp.asarray(p.blocks[c].nulls)
                  if p.blocks[c].nulls is not None
                  else jnp.zeros(p.capacity, dtype=jnp.bool_) for p in pages]
        nulls.append(nparts[0] if len(nparts) == 1 else jnp.concatenate(nparts))
    # live count stays a DEVICE scalar: the caller batches all workers'
    # counts into one host transfer instead of W serialized syncs
    return datas, nulls, mask, jnp.sum(mask.astype(jnp.int32))


def _range_key_for(data, nulls, type_, dictionary, descending: bool,
                   nulls_first: bool):
    """One worker's MERGE routing key (device, eager): the primary ORDER BY
    column mapped to a monotone int64/float64 code — mirrors the local sort's
    transform (ops/topn.py _sort_key_arrays) so range routing and the
    per-worker sort can never disagree on order."""
    import jax.numpy as jnp

    from ..types import is_string

    x = data
    if is_string(type_) and dictionary is not None:
        if hasattr(dictionary, "values"):
            x = jnp.asarray(dictionary.sort_keys())[x]
        elif not getattr(dictionary, "monotonic", False):
            raise NotImplementedError(
                f"distributed ORDER BY over non-monotonic virtual "
                f"dictionary {dictionary!r}")
    if jnp.issubdtype(x.dtype, jnp.floating):
        key = x.astype(jnp.float64)
        lo, hi = -jnp.inf, jnp.inf
    else:
        key = x.astype(jnp.int64)
        info = np.iinfo(np.int64)
        lo, hi = info.min + 1, info.max
    if descending:
        key = -key
    if nulls is not None:
        key = jnp.where(nulls, lo if nulls_first else hi, key)
    return key


@functools.lru_cache(maxsize=256)
def _exchange_program(mesh, kind: str, key_idx: Optional[Tuple[int, ...]],
                      ncols: int, W: int, L: int, out_cap: int,
                      range_dtype: Optional[str] = None):
    """Build + jit the exchange collective ONCE per (mesh, kind, keys, shape)
    signature — repeated exchanges of the same shape reuse the compiled XLA
    program (the reference reuses its HTTP buffer machinery similarly).

    `out_cap` is the per-peer receive capacity. For REPARTITION the caller
    sizes it from the measured max (worker, peer) send count — sizing it to L
    (the worst case) would make every downstream page W/occupancy times
    padding, which on an 8-way mesh was a ~10x compute blowup."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    from ..ops.hash_join import combined_key
    from .exchange import (broadcast_gather, gather_to_single,
                           range_partition_ids, repartition,
                           repartition_by_pid)

    n_arrays = 2 * ncols

    if kind == MERGE:
        def merge_stage(arrays, mask, range_key, splitters):
            pid = range_partition_ids(range_key, splitters, mask, W)
            out, m, dropped = repartition_by_pid(
                list(arrays) + [range_key], mask, pid, W, out_cap)
            return tuple(out[:-1]), m, dropped.reshape(1)

        smapped = shard_map(
            merge_stage, mesh=mesh,
            in_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                      P(WORKER_AXIS), P(WORKER_AXIS), P()),
            out_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                       P(WORKER_AXIS), P(WORKER_AXIS)))
        return jax.jit(smapped)

    def stage(arrays, mask):
        if kind == REPARTITION:
            keys = [jnp.where(arrays[ncols + i], 0, arrays[i]).astype(jnp.int64)
                    for i in key_idx]
            out, m, dropped = repartition(list(arrays), mask,
                                          combined_key(keys), W, out_cap)
            return tuple(out), m, dropped.reshape(1)
        if kind == BROADCAST:
            out, m = broadcast_gather(list(arrays), mask)
        elif kind == GATHER:
            out, m = gather_to_single(list(arrays), mask)
        else:
            raise AssertionError(kind)
        return tuple(out), m, jnp.zeros(1, dtype=jnp.int32)

    smapped = shard_map(
        stage, mesh=mesh,
        in_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)), P(WORKER_AXIS)),
        out_specs=(tuple(P(WORKER_AXIS) for _ in range(n_arrays)),
                   P(WORKER_AXIS), P(WORKER_AXIS)))
    return jax.jit(smapped)


def run_exchange(mesh: MeshContext, kind: str, key_idx: Optional[List[int]],
                 per_worker_pages: List[List[Page]], types: Sequence[Type],
                 dicts: Sequence[Optional[Dictionary]],
                 page_capacity: int = 1 << 14,
                 orderings=None) -> List[List[Page]]:
    """Route every worker's output pages to their consumers with ONE shard_map
    collective over the mesh (REPARTITION=all_to_all, BROADCAST=all_gather,
    GATHER=all_gather masked to worker 0).

    DEVICE-RESIDENT end to end: each worker's pages compact on their own
    device, the global sharded array is assembled from those per-device
    shards (jax.make_array_from_single_device_arrays — no host gather), the
    collective runs, and the output shards are handed to the next fragment as
    device pages. The only host->device uploads are zero backfills for
    workers that produced nothing (counted in EXCHANGE_STATS). The reference
    never re-materializes pages host-side mid-query either — its data plane
    streams serialized pages process-to-process (ExchangeClient.java)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    W = mesh.n_workers
    ncols = len(types)
    EXCHANGE_STATS["exchanges"] += 1

    compacted = [None] * W
    for w, pages in enumerate(per_worker_pages):
        if pages:
            compacted[w] = _worker_device_columns(pages, types)
    # ONE batched host transfer for all workers' live counts (device_get on
    # the list issues every d2h together, not W serialized blocking syncs)
    live_devs = [c[3] for c in compacted if c is not None]
    live_np = iter(jax.device_get(live_devs))
    live = [int(next(live_np)) if compacted[w] is not None else 0
            for w in range(W)]
    # bucket L (live rows of the fullest worker) to powers of two — with a
    # floor — so repeated exchanges of similar volume reuse one compiled
    # collective; every distinct (L, out_cap) is a separate XLA program, and
    # distinct-program count is worth bounding (compile time, code memory)
    L = max(1 << (max(max(live), 1) - 1).bit_length(), _MIN_EXCHANGE_CAP)

    compact = _compact_pad_jit()
    shard_datas: List[List] = [None] * W  # per worker: ncols data arrays
    shard_nulls: List[List] = [None] * W
    shard_masks: List = [None] * W
    for w in range(W):
        dev = mesh.devices[w]
        if compacted[w] is None:
            # no output on this worker: cached constant zero shards
            shard_datas[w] = [_zeros_shard(dev, types[c].np_dtype, L)
                              for c in range(ncols)]
            shard_nulls[w] = [_zeros_shard(dev, bool, L)
                              for _ in range(ncols)]
            shard_masks[w] = _zeros_shard(dev, bool, L)
            continue
        datas, nulls, mask, _ = compacted[w]
        out_d, out_n, out_m = compact(tuple(datas), tuple(nulls), mask, L)
        # device_put to the worker's own device is a no-op when the pipeline
        # already ran there; otherwise a direct device-to-device move
        shard_datas[w] = [jax.device_put(a, dev) for a in out_d]
        shard_nulls[w] = [jax.device_put(a, dev) for a in out_n]
        shard_masks[w] = jax.device_put(out_m, dev)

    sharding = NamedSharding(mesh.mesh, P(WORKER_AXIS))

    def assemble(shards):
        return jax.make_array_from_single_device_arrays(
            (W * L,), sharding, shards)

    dev_arrays = [assemble([shard_datas[w][c] for w in range(W)])
                  for c in range(ncols)]
    dev_arrays += [assemble([shard_nulls[w][c] for w in range(W)])
                   for c in range(ncols)]
    dev_mask = assemble([shard_masks[w] for w in range(W)])

    # per-peer receive capacity: worst case (L) for gather/broadcast; for
    # REPARTITION/MERGE measure the true max (worker, peer) send count so
    # output pages are sized to the data, not to the theoretical skew bound
    out_cap = L
    range_keys = splitters = None
    if kind == REPARTITION:
        from ..ops.hash_join import combined_key
        from .exchange import partition_ids

        maxes = []
        for w in range(W):
            if compacted[w] is None:
                continue
            datas, nulls_w, mask, _ = compacted[w]
            keys = [jnp.where(nulls_w[i], 0, datas[i]).astype(jnp.int64)
                    for i in key_idx]
            pid = jnp.where(mask, partition_ids(combined_key(keys), W), W)
            counts = jax.ops.segment_sum(
                jnp.ones_like(pid), pid, num_segments=W + 1)[:W]
            maxes.append(jnp.max(counts))
        max_count = int(max(jax.device_get(maxes))) if maxes else 1
        out_cap = max(1 << (max(max_count, 1) - 1).bit_length(),
                      _MIN_EXCHANGE_CAP)
        out_cap = min(out_cap, L)
    elif kind == MERGE:
        # range routing for distributed ORDER BY: per-worker routing key on
        # each worker's device, splitters from pooled samples (control-plane
        # scalars — the reference samples the same way for bucketed sorts)
        from .exchange import range_partition_ids

        ch, desc, nf = orderings[0]
        range_keys = [None] * W
        samples = []
        for w in range(W):
            key_w = _range_key_for(
                jax.device_put(shard_datas[w][ch], mesh.devices[w]),
                shard_nulls[w][ch], types[ch], dicts[ch], desc, nf)
            range_keys[w] = jax.device_put(key_w, mesh.devices[w])
            lw = live[w]
            if lw:
                stride = max(1, lw // 128)
                samples.append(np.asarray(key_w[:lw:stride][:128]))
        pooled = np.sort(np.concatenate(samples)) if samples else \
            np.zeros(1, dtype=range_keys[0].dtype)
        splitters = np.asarray(
            [pooled[len(pooled) * i // W] for i in range(1, W)],
            dtype=pooled.dtype)
        maxes = []
        for w in range(W):
            if compacted[w] is None:
                continue
            pid = range_partition_ids(range_keys[w],
                                      jax.device_put(splitters,
                                                     mesh.devices[w]),
                                      shard_masks[w], W)
            counts = jax.ops.segment_sum(
                jnp.ones_like(pid), pid, num_segments=W + 1)[:W]
            maxes.append(jnp.max(counts))
        max_count = int(max(jax.device_get(maxes))) if maxes else 1
        out_cap = max(1 << (max(max_count, 1) - 1).bit_length(),
                      _MIN_EXCHANGE_CAP)
        out_cap = min(out_cap, L)

    # jax.sharding.Mesh is hashable and value-equal: safe as the cache key
    program = _exchange_program(
        mesh.mesh, kind, tuple(key_idx) if key_idx is not None else None,
        ncols, W, L, out_cap,
        str(range_keys[0].dtype) if kind == MERGE else None)
    if kind == MERGE:
        g_rangekey = assemble([range_keys[w] for w in range(W)])
        out_arrays, out_mask, dropped = program(
            tuple(dev_arrays), dev_mask, g_rangekey, splitters)
    else:
        out_arrays, out_mask, dropped = program(tuple(dev_arrays), dev_mask)
    n_dropped = int(np.asarray(dropped).sum())
    if n_dropped:
        # the send buffers are sized to the fullest worker's live rows, so a
        # drop means a sizing bug upstream — corrupt results must fail loudly
        # (the reference's OutputBuffer applies backpressure instead; see
        # parallel/exchange.py repartition docstring)
        raise RuntimeError(
            f"repartition exchange dropped {n_dropped} rows "
            f"(capacity {L} per peer, {W} workers)")

    # hand each worker its output shard as DEVICE pages (no host round trip):
    # prefix-compact the shard on its device, then slice into STANDARD pow2
    # page capacities — downstream operators then reuse the kernels already
    # compiled for scan pages instead of tracing one variant per shard length
    # (capacity diversity compiles programs; program count is a real cost)
    out_len = out_mask.shape[0] // W
    # one host sync per column to decide null-mask presence (downstream
    # kernels skip null arithmetic entirely for all-non-null columns)
    null_cols = out_arrays[ncols:]
    has_nulls = np.asarray(jnp.stack([jnp.any(a) for a in null_cols])) \
        if ncols else np.zeros(0, dtype=bool)

    def shards_by_worker(arr):
        out = [None] * W
        for sh in arr.addressable_shards:
            start = sh.index[0].start or 0  # W=1: index is slice(None)
            out[start // out_len] = sh.data
        return out

    data_shards = [shards_by_worker(out_arrays[c]) for c in range(ncols)]
    nulls_shards = [shards_by_worker(null_cols[c]) for c in range(ncols)]
    mask_shards = shards_by_worker(out_mask)
    cap = min(max(page_capacity, _MIN_EXCHANGE_CAP), out_len)
    out_compact = []
    for w in range(W):
        out_compact.append(compact(
            tuple(data_shards[c][w] for c in range(ncols)),
            tuple(nulls_shards[c][w] for c in range(ncols)),
            mask_shards[w], out_len))
    out_live = jax.device_get(
        [jnp.sum(m.astype(jnp.int32)) for _, _, m in out_compact])
    routed: List[List[Page]] = []
    for w in range(W):
        out_d, out_n, out_m = out_compact[w]
        live_w = int(out_live[w])
        n_pages = max(1, -(-live_w // cap))
        pages: List[Page] = []
        for off in range(0, n_pages * cap, cap):
            blocks = []
            for c in range(ncols):
                nm = out_n[c][off:off + cap] if has_nulls[c] else None
                blocks.append(Block(types[c], out_d[c][off:off + cap],
                                    nm, dicts[c]))
            pages.append(Page(tuple(blocks), out_m[off:off + cap]))
        routed.append(pages if live_w else [])
    return routed
